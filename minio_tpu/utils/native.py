"""ctypes loader for the native C++ GF(2^8) codec (native/csrc/gf_cpu.cc).

Builds the shared library on first use (g++ -O3 -mavx2) and caches it under
native/build/.  This is the CPU fallback erasure backend - the counterpart
of klauspost/reedsolomon's role in the reference - selected when no TPU is
present or via MINIO_ERASURE_BACKEND=cpu (BASELINE.json north-star seam).

The built artifact is fingerprinted by a hash of the source file plus the
compiler flags (``libgf_cpu-<hash>.so``): editing csrc or changing flags
yields a different path and therefore a rebuild, so a stale library body
can never be silently loaded (an mtime check misses checkouts and clock
skew, and the old ``AttributeError`` guard only caught *missing* symbols,
not stale ones).

The hot entry points are batch-native: ``encode_and_hash_cpu`` runs the
fused single-pass encode+digest kernel over a whole (B, k, L) batch in ONE
C call (stripe-parallel inside; ctypes drops the GIL for the duration), and
``reconstruct_batch_cpu`` / ``reconstruct_and_verify_cpu`` are the decode
twins.  The per-stripe ``gf_matmul_cpu`` remains for tests and the
``--codec-micro`` split baseline.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_ROOT, "native", "csrc", "gf_cpu.cc")
_BUILD_DIR = os.path.join(_ROOT, "native", "build")

_CFLAGS = ["-O3", "-march=native", "-shared", "-fPIC", "-pthread"]

# ASan+UBSan build variant (MINIO_TPU_SANITIZE=1): undefined behaviour
# is fatal (-fno-sanitize-recover), frames are kept for readable
# reports.  -O1 instead of -O3: redzone checks dominate anyway and the
# sanitized library exists for the slow test sweep, not for speed.
_SAN_CFLAGS = [
    "-O1",
    "-g",
    "-fno-omit-frame-pointer",
    "-fsanitize=address,undefined",
    "-fno-sanitize-recover=all",
]

_lock = threading.Lock()
_libs: "dict[str, ctypes.CDLL]" = {}


def _variant() -> str:
    """"" for the production build, "san" under MINIO_TPU_SANITIZE=1."""
    return "san" if os.environ.get("MINIO_TPU_SANITIZE") == "1" else ""


def _flags(variant: str = "") -> "list[str]":
    if variant == "san":
        return [f for f in _CFLAGS if f != "-O3"] + _SAN_CFLAGS
    return list(_CFLAGS)


def _fingerprint(variant: str = "") -> str:
    """Hash of the source body + compiler flags: the .so identity."""
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    h.update(b"\x00" + " ".join(_flags(variant)).encode())
    return h.hexdigest()[:16]


def _so_path(variant: str = "") -> str:
    suffix = f"-{variant}" if variant else ""
    return os.path.join(
        _BUILD_DIR, f"libgf_cpu-{_fingerprint(variant)}{suffix}.so"
    )


def _build(variant: str = "") -> str:
    so = _so_path(variant)
    if os.path.exists(so):
        return so
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = so + f".tmp.{os.getpid()}"
    cmd = ["g++", *_flags(variant), "-o", tmp, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)
    # retire other fingerprints OF THE SAME VARIANT (including the
    # legacy unfingerprinted libgf_cpu.so) so the build dir doesn't
    # accrete one .so per edit; the sanitized and production artifacts
    # coexist - pruning across variants would force a rebuild on every
    # alternation between the test sweep and normal runs
    for name in os.listdir(_BUILD_DIR):
        if (
            name.startswith("libgf_cpu")
            and name.endswith(".so")
            and name.endswith("-san.so") == (variant == "san")
            and os.path.join(_BUILD_DIR, name) != so
        ):
            try:
                os.remove(os.path.join(_BUILD_DIR, name))
            except OSError:
                pass  # another process may hold/clean it concurrently
    return so


def default_threads() -> int:
    """Stripe-parallel worker count for the batch entry points.

    ``MINIO_TPU_NATIVE_THREADS`` overrides; defaults to the host's core
    count.  On a 1-core host this is 1 and the native kernels run
    strictly inline (no thread spawn).
    """
    try:
        v = int(os.environ.get("MINIO_TPU_NATIVE_THREADS") or 0)
    except ValueError:
        v = 0
    if v > 0:
        return v
    return os.cpu_count() or 1


def lib() -> ctypes.CDLL:
    variant = _variant()
    with _lock:
        if variant not in _libs:
            l = ctypes.CDLL(_build(variant))
            l.gf_matmul.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_size_t,
            ]
            l.gf_matmul.restype = None
            l.gf_mul_acc.argtypes = [
                ctypes.c_uint8, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_size_t,
            ]
            l.gf_mul_acc.restype = None
            l.gf_has_avx2.restype = ctypes.c_int
            # fingerprinted paths make a stale body unreachable, but a
            # hand-copied prebuilt .so could still predate a symbol:
            # its absence must only disable that entry point, never
            # break the ones that DO exist
            if hasattr(l, "phash256_rows"):
                l.phash256_rows.argtypes = [
                    ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
                    ctypes.c_uint64, ctypes.c_void_p,
                ]
                l.phash256_rows.restype = None
            if hasattr(l, "encode_and_hash"):
                l.encode_and_hash.argtypes = [
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_size_t, ctypes.c_void_p, ctypes.c_char_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
                ]
                l.encode_and_hash.restype = None
            if hasattr(l, "reconstruct_batch"):
                l.reconstruct_batch.argtypes = [
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_size_t, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
                ]
                l.reconstruct_batch.restype = None
            if hasattr(l, "reconstruct_and_verify"):
                l.reconstruct_and_verify.argtypes = [
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_size_t, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
                ]
                l.reconstruct_and_verify.restype = None
            _libs[variant] = l
    return _libs[variant]


def _ptr_array(arrs: list[np.ndarray]) -> "ctypes.Array":
    ptrs = (ctypes.c_void_p * len(arrs))()
    for i, a in enumerate(arrs):
        assert a.dtype == np.uint8 and a.flags.c_contiguous
        ptrs[i] = a.ctypes.data_as(ctypes.c_void_p)
    return ptrs


def gf_matmul_cpu(matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """out = matrix (o, s) GF-matmul shards (s, len) -> (o, len), native."""
    o, s = matrix.shape
    assert shards.shape[0] == s
    length = shards.shape[1]
    out = np.zeros((o, length), dtype=np.uint8)
    in_rows = [np.ascontiguousarray(shards[i]) for i in range(s)]
    out_rows = [out[i] for i in range(o)]
    lib().gf_matmul(
        o, s, np.ascontiguousarray(matrix, dtype=np.uint8).tobytes(),
        _ptr_array(in_rows), _ptr_array(out_rows), length,
    )
    return out


def gf_mul_acc_cpu(
    coef: int, src: np.ndarray, dst: np.ndarray
) -> np.ndarray:
    """dst ^= coef * src in GF(2^8), native single mul-acc (tests)."""
    src = np.ascontiguousarray(src, dtype=np.uint8)
    dst = np.ascontiguousarray(dst, dtype=np.uint8)
    assert src.shape == dst.shape
    lib().gf_mul_acc(
        coef,
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        src.shape[0],
    )
    return dst


def encode_cpu(data: np.ndarray, parity_shards: int) -> np.ndarray:
    """Native-CPU RS encode: (k, len) -> (m, len)."""
    from ..ops import gf

    return gf_matmul_cpu(gf.parity_matrix(data.shape[0], parity_shards), data)


def encode_and_hash_cpu(
    data: np.ndarray, parity_shards: int, nthreads: "int | None" = None
) -> "tuple[np.ndarray, np.ndarray]":
    """Fused single-pass batch encode+digest: ONE native call per batch.

    data: (B, k, L) uint8, L a multiple of 32.  Returns
    (parity (B, m, L) uint8, digests (B, k+m, 8) uint32, data rows
    first) - bit-identical to the split gf_matmul + phash256_rows path
    and to the numpy/jax twins, but each byte is touched once while
    L1/L2-hot instead of three times through DRAM.
    """
    from ..ops import gf

    data = np.ascontiguousarray(data, dtype=np.uint8)
    B, k, L = data.shape
    m = parity_shards
    if L % 32:
        raise ValueError(f"shard length {L} must be a multiple of 32")
    parity = np.empty((B, m, L), dtype=np.uint8)
    digests = np.empty((B, k + m, 8), dtype=np.uint32)
    matrix = np.ascontiguousarray(
        gf.parity_matrix(k, m), dtype=np.uint8
    ).tobytes() if m else b""
    lib().encode_and_hash(
        B, k, m, L,
        data.ctypes.data_as(ctypes.c_void_p),
        matrix,
        parity.ctypes.data_as(ctypes.c_void_p),
        digests.ctypes.data_as(ctypes.c_void_p),
        nthreads if nthreads is not None else default_threads(),
    )
    return parity, digests


def _survivors(present: np.ndarray, k: int) -> "tuple[np.ndarray, tuple]":
    idx = tuple(int(i) for i in np.nonzero(present)[0])
    if len(idx) < k:
        raise ValueError(f"need {k} shards to reconstruct, have {len(idx)}")
    return np.asarray(idx[:k], dtype=np.int32), idx


def reconstruct_batch_cpu(
    shards: np.ndarray,
    present: np.ndarray,
    data_shards: int,
    parity_shards: int,
    nthreads: "int | None" = None,
) -> np.ndarray:
    """Batched native reconstruct: (B, n, L) + mask -> (B, k, L), one call."""
    from ..ops import gf

    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    B, n, L = shards.shape
    k = data_shards
    surv, idx = _survivors(np.asarray(present, dtype=bool), k)
    rm = gf.reconstruction_matrix(k, parity_shards, idx)
    out = np.empty((B, k, L), dtype=np.uint8)
    lib().reconstruct_batch(
        B, n, k, L,
        shards.ctypes.data_as(ctypes.c_void_p),
        surv.ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(rm, dtype=np.uint8).tobytes(),
        out.ctypes.data_as(ctypes.c_void_p),
        nthreads if nthreads is not None else default_threads(),
    )
    return out


def reconstruct_and_verify_cpu(
    shards: np.ndarray,
    digests: np.ndarray,
    present: np.ndarray,
    data_shards: int,
    parity_shards: int,
    nthreads: "int | None" = None,
) -> "tuple[np.ndarray, np.ndarray]":
    """Fused GET-side pass: verify digests of the present shards AND
    decode the data rows from the first k of them, one memory pass.

    Returns (data (B, k, L) uint8, ok (B, n) bool).  ``data`` is valid
    for a stripe only where every chosen survivor verified; the caller
    re-picks survivors from ``ok`` on the rare bitrot hit.
    """
    from ..ops import gf

    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    B, n, L = shards.shape
    k = data_shards
    if L % 32:
        raise ValueError(f"shard length {L} must be a multiple of 32")
    pres = np.ascontiguousarray(
        np.asarray(present, dtype=bool), dtype=np.uint8
    )
    surv, idx = _survivors(pres.astype(bool), k)
    rm = gf.reconstruction_matrix(k, parity_shards, idx)
    ok = np.empty((B, n), dtype=np.uint8)
    out = np.empty((B, k, L), dtype=np.uint8)
    lib().reconstruct_and_verify(
        B, n, k, L,
        shards.ctypes.data_as(ctypes.c_void_p),
        surv.ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(rm, dtype=np.uint8).tobytes(),
        digests.ctypes.data_as(ctypes.c_void_p),
        pres.ctypes.data_as(ctypes.c_void_p),
        ok.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        nthreads if nthreads is not None else default_threads(),
    )
    return out, ok.astype(bool)


def reconstruct_cpu(
    shards: np.ndarray,
    present: np.ndarray,
    data_shards: int,
    parity_shards: int,
) -> np.ndarray:
    """Native-CPU RS reconstruct of the data rows: -> (k, len)."""
    from ..ops import gf

    present = np.asarray(present, dtype=bool)
    idx = tuple(int(i) for i in np.nonzero(present)[0])
    rm = gf.reconstruction_matrix(data_shards, parity_shards, idx)
    survivors = shards[list(idx[:data_shards])]
    return gf_matmul_cpu(rm, survivors)


def has_avx2() -> bool:
    return bool(lib().gf_has_avx2())


def phash256_rows(words: np.ndarray, nbytes: int) -> np.ndarray:
    """Native phash256 over rows: (..., w) uint32 -> (..., 8) uint32.

    Bit-identical AVX2 twin of ops/hash.py phash256_host_batched; the
    hash dominated the CPU-codec e2e path in profiling (the encode
    itself is native already)."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    lead = words.shape[:-1]
    n = words.shape[-1]
    if n % 4:
        # mirror the numpy twin's contract so digests can never
        # silently diverge between hosts with and without the lib
        raise ValueError(f"word count {n} must be a multiple of 4")
    flat = words.reshape(-1, n)
    out = np.empty((flat.shape[0], 8), dtype=np.uint32)
    lib().phash256_rows(
        flat.ctypes.data_as(ctypes.c_void_p),
        flat.shape[0],
        n,
        nbytes,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out.reshape(*lead, 8)


# ---------------------------------------------------------------------
# Sanitizer harness (MINIO_TPU_SANITIZE=1)
#
# The instrumented library cannot be dlopen'd into an uninstrumented
# CPython: the ASan runtime must be first in the initial library list.
# The supported recipe is a SUBPROCESS with the env from
# sanitizer_env(): LD_PRELOAD of the toolchain's libasan plus
# PYTHONMALLOC=malloc, so ctypes scratch buffers get real redzones
# instead of hiding inside pymalloc arenas (numpy buffers use malloc
# either way).  tests/test_native.py's slow sweep drives this.
# ---------------------------------------------------------------------


def asan_runtime_path() -> "str | None":
    """The toolchain's libasan.so for LD_PRELOAD, or None if absent."""
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            capture_output=True,
            text=True,
            check=True,
        ).stdout.strip()
    except (OSError, subprocess.CalledProcessError):
        return None
    # an unresolved name is echoed back bare, with no directory part
    if os.path.sep in out and os.path.exists(out):
        return os.path.realpath(out)
    return None


def sanitizer_env(base: "dict | None" = None) -> "dict[str, str]":
    """Subprocess env that makes lib() load the instrumented build."""
    env = dict(os.environ if base is None else base)
    env["MINIO_TPU_SANITIZE"] = "1"
    env["PYTHONMALLOC"] = "malloc"
    rt = asan_runtime_path()
    if rt:
        env["LD_PRELOAD"] = rt
    # leaks are checked explicitly mid-run (lsan_recoverable_leak_check)
    # - the at-exit sweep would drown in CPython's own still-reachable
    # allocations under PYTHONMALLOC=malloc
    env.setdefault("ASAN_OPTIONS", "detect_leaks=1:leak_check_at_exit=0")
    env.setdefault("UBSAN_OPTIONS", "print_stacktrace=1")
    return env


def lsan_recoverable_leak_check() -> int:
    """Run LeakSanitizer now; 0 = clean, nonzero = native leaks found.

    Only meaningful inside a sanitizer_env() subprocess; returns 0 when
    the LSan runtime is not loaded.
    """
    try:
        fn = ctypes.CDLL(None).__lsan_do_recoverable_leak_check
    except (AttributeError, OSError):
        return 0
    fn.restype = ctypes.c_int
    fn.argtypes = []
    return int(fn())
