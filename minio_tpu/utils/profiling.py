"""On-demand profiling (admin profiling start/download,
cmd/admin-handlers.go StartProfilingHandler + DownloadProfilingData;
the reference collects pprof profiles per node and zips them).

cProfile for "cpu", tracemalloc snapshots for "mem"; results are
per-node bytes (pstats dump / tracemalloc top lines) the admin API
zips together.
"""

from __future__ import annotations

import cProfile
import io
import pstats
import threading


class Profiler:
    def __init__(self):
        self._mu = threading.Lock()
        self._cpu: "cProfile.Profile | None" = None
        self._mem = False

    def start(self, kind: str = "cpu") -> None:
        with self._mu:
            if kind == "cpu":
                if self._cpu is not None:
                    raise RuntimeError("cpu profiling already running")
                self._cpu = cProfile.Profile()
                self._cpu.enable()
            elif kind == "mem":
                import tracemalloc

                if not tracemalloc.is_tracing():
                    tracemalloc.start()
                self._mem = True
            else:
                raise ValueError(f"unknown profiler {kind!r}")

    def stop(self, kind: str = "cpu") -> bytes:
        """Stop + return the profile artifact bytes."""
        with self._mu:
            if kind == "cpu":
                if self._cpu is None:
                    raise RuntimeError("cpu profiling not running")
                self._cpu.disable()
                buf = io.StringIO()
                stats = pstats.Stats(self._cpu, stream=buf)
                stats.sort_stats("cumulative").print_stats(100)
                self._cpu = None
                return buf.getvalue().encode()
            if kind == "mem":
                import tracemalloc

                if not self._mem:
                    raise RuntimeError("mem profiling not running")
                snap = tracemalloc.take_snapshot()
                self._mem = False
                tracemalloc.stop()
                lines = [
                    str(s) for s in snap.statistics("lineno")[:200]
                ]
                return "\n".join(lines).encode()
            raise ValueError(f"unknown profiler {kind!r}")

    @property
    def running(self) -> "list[str]":
        with self._mu:
            out = []
            if self._cpu is not None:
                out.append("cpu")
            if self._mem:
                out.append("mem")
            return out
