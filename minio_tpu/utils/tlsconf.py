"""TLS configuration for the listener and every internode client
(the reference's pkg/certs hot-reload + xhttp TLS listener, trimmed to
env-driven static certs).

Env contract:
  MINIO_TPU_TLS=on            enable TLS (listener + internode clients)
  MINIO_TPU_CERT_FILE/MINIO_TPU_KEY_FILE   the server keypair
  MINIO_TPU_CA_FILE           CA bundle clients verify against;
                              without one, clients accept any cert
                              (self-signed single-cluster deployments -
                              internode auth still rides JWT)
"""

from __future__ import annotations

import http.client
import os
import ssl


def enabled() -> bool:
    return os.environ.get("MINIO_TPU_TLS", "off") == "on"


def server_context() -> "ssl.SSLContext":
    cert = os.environ.get("MINIO_TPU_CERT_FILE", "")
    key = os.environ.get("MINIO_TPU_KEY_FILE", "")
    if not cert or not key:
        raise RuntimeError(
            "MINIO_TPU_TLS=on needs MINIO_TPU_CERT_FILE and "
            "MINIO_TPU_KEY_FILE"
        )
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    return ctx


def _client_context() -> "ssl.SSLContext":
    ca = os.environ.get("MINIO_TPU_CA_FILE", "")
    if ca:
        return ssl.create_default_context(cafile=ca)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return ctx


def client_connection(
    host: str, port: int, timeout: float
) -> "http.client.HTTPConnection":
    """The one constructor every internode client uses, so the whole
    mesh switches to TLS with the env flag."""
    if enabled():
        return http.client.HTTPSConnection(
            host, port, timeout=timeout, context=_client_context()
        )
    return http.client.HTTPConnection(host, port, timeout=timeout)
