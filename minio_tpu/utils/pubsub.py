"""In-process pub/sub with bounded subscriber queues (pkg/pubsub).

Publishers never block: a slow subscriber drops its oldest entries
(the reference's non-blocking Publish with buffered channels).
"""

from __future__ import annotations

import collections
import threading


class PubSub:
    def __init__(self, maxlen: int = 10_000):
        self._mu = threading.Lock()
        self._subs: "list[_Sub]" = []
        self._maxlen = maxlen

    def publish(self, item) -> None:
        with self._mu:
            subs = list(self._subs)
        for s in subs:
            s._push(item)

    def subscribe(self) -> "_Sub":
        s = _Sub(self, self._maxlen)
        with self._mu:
            self._subs.append(s)
        return s

    def unsubscribe(self, sub: "_Sub") -> None:
        with self._mu:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    @property
    def num_subscribers(self) -> int:
        with self._mu:
            return len(self._subs)


class _Sub:
    def __init__(self, ps: PubSub, maxlen: int):
        self._ps = ps
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._q: collections.deque = collections.deque(maxlen=maxlen)

    def _push(self, item) -> None:
        with self._cv:
            self._q.append(item)
            self._cv.notify()

    def get(self, timeout: "float | None" = None):
        """Next item or None on timeout."""
        with self._cv:
            if not self._q and not self._cv.wait(timeout):
                return None
            if not self._q:
                return None
            return self._q.popleft()

    def drain(self) -> list:
        with self._cv:
            out = list(self._q)
            self._q.clear()
            return out

    def close(self) -> None:
        self._ps.unsubscribe(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
