"""Namespace-insensitive XML helpers shared by the S3 config document
parsers (tagging, object-lock, replication, SSE config)."""

from __future__ import annotations


def strip_ns(tag: str) -> str:
    return tag.rpartition("}")[2]


def findtext(root, name: str) -> str:
    """Text of the first *descendant* with the local name (documents
    where the name appears once, e.g. LegalHold/Status)."""
    for el in root.iter():
        if strip_ns(el.tag) == name:
            return (el.text or "").strip()
    return ""


def child_text(el, name: str) -> str:
    """Text of a *direct child* - for elements whose local name also
    appears nested deeper (e.g. Rule/Status vs
    Rule/DeleteMarkerReplication/Status)."""
    for c in el:
        if strip_ns(c.tag) == name:
            return (c.text or "").strip()
    return ""


def child(el, name: str):
    for c in el:
        if strip_ns(c.tag) == name:
            return c
    return None
