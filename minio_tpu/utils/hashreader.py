"""Hash-verifying reader wrapper (pkg/hash PutObjReader equivalent).

Wraps every upload stream: counts bytes, computes MD5 (the S3 ETag) and
optionally verifies client-supplied MD5/SHA256 at EOF, like
pkg/hash/reader.go.
"""

from __future__ import annotations

import hashlib


class BadDigest(Exception):
    def __init__(self, want: str, got: str):
        super().__init__(f"bad digest: want {want} got {got}")
        self.want, self.got = want, got


class SizeMismatch(Exception):
    """Fewer bytes arrived than the declared size (errIncompleteBody)."""

    def __init__(self, want: int, got: int):
        super().__init__(f"incomplete body: want {want} got {got}")
        self.want, self.got = want, got


class HashReader:
    def __init__(
        self,
        reader,
        size: int = -1,
        md5_hex: str = "",
        sha256_hex: str = "",
    ):
        self._r = reader
        self.size = size
        self.bytes_read = 0
        self._md5 = hashlib.md5()
        self._sha = hashlib.sha256() if sha256_hex else None
        self._want_md5 = md5_hex.lower()
        self._want_sha = sha256_hex.lower()
        self._eof = False

    def read(self, n: int = -1) -> bytes:
        if self._eof:
            return b""
        limit = n
        if self.size >= 0:
            remaining = self.size - self.bytes_read
            limit = remaining if n < 0 else min(n, remaining)
            if limit <= 0:
                self._finish()
                return b""
        chunk = self._r.read(limit)
        if not chunk:
            self._finish()
            return b""
        self.bytes_read += len(chunk)
        self._md5.update(chunk)
        if self._sha is not None:
            self._sha.update(chunk)
        return chunk

    def _finish(self) -> None:
        if self._eof:
            return
        self._eof = True
        # a framed stream (SigV4ChunkedReader) still holds its terminal
        # chunk + trailer signatures/checksums - verify them at EOF
        fin = getattr(self._r, "finalize", None)
        if fin is not None:
            fin()
        if 0 <= self.size != self.bytes_read:
            raise SizeMismatch(self.size, self.bytes_read)
        if self._want_md5 and self.md5_hex() != self._want_md5:
            raise BadDigest(self._want_md5, self.md5_hex())
        if self._want_sha and self._sha.hexdigest() != self._want_sha:
            raise BadDigest(self._want_sha, self._sha.hexdigest())

    def md5_hex(self) -> str:
        return self._md5.hexdigest()

    def etag(self) -> str:
        return self.md5_hex()
