"""Minimal HMAC-SHA256 JWT for internode authentication (cmd/jwt.go).

Every internode request carries a short-lived token signed with the
cluster credentials (newAuthToken, jwt.go:164; validated by
authenticateNode, jwt.go:84).  Only HS256 is supported - the algorithm
field is verified, not trusted.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


class JWTError(Exception):
    pass


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    pad = (-len(s)) % 4
    return base64.urlsafe_b64decode(s + "=" * pad)


def sign(claims: dict, secret: str, expiry_s: int = 900) -> str:
    header = {"alg": "HS256", "typ": "JWT"}
    body = dict(claims)
    now = int(time.time())
    body.setdefault("iat", now)
    body.setdefault("exp", now + expiry_s)
    h = _b64(json.dumps(header, separators=(",", ":")).encode())
    p = _b64(json.dumps(body, separators=(",", ":")).encode())
    sig = hmac.new(
        secret.encode(), f"{h}.{p}".encode(), hashlib.sha256
    ).digest()
    return f"{h}.{p}.{_b64(sig)}"


def verify(token: str, secret: str) -> dict:
    try:
        h, p, s = token.split(".")
    except ValueError:
        raise JWTError("malformed token") from None
    try:
        header = json.loads(_unb64(h))
    except Exception:  # noqa: BLE001
        raise JWTError("bad header") from None
    if header.get("alg") != "HS256":
        raise JWTError(f"algorithm {header.get('alg')!r} not allowed")
    want = hmac.new(
        secret.encode(), f"{h}.{p}".encode(), hashlib.sha256
    ).digest()
    if not hmac.compare_digest(want, _unb64(s)):
        raise JWTError("signature mismatch")
    try:
        claims = json.loads(_unb64(p))
    except Exception:  # noqa: BLE001
        raise JWTError("bad claims") from None
    if claims.get("exp", 0) < time.time():
        raise JWTError("token expired")
    return claims
