"""S3 tag sets (pkg/tags in later reference trees; mid-2020 reference
validates tags inline in the handlers).

One parser/serializer used by bucket tagging, object tagging, and the
``x-amz-tagging`` PUT header (URL-encoded form).
"""

from __future__ import annotations

import urllib.parse
import xml.etree.ElementTree as ET

from .xmlutil import strip_ns as _strip_ns

MAX_OBJECT_TAGS = 10
MAX_BUCKET_TAGS = 50
MAX_KEY_LEN = 128
MAX_VALUE_LEN = 256

_S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


class TagError(Exception):
    pass


class TagXMLError(TagError):
    """Unparseable document: MalformedXML on the wire, not InvalidTag
    (AWS distinguishes schema failure from tag-content failure)."""


def validate(tags: "dict[str, str]", limit: int) -> None:
    if len(tags) > limit:
        raise TagError(f"too many tags (max {limit})")
    for k, v in tags.items():
        if not k or len(k) > MAX_KEY_LEN:
            raise TagError(f"invalid tag key {k!r}")
        if len(v) > MAX_VALUE_LEN:
            raise TagError(f"tag value too long for key {k!r}")


def from_xml(body: bytes, limit: int) -> "dict[str, str]":
    """Parse a <Tagging><TagSet><Tag>... document."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise TagXMLError("malformed XML") from None
    if _strip_ns(root.tag) != "Tagging":
        raise TagXMLError("not a Tagging document")
    tags: dict[str, str] = {}
    for el in root.iter():
        if _strip_ns(el.tag) != "Tag":
            continue
        key = value = None
        for child in el:
            name = _strip_ns(child.tag)
            if name == "Key":
                key = (child.text or "").strip()
            elif name == "Value":
                value = child.text or ""
        if key is None:
            raise TagError("Tag missing Key")
        if key in tags:
            raise TagError(f"duplicate tag key {key!r}")
        tags[key] = value or ""
    validate(tags, limit)
    return tags


def to_xml(tags: "dict[str, str]") -> bytes:
    import xml.sax.saxutils as sx

    items = "".join(
        f"<Tag><Key>{sx.escape(k)}</Key><Value>{sx.escape(v)}</Value></Tag>"
        for k, v in tags.items()
    )
    return (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        f'<Tagging xmlns="{_S3_NS}"><TagSet>{items}</TagSet></Tagging>'
    ).encode()


def from_header(value: str, limit: int = MAX_OBJECT_TAGS) -> "dict[str, str]":
    """Parse the URL-encoded x-amz-tagging request header."""
    tags: dict[str, str] = {}
    if not value:
        return tags
    for k, v in urllib.parse.parse_qsl(value, keep_blank_values=True):
        if k in tags:
            raise TagError(f"duplicate tag key {k!r}")
        tags[k] = v
    validate(tags, limit)
    return tags


def encode(tags: "dict[str, str]") -> str:
    """Tags -> the URL-encoded form stored in object metadata
    (xhttp.AmzObjectTagging / UserTags in FileInfo)."""
    return urllib.parse.urlencode(tags)


def decode(value: str) -> "dict[str, str]":
    return dict(
        urllib.parse.parse_qsl(value, keep_blank_values=True)
    )
