"""Structured JSON logging (cmd/logger analogue).

One line per event: ``{"ts": ..., "level": ..., "name": ..., "msg":
..., **fields}``.  Console-friendly in dev (MINIO_TPU_LOG=console
switches to plain text); the JSON shape is what the reference's
logger targets emit (cmd/logger/logger.go:301-389).
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import sys

_CONFIGURED = False


class _JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": datetime.datetime.fromtimestamp(
                record.created, tz=datetime.timezone.utc
            ).isoformat(timespec="milliseconds"),
            "level": record.levelname.lower(),
            "name": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            doc.update(extra)
        if record.exc_info and record.exc_info[0] is not None:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def setup(level: str = "info") -> None:
    """Install the process-wide handler (idempotent)."""
    global _CONFIGURED
    if _CONFIGURED:
        return
    _CONFIGURED = True
    root = logging.getLogger("minio_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    h = logging.StreamHandler(sys.stdout)
    if os.environ.get("MINIO_TPU_LOG", "json") == "console":
        h.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(message)s")
        )
    else:
        h.setFormatter(_JSONFormatter())
    root.addHandler(h)
    root.propagate = False


def logger(name: str) -> logging.Logger:
    return logging.getLogger(f"minio_tpu.{name}")


def kv(**fields) -> dict:
    """Attach structured fields: log.info("msg", extra=kv(bucket=b))."""
    return {"fields": fields}
