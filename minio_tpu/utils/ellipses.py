"""Ellipses endpoint expansion + set layout math.

The CLI arg syntax of the reference (pkg/ellipses + endpoint-ellipses.go):
``http://host{1...4}/disk{1...8}`` expands to the cross-product of ranges,
and the total drive count is divided into erasure sets of 4-16 drives
using the greatest valid symmetric divisor (getSetIndexes,
endpoint-ellipses.go:132; docs/distributed/DESIGN.md:38-48).
"""

from __future__ import annotations

import itertools
import re

_ELLIPSIS = re.compile(r"\{(\d+)\.\.\.(\d+)\}")

# valid erasure set sizes (docs/distributed/DESIGN.md:40; the reference
# uses 4-16, we additionally allow 2 for tiny test layouts)
SET_SIZES = tuple(range(2, 17))


def has_ellipses(arg: str) -> bool:
    return bool(_ELLIPSIS.search(arg))


def expand(arg: str) -> list[str]:
    """Expand every {a...b} range in the pattern (cross-product order:
    rightmost varies fastest, matching the reference's arg expansion)."""
    spans = list(_ELLIPSIS.finditer(arg))
    if not spans:
        return [arg]
    ranges = []
    for m in spans:
        lo, hi = int(m.group(1)), int(m.group(2))
        if hi < lo:
            raise ValueError(f"bad range {m.group(0)}")
        width = len(m.group(1)) if m.group(1).startswith("0") else 0
        ranges.append(
            [str(v).zfill(width) for v in range(lo, hi + 1)]
        )
    out = []
    for combo in itertools.product(*ranges):
        s = arg
        # replace right-to-left so spans stay valid
        for m, v in zip(reversed(spans), reversed(combo)):
            s = s[: m.start()] + v + s[m.end() :]
        out.append(s)
    return out


def expand_all(args: list[str]) -> list[str]:
    out = []
    for a in args:
        out.extend(expand(a))
    return out


def get_set_size(count: int) -> int:
    """Drives per set: the largest valid size dividing count evenly."""
    for size in sorted(SET_SIZES, reverse=True):
        if count % size == 0:
            return size
    raise ValueError(
        f"cannot partition {count} drives into sets of {SET_SIZES}"
    )


def layout(count: int) -> tuple[int, int]:
    """(set_count, drives_per_set) for a drive count."""
    size = get_set_size(count)
    return count // size, size
