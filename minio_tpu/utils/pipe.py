"""Bounded in-process byte pipe (io.Pipe analogue).

Connects a push-style producer (get_object writing into a sink) to a
pull-style consumer (put_object reading from a source) across two
threads with bounded memory - the streaming-copy primitive
(CopyObject pipes GetObject into PutObject in the reference without
materializing the object).
"""

from __future__ import annotations

import queue
import threading

_EOF = object()
CHUNK = 1 << 20


class PipeClosed(OSError):
    pass


class StreamPipe:
    """One writer thread, one reader thread, bounded chunk queue."""

    def __init__(self, depth: int = 4):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._buf = b""
        self._eof = False
        self._err: "BaseException | None" = None
        self._closed_read = threading.Event()

    # -- writer side ------------------------------------------------------

    def write(self, data: bytes) -> int:
        if self._closed_read.is_set():
            raise PipeClosed("read side closed")
        view = memoryview(data)
        for off in range(0, len(view), CHUNK):
            chunk = bytes(view[off : off + CHUNK])
            while True:
                if self._closed_read.is_set():
                    raise PipeClosed("read side closed")
                try:
                    self._q.put(chunk, timeout=0.25)
                    break
                except queue.Full:
                    continue
        return len(data)

    def close_write(self, error: "BaseException | None" = None) -> None:
        """Signal EOF (or a producer error, re-raised to the reader)."""
        self._err = error
        while True:
            if self._closed_read.is_set():
                return
            try:
                self._q.put(_EOF, timeout=0.25)
                return
            except queue.Full:
                continue

    # -- reader side ------------------------------------------------------

    def read(self, n: int = -1) -> bytes:
        out = bytearray()
        while n < 0 or len(out) < n:
            if self._buf:
                take = len(self._buf) if n < 0 else n - len(out)
                out += self._buf[:take]
                self._buf = self._buf[take:]
                continue
            if self._eof:
                break
            item = self._q.get()
            if item is _EOF:
                self._eof = True
                if self._err is not None:
                    raise OSError(
                        f"pipe producer failed: {self._err}"
                    ) from self._err
                break
            self._buf = item
        return bytes(out)

    def close_read(self) -> None:
        """Abandon the stream; unblocks a producer stuck on a full pipe."""
        self._closed_read.set()
        # drain so a producer blocked in put() exits promptly
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


def streaming_copy(producer, consumer):
    """Run ``producer(sink)`` in a thread while ``consumer(source)``
    runs inline; returns the consumer's result.  Producer errors
    surface to the consumer as a short/failed read; consumer errors
    unblock and cancel the producer."""
    pipe = StreamPipe()

    def run():
        try:
            producer(pipe)
        except BaseException as e:  # noqa: BLE001
            pipe.close_write(e)
        else:
            pipe.close_write()

    t = threading.Thread(target=run, name="stream-copy", daemon=True)
    t.start()
    try:
        return consumer(pipe)
    finally:
        pipe.close_read()
        t.join(timeout=30)
