"""Disk cache layer (cmd/disk-cache.go CacheObjectLayer +
disk-cache-backend.go diskCache).

An SSD edge cache shadowing any ObjectLayer: GETs read through the
cache (consistent-hash drive pick, etag-validated against the backend),
writes go straight to the backend and invalidate, and an LRU GC keeps
each cache drive between its low/high watermarks.  Only full-object
GETs populate the cache; range reads are served from a cached whole
object when present and pass through otherwise (the reference's
range-caching refinement is skipped - ranges never cause eviction
pressure here).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading

from .api import ObjectInfo, ObjectNotFound

# GC watermarks (disk-cache.go cacheGCHighWater/LowWater defaults)
HIGH_WATERMARK = 0.80
LOW_WATERMARK = 0.70
# objects above this fraction of the quota are never cached
MAX_OBJECT_FRACTION = 0.25


class _CacheDrive:
    """One cache directory with a byte quota and LRU eviction."""

    def __init__(self, root: str, quota_bytes: int):
        self.root = root
        self.quota = quota_bytes
        self._mu = threading.Lock()
        os.makedirs(root, exist_ok=True)
        self._used = self._scan_used()

    def _scan_used(self) -> int:
        total = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    pass
        return total

    def _entry_dir(self, bucket: str, key: str) -> str:
        h = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.root, h[:2], h)

    # -- lookup -----------------------------------------------------------

    def get(self, bucket: str, key: str) -> "tuple[str, dict] | None":
        """(data_path, meta) when cached; touches the data file's
        mtime for LRU.  meta.json is never rewritten on the read path:
        an in-place rewrite would race concurrent readers into
        spurious misses (and re-population)."""
        d = self._entry_dir(bucket, key)
        data, meta_p = os.path.join(d, "data"), os.path.join(d, "meta.json")
        try:
            with open(meta_p, encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        if not os.path.isfile(data):
            return None
        try:
            os.utime(data)  # LRU recency = data-file mtime
        except OSError:
            pass
        return data, meta

    # -- population -------------------------------------------------------

    def put(
        self, bucket: str, key: str, data_path_tmp: str, meta: dict
    ) -> None:
        """Adopt a staged data file into the cache (rename, no copy)."""
        size = os.path.getsize(data_path_tmp)
        if self.quota and size > self.quota * MAX_OBJECT_FRACTION:
            os.remove(data_path_tmp)
            return
        with self._mu:
            if self.quota and self._used + size > self.quota * HIGH_WATERMARK:
                self._gc_locked(
                    int(self.quota * LOW_WATERMARK) - size
                )
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        data_p = os.path.join(d, "data")
        # re-population overwrites a stale entry in place: its old
        # bytes leave the accounting as the new ones enter
        try:
            old_size = os.path.getsize(data_p)
        except OSError:
            old_size = 0
        os.replace(data_path_tmp, data_p)
        meta = {**meta, "size": size}
        tmp = os.path.join(d, "meta.json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(d, "meta.json"))
        with self._mu:
            self._used += size - old_size

    def invalidate(self, bucket: str, key: str) -> None:
        d = self._entry_dir(bucket, key)
        try:
            size = os.path.getsize(os.path.join(d, "data"))
        except OSError:
            size = 0
        shutil.rmtree(d, ignore_errors=True)
        with self._mu:
            self._used = max(0, self._used - size)

    # -- GC (disk-cache.go gc at watermarks) ------------------------------

    def _entries(self) -> "list[tuple[float, int, str]]":
        out = []
        for sub in os.listdir(self.root):
            subp = os.path.join(self.root, sub)
            if not os.path.isdir(subp):
                continue
            for h in os.listdir(subp):
                d = os.path.join(subp, h)
                try:
                    with open(
                        os.path.join(d, "meta.json"), encoding="utf-8"
                    ) as f:
                        json.load(f)  # unreadable meta -> reap entry
                    st = os.stat(os.path.join(d, "data"))
                except (OSError, ValueError):
                    shutil.rmtree(d, ignore_errors=True)
                    continue
                out.append((st.st_mtime, st.st_size, d))
        return out

    def _gc_locked(self, target_used: int) -> None:
        """Evict least-recently-used entries until used <= target."""
        if self._used <= max(target_used, 0):
            return
        for _atime, size, d in sorted(self._entries()):
            shutil.rmtree(d, ignore_errors=True)
            self._used = max(0, self._used - size)
            if self._used <= max(target_used, 0):
                break

    @property
    def used(self) -> int:
        with self._mu:
            return self._used


class CacheObjectLayer:
    """ObjectLayer decorator adding the read cache.  Every unknown
    attribute passes straight through to the backend layer."""

    def __init__(
        self,
        backend,
        drives: "list[str]",
        quota_bytes: int = 0,
    ):
        self._ol = backend
        self.drives = [_CacheDrive(d, quota_bytes) for d in drives]
        self.hits = 0
        self.misses = 0

    def _drive(self, bucket: str, key: str) -> "_CacheDrive":
        """Consistent drive pick (disk-cache.go:534 hashIndex)."""
        h = int.from_bytes(
            hashlib.sha256(f"{bucket}/{key}".encode()).digest()[:8],
            "big",
        )
        return self.drives[h % len(self.drives)]

    # -- reads ------------------------------------------------------------

    def get_object(
        self, bucket, object_name, writer, offset=0, length=-1,
        version_id="", sse=None,
    ):
        if version_id or sse is not None:
            return self._ol.get_object(
                bucket, object_name, writer, offset, length,
                version_id, sse,
            )
        drive = self._drive(bucket, object_name)
        # backend metadata is the source of truth; a cached entry with
        # a stale etag is invalid (DecryptObjectInfo-less path of
        # cacheObjects.GetObjectNInfo)
        info = self._ol.get_object_info(bucket, object_name)
        # the same range validation the backend performs: cached and
        # uncached objects must answer identically (InvalidRange, not
        # a silently short body)
        logical = info.size
        if offset < 0 or (
            length >= 0 and offset + length > logical
        ) or offset > logical:
            from .api import InvalidRange

            raise InvalidRange(f"{offset}+{length} of {logical}")
        hit = drive.get(bucket, object_name)
        if hit is not None and hit[1].get("etag") == info.etag:
            self.hits += 1
            path, meta = hit
            total = meta.get("size", info.size)
            want = length if length >= 0 else total - offset
            with open(path, "rb") as f:
                f.seek(offset)
                remaining = want
                while remaining > 0:
                    chunk = f.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    writer.write(chunk)
                    remaining -= len(chunk)
            return info
        self.misses += 1
        if offset == 0 and (length < 0 or length >= info.size):
            # full read: tee into the cache while serving
            import tempfile

            tmp = tempfile.NamedTemporaryFile(
                dir=drive.root, delete=False
            )
            try:
                tee = _Tee(writer, tmp)
                out = self._ol.get_object(
                    bucket, object_name, tee, 0, -1
                )
                tmp.close()
                drive.put(
                    bucket, object_name, tmp.name,
                    {"etag": info.etag},
                )
                return out
            except BaseException:
                tmp.close()
                try:
                    os.remove(tmp.name)
                except OSError:
                    pass
                raise
        return self._ol.get_object(
            bucket, object_name, writer, offset, length
        )

    # -- writes invalidate ------------------------------------------------

    def put_object(self, bucket, object_name, *a, **kw):
        self._drive(bucket, object_name).invalidate(bucket, object_name)
        return self._ol.put_object(bucket, object_name, *a, **kw)

    def delete_object(self, bucket, object_name, *a, **kw):
        self._drive(bucket, object_name).invalidate(bucket, object_name)
        return self._ol.delete_object(bucket, object_name, *a, **kw)

    def copy_object(
        self, src_bucket, src_object, dst_bucket, dst_object, *a, **kw
    ):
        self._drive(dst_bucket, dst_object).invalidate(
            dst_bucket, dst_object
        )
        return self._ol.copy_object(
            src_bucket, src_object, dst_bucket, dst_object, *a, **kw
        )

    def complete_multipart_upload(self, bucket, object_name, *a, **kw):
        self._drive(bucket, object_name).invalidate(bucket, object_name)
        return self._ol.complete_multipart_upload(
            bucket, object_name, *a, **kw
        )

    def update_object_meta(self, bucket, object_name, *a, **kw):
        # metadata rides the backend; cached data stays valid (same
        # etag) so no invalidation needed - but tags/retention changes
        # do not flow into cached meta, which only holds the etag
        return self._ol.update_object_meta(bucket, object_name, *a, **kw)

    def cache_stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "drives": [
                {"root": d.root, "used": d.used, "quota": d.quota}
                for d in self.drives
            ],
        }

    def __getattr__(self, name):
        return getattr(self._ol, name)


class _Tee:
    def __init__(self, a, b):
        self._a, self._b = a, b

    def write(self, data):
        self._a.write(data)
        self._b.write(data)


def cache_from_env(backend):
    """Wrap per MINIO_TPU_CACHE_DRIVES / MINIO_TPU_CACHE_QUOTA_MB."""
    drives = [
        d.strip()
        for d in os.environ.get("MINIO_TPU_CACHE_DRIVES", "").split(",")
        if d.strip()
    ]
    if not drives:
        return backend
    try:
        quota_mb = int(os.environ.get("MINIO_TPU_CACHE_QUOTA_MB") or 0)
    except ValueError:
        quota_mb = 0
    return CacheObjectLayer(backend, drives, quota_mb << 20)
