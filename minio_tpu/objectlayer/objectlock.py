"""Object lock / retention / legal hold (WORM).

The S3 object-lock data model and enforcement rules from the reference's
``pkg/bucket/object/lock/lock.go`` and ``cmd/bucket-object-lock.go``:

- A bucket may carry an ``ObjectLockConfiguration`` (only on buckets
  created with object-lock enabled, which forces versioning).  Its
  optional default retention rule stamps every new object version.
- An object version carries retention (mode GOVERNANCE/COMPLIANCE +
  retain-until date) and/or a legal hold flag in its user metadata.
- Deletion of a version is blocked while the legal hold is ON or the
  retain-until date is in the future; GOVERNANCE can be bypassed by a
  caller holding ``s3:BypassGovernanceRetention`` who set the
  ``x-amz-bypass-governance-retention: true`` header
  (``enforceRetentionBypassForDelete``, cmd/bucket-object-lock.go:83).
"""

from __future__ import annotations

import dataclasses
import datetime
import xml.etree.ElementTree as ET

# metadata keys on the object version (objectlock.AmzObjectLock* keys)
META_MODE = "x-amz-object-lock-mode"
META_RETAIN_UNTIL = "x-amz-object-lock-retain-until-date"
META_LEGAL_HOLD = "x-amz-object-lock-legal-hold"

GOVERNANCE = "GOVERNANCE"
COMPLIANCE = "COMPLIANCE"

from ..utils.xmlutil import findtext as _findtext, strip_ns as _strip_ns

_S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


class ObjectLockError(Exception):
    """Malformed object-lock configuration or headers."""


def utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def parse_iso8601(value: str) -> datetime.datetime:
    """RetainUntilDate parser - accepts the AWS ISO8601 forms."""
    v = value.strip()
    if v.endswith("Z"):
        v = v[:-1] + "+00:00"
    try:
        dt = datetime.datetime.fromisoformat(v)
    except ValueError:
        raise ObjectLockError(f"invalid date {value!r}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt


def format_iso8601(dt: datetime.datetime) -> str:
    return dt.astimezone(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


@dataclasses.dataclass
class DefaultRetention:
    mode: str = ""  # GOVERNANCE | COMPLIANCE
    days: int = 0
    years: int = 0


@dataclasses.dataclass
class ObjectLockConfig:
    """Parsed ObjectLockConfiguration document."""

    enabled: bool = True
    default: "DefaultRetention | None" = None

    @classmethod
    def from_xml(cls, body: bytes) -> "ObjectLockConfig":
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise ObjectLockError("malformed XML") from None
        if _strip_ns(root.tag) != "ObjectLockConfiguration":
            raise ObjectLockError("not an ObjectLockConfiguration")
        enabled_s = _findtext(root, "ObjectLockEnabled")
        if enabled_s and enabled_s != "Enabled":
            raise ObjectLockError("ObjectLockEnabled must be 'Enabled'")
        default = None
        mode = _findtext(root, "Mode")
        if mode:
            if mode not in (GOVERNANCE, COMPLIANCE):
                raise ObjectLockError(f"invalid Mode {mode!r}")
            days_s = _findtext(root, "Days")
            years_s = _findtext(root, "Years")
            if bool(days_s) == bool(years_s):
                raise ObjectLockError(
                    "exactly one of Days or Years is required"
                )
            try:
                days = int(days_s) if days_s else 0
                years = int(years_s) if years_s else 0
            except ValueError:
                raise ObjectLockError("Days/Years must be integers") from None
            if days < 0 or years < 0 or (days_s and days == 0) or (
                years_s and years == 0
            ):
                raise ObjectLockError("Days/Years must be positive")
            default = DefaultRetention(mode, days, years)
        return cls(enabled=True, default=default)

    def to_xml(self) -> bytes:
        rule = ""
        if self.default is not None:
            dur = (
                f"<Days>{self.default.days}</Days>"
                if self.default.days
                else f"<Years>{self.default.years}</Years>"
            )
            rule = (
                "<Rule><DefaultRetention>"
                f"<Mode>{self.default.mode}</Mode>{dur}"
                "</DefaultRetention></Rule>"
            )
        return (
            '<?xml version="1.0" encoding="UTF-8"?>\n'
            f'<ObjectLockConfiguration xmlns="{_S3_NS}">'
            "<ObjectLockEnabled>Enabled</ObjectLockEnabled>"
            f"{rule}</ObjectLockConfiguration>"
        ).encode()

    def default_retention_meta(self) -> dict:
        """Metadata stamped on new versions by the default rule
        (checkPutObjectLockAllowed, cmd/object-handlers.go)."""
        if self.default is None:
            return {}
        until = utcnow() + datetime.timedelta(
            days=self.default.days + 365 * self.default.years
        )
        return {
            META_MODE: self.default.mode,
            META_RETAIN_UNTIL: format_iso8601(until),
        }


@dataclasses.dataclass
class Retention:
    mode: str = ""
    retain_until: "datetime.datetime | None" = None

    @property
    def valid(self) -> bool:
        return self.mode in (GOVERNANCE, COMPLIANCE)

    @classmethod
    def from_xml(cls, body: bytes) -> "Retention":
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise ObjectLockError("malformed XML") from None
        if _strip_ns(root.tag) != "Retention":
            raise ObjectLockError("not a Retention document")
        mode = _findtext(root, "Mode")
        until_s = _findtext(root, "RetainUntilDate")
        if mode not in (GOVERNANCE, COMPLIANCE):
            raise ObjectLockError(f"invalid Mode {mode!r}")
        if not until_s:
            raise ObjectLockError("RetainUntilDate is required")
        until = parse_iso8601(until_s)
        if until <= utcnow():
            raise ObjectLockError("RetainUntilDate must be in the future")
        return cls(mode, until)

    @classmethod
    def from_meta(cls, user_defined: dict) -> "Retention":
        mode = user_defined.get(META_MODE, "")
        until_s = user_defined.get(META_RETAIN_UNTIL, "")
        if not mode or not until_s:
            return cls()
        try:
            return cls(mode, parse_iso8601(until_s))
        except ObjectLockError:
            return cls()

    def to_xml(self) -> bytes:
        return (
            '<?xml version="1.0" encoding="UTF-8"?>\n'
            f'<Retention xmlns="{_S3_NS}">'
            f"<Mode>{self.mode}</Mode>"
            f"<RetainUntilDate>{format_iso8601(self.retain_until)}"
            "</RetainUntilDate></Retention>"
        ).encode()


def parse_legal_hold_xml(body: bytes) -> str:
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise ObjectLockError("malformed XML") from None
    if _strip_ns(root.tag) != "LegalHold":
        raise ObjectLockError("not a LegalHold document")
    status = _findtext(root, "Status")
    if status not in ("ON", "OFF"):
        raise ObjectLockError("Status must be ON or OFF")
    return status


def legal_hold_xml(status: str) -> bytes:
    return (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        f'<LegalHold xmlns="{_S3_NS}">'
        f"<Status>{status}</Status></LegalHold>"
    ).encode()


def retention_meta_from_headers(headers: dict) -> dict:
    """Explicit per-object lock headers on PUT
    (x-amz-object-lock-*, objectlock.ParseObjectLockHeaders)."""
    lower = {k.lower(): v for k, v in headers.items()}
    mode = lower.get(META_MODE, "")
    until_s = lower.get(META_RETAIN_UNTIL, "")
    hold = lower.get(META_LEGAL_HOLD, "")
    meta: dict = {}
    if bool(mode) != bool(until_s):
        raise ObjectLockError(
            "x-amz-object-lock-mode and "
            "x-amz-object-lock-retain-until-date must both be present"
        )
    if mode:
        if mode.upper() not in (GOVERNANCE, COMPLIANCE):
            raise ObjectLockError(f"unknown WORM mode {mode!r}")
        until = parse_iso8601(until_s)
        if until <= utcnow():
            raise ObjectLockError("retain date must be in the future")
        meta[META_MODE] = mode.upper()
        meta[META_RETAIN_UNTIL] = format_iso8601(until)
    if hold:
        if hold.upper() not in ("ON", "OFF"):
            raise ObjectLockError("legal hold must be ON or OFF")
        meta[META_LEGAL_HOLD] = hold.upper()
    return meta


def is_governance_bypass(headers: dict) -> bool:
    for k, v in headers.items():
        if k.lower() == "x-amz-bypass-governance-retention":
            return v.strip().lower() == "true"
    return False


def retention_blocks_delete(
    user_defined: dict, bypass_governance: bool = False
) -> "str | None":
    """Why (if at all) this version cannot be deleted right now.

    Returns None when deletion may proceed, "legal-hold" or "retention"
    otherwise.  ``bypass_governance`` reflects a caller who both set the
    bypass header AND holds the bypass permission - GOVERNANCE yields to
    it, COMPLIANCE never does (enforceRetentionBypassForDelete).
    """
    if user_defined.get(META_LEGAL_HOLD, "") == "ON":
        return "legal-hold"
    ret = Retention.from_meta(user_defined)
    if not ret.valid or ret.retain_until is None:
        return None
    if ret.retain_until <= utcnow():
        return None
    if ret.mode == GOVERNANCE and bypass_governance:
        return None
    return "retention"
