"""ObjectLayer interface + object-level data types and errors.

The seam between API handlers and storage backends
(cmd/object-api-interface.go:66-140 ObjectLayer; error types from
cmd/object-api-errors.go).  Implementations: ErasureObjects (one set),
ErasureSets (hash-routed sets), ErasureZones (capacity-routed zones),
FSObjects (single-disk).
"""

from __future__ import annotations

import dataclasses
import time


class ObjectLayerError(Exception):
    pass


class BucketNotFound(ObjectLayerError):
    pass


class BucketExists(ObjectLayerError):
    pass


class BucketNotEmpty(ObjectLayerError):
    pass


class InvalidBucketName(ObjectLayerError):
    pass


class ObjectNotFound(ObjectLayerError):
    pass


class VersionNotFound(ObjectLayerError):
    pass


class InvalidObjectName(ObjectLayerError):
    pass


class ReadQuorumError(ObjectLayerError):
    """errErasureReadQuorum."""


class WriteQuorumError(ObjectLayerError):
    """errErasureWriteQuorum."""


class InvalidRange(ObjectLayerError):
    pass


class InvalidUploadID(ObjectLayerError):
    pass


class InvalidPart(ObjectLayerError):
    pass


class InvalidPartOrder(ObjectLayerError):
    pass


class EntityTooSmall(ObjectLayerError):
    """Non-final multipart part below the S3 5 MiB minimum."""


class PreconditionFailed(ObjectLayerError):
    pass


@dataclasses.dataclass
class BucketInfo:
    name: str
    created_ns: int


@dataclasses.dataclass
class ObjectInfo:
    """Object metadata surfaced to the API layer (cmd/object-api-datatypes.go)."""

    bucket: str
    name: str
    size: int = 0
    mod_time_ns: int = 0
    etag: str = ""
    content_type: str = ""
    version_id: str = ""
    is_latest: bool = True
    delete_marker: bool = False
    user_defined: dict = dataclasses.field(default_factory=dict)
    parts: list = dataclasses.field(default_factory=list)
    is_dir: bool = False

    @property
    def mod_time(self) -> float:
        return self.mod_time_ns / 1e9


@dataclasses.dataclass
class ListObjectsInfo:
    is_truncated: bool = False
    next_marker: str = ""
    objects: list = dataclasses.field(default_factory=list)
    prefixes: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ListObjectVersionsInfo:
    """ListObjectVersions result: versions + delete markers interleaved
    newest-first per key (ListObjectVersions, cmd/object-api-datatypes.go)."""

    is_truncated: bool = False
    next_key_marker: str = ""
    next_version_id_marker: str = ""
    versions: list = dataclasses.field(default_factory=list)
    prefixes: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ListMultipartsInfo:
    uploads: list = dataclasses.field(default_factory=list)
    is_truncated: bool = False


@dataclasses.dataclass
class MultipartInfo:
    bucket: str = ""
    object: str = ""
    upload_id: str = ""
    initiated_ns: int = 0


@dataclasses.dataclass
class PartInfo:
    part_number: int = 0
    etag: str = ""
    size: int = 0
    actual_size: int = 0
    mod_time_ns: int = 0


@dataclasses.dataclass
class CompletePart:
    part_number: int
    etag: str


META_BUCKET = ".sys"


def check_bucket_name(name: str) -> None:
    """S3 bucket naming rules (IsValidBucketName, pkg bucket rules).

    The reserved meta volume is exempt (isMinioMetaBucketName): internal
    subsystems (IAM, bucket metadata) store erasure-coded documents
    there through the ordinary ObjectLayer path; the S3 router refuses
    it before any handler runs (authz.is_reserved_bucket)."""
    if name == META_BUCKET:
        return
    if not (3 <= len(name) <= 63):
        raise InvalidBucketName(name)
    if name.startswith((".", "-")) or name.endswith((".", "-")):
        raise InvalidBucketName(name)
    for ch in name:
        if not (ch.islower() and ch.isalnum() or ch.isdigit() or ch in ".-"):
            raise InvalidBucketName(name)
    if ".." in name or ".-" in name or "-." in name:
        raise InvalidBucketName(name)


def check_object_name(name: str) -> None:
    if not name or len(name) > 1024:
        raise InvalidObjectName(name)
    if name.startswith("/") or ".." in name.split("/"):
        raise InvalidObjectName(name)
    if "\0" in name:
        raise InvalidObjectName(name)


def prepare_copy_meta(src_info, metadata: "dict | None") -> dict:
    """Destination metadata for CopyObject: source user metadata with
    directive overrides applied, minus the etag and EVERY internal
    transform marker (compression, SSE, ...) - the copy pipe carries
    decoded plaintext and the destination put re-applies its own
    transforms, so a stale marker would make GET misinterpret the
    stored bytes."""
    meta = {
        k: v
        for k, v in src_info.user_defined.items()
        if not k.startswith("x-internal-")
    }
    if metadata:
        meta.update(metadata)
    meta.pop("etag", None)
    return meta


class ObjectLayer:
    """Abstract object store (subset grows as surfaces land)."""

    # buckets
    def make_bucket(self, bucket: str) -> None:
        raise NotImplementedError

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        raise NotImplementedError

    def list_buckets(self) -> list[BucketInfo]:
        raise NotImplementedError

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        raise NotImplementedError

    # objects
    def put_object(
        self, bucket: str, object_name: str, reader, size: int = -1,
        metadata: "dict | None" = None, versioned: bool = False,
        compress: "bool | None" = None,
    ) -> ObjectInfo:
        raise NotImplementedError

    def get_object_info(
        self, bucket: str, object_name: str, version_id: str = ""
    ) -> ObjectInfo:
        raise NotImplementedError

    def get_object(
        self, bucket: str, object_name: str, writer,
        offset: int = 0, length: int = -1, version_id: str = "",
    ) -> ObjectInfo:
        raise NotImplementedError

    def delete_object(
        self, bucket: str, object_name: str, version_id: str = ""
    ) -> ObjectInfo:
        raise NotImplementedError

    def update_object_meta(
        self, bucket: str, object_name: str, updates: dict,
        version_id: str = "",
    ) -> ObjectInfo:
        """Merge metadata updates into an existing version (tags,
        retention, legal hold).  None values remove keys."""
        raise NotImplementedError

    def copy_object(
        self, src_bucket: str, src_object: str, dst_bucket: str,
        dst_object: str, metadata: "dict | None" = None,
        versioned: bool = False,
    ) -> ObjectInfo:
        raise NotImplementedError

    def list_objects(
        self, bucket: str, prefix: str = "", marker: str = "",
        delimiter: str = "", max_keys: int = 1000,
    ) -> ListObjectsInfo:
        raise NotImplementedError

    # multipart
    def new_multipart_upload(
        self, bucket: str, object_name: str, metadata: "dict | None" = None
    ) -> str:
        raise NotImplementedError

    def put_object_part(
        self, bucket: str, object_name: str, upload_id: str,
        part_number: int, reader, size: int = -1,
    ) -> PartInfo:
        raise NotImplementedError

    def list_object_parts(
        self, bucket: str, object_name: str, upload_id: str,
        part_marker: int = 0, max_parts: int = 1000,
    ) -> list[PartInfo]:
        raise NotImplementedError

    def abort_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str
    ) -> None:
        raise NotImplementedError

    def complete_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str,
        parts: list[CompletePart],
    ) -> ObjectInfo:
        raise NotImplementedError

    # health / maintenance
    def heal_object(
        self, bucket: str, object_name: str, version_id: str = "",
        dry_run: bool = False,
    ):
        raise NotImplementedError

    def heal_bucket(self, bucket: str):
        raise NotImplementedError

    def storage_info(self) -> dict:
        raise NotImplementedError
