"""format.json: disk identity + cluster layout (cmd/format-erasure.go).

Every disk carries ``.sys/format.json`` recording the deployment ID, its
own UUID, and the full set layout (formatErasureV3, format-erasure.go:105).
At boot the format is created on fresh disks, quorum-loaded from used ones
(waitForFormatErasure, prepare-storage.go:350), disks are re-ordered to
their recorded set positions (fixFormatErasureV3 ordering semantics), and
swapped/foreign disks are detected by UUID mismatch.
"""

from __future__ import annotations

import dataclasses
import json
import uuid

from ..storage import errors as serrors

from ..utils.log import kv, logger

_log = logger("objectlayer")

FORMAT_FILE = "format.json"
FORMAT_BACKEND = "erasure-tpu"
DISTRIBUTION_ALGO = "CRCMOD"


@dataclasses.dataclass
class FormatErasure:
    """One disk's format document."""

    id: str  # deployment id (cluster-wide)
    this: str  # this disk's uuid
    sets: list[list[str]]  # disk uuids per set
    distribution_algo: str = DISTRIBUTION_ALGO
    version: str = "1"

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "version": self.version,
                "format": FORMAT_BACKEND,
                "id": self.id,
                "erasure": {
                    "version": "3",
                    "this": self.this,
                    "sets": self.sets,
                    "distributionAlgo": self.distribution_algo,
                },
            },
            indent=2,
        ).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "FormatErasure":
        try:
            doc = json.loads(raw)
            if doc.get("format") != FORMAT_BACKEND:
                raise ValueError(f"backend {doc.get('format')!r}")
            er = doc["erasure"]
            return cls(
                id=doc["id"],
                this=er["this"],
                sets=[list(s) for s in er["sets"]],
                distribution_algo=er.get(
                    "distributionAlgo", DISTRIBUTION_ALGO
                ),
                version=doc.get("version", "1"),
            )
        except (KeyError, ValueError, TypeError) as e:
            raise serrors.CorruptedFormat(str(e)) from e


def read_format(disk) -> "FormatErasure | None":
    """Load a disk's format; None when unformatted (fresh disk)."""
    try:
        raw = disk.read_all(".sys", FORMAT_FILE)
    except (serrors.FileNotFound, serrors.VolumeNotFound):
        return None
    return FormatErasure.from_bytes(raw)


def write_format(disk, fmt: FormatErasure) -> None:
    try:
        disk.make_vol(".sys")  # a wiped drive lost its staging volume
    except Exception as exc:
        _log.debug("staging vol re-create failed", extra=kv(err=str(exc)))
    disk.write_all(".sys", FORMAT_FILE, fmt.to_bytes())
    disk.set_disk_id(fmt.this)


def init_format_erasure(
    disks: list, set_count: int, drives_per_set: int
) -> FormatErasure:
    """Format a fresh cluster: mint UUIDs, stamp every disk
    (initFormatErasure, format-erasure.go:442)."""
    if len(disks) != set_count * drives_per_set:
        raise ValueError("disk count != sets * drives")
    deployment = str(uuid.uuid4())
    sets = [
        [str(uuid.uuid4()) for _ in range(drives_per_set)]
        for _ in range(set_count)
    ]
    ref = None
    for i, disk in enumerate(disks):
        s, d = divmod(i, drives_per_set)
        fmt = FormatErasure(
            id=deployment, this=sets[s][d], sets=sets
        )
        if disk is not None:
            write_format(disk, fmt)
        if ref is None:
            ref = fmt
    return ref


def wait_for_format(
    disks: list,
    set_count: int,
    drives_per_set: int,
    init_allowed: bool = True,
    timeout_s: float = 120.0,
    poll_s: float = 1.0,
) -> tuple["FormatErasure", list]:
    """Boot retry loop over possibly-remote disks
    (waitForFormatErasure, prepare-storage.go:350).

    Unreachable disks do NOT count as fresh - a fully fresh cluster is
    only initialized when every disk is reachable, and only by the node
    owning the first endpoint (init_allowed), so concurrent first boots
    cannot mint two deployments.  A formatted quorum proceeds with
    offline disks passed as None (healed later).
    """
    import time as _time

    deadline = _time.monotonic() + timeout_s
    last = "no probe yet"
    while True:
        fmts: list = []  # FormatErasure | None (fresh) | False (offline)
        for d in disks:
            try:
                fmts.append(read_format(d))
            except serrors.CorruptedFormat:
                raise
            except Exception:  # noqa: BLE001 - unreachable remote
                fmts.append(False)
        n_offline = sum(1 for f in fmts if f is False)
        live = [f for f in fmts if f]
        if not live:
            if n_offline == 0 and init_allowed:
                return load_or_init_format(
                    disks, set_count, drives_per_set
                )
            last = (
                f"fresh cluster: {n_offline} unreachable, "
                f"init_allowed={init_allowed}"
            )
        elif len(live) > len(disks) // 2:
            use = [
                None if f is False else d
                for d, f in zip(disks, fmts)
            ]
            return load_or_init_format(use, set_count, drives_per_set)
        else:
            last = f"format quorum {len(live)}/{len(disks)} not reached"
        if _time.monotonic() >= deadline:
            raise serrors.UnformattedDisk(
                f"timed out waiting for format: {last}"
            )
        _time.sleep(poll_s)


def load_or_init_format(
    disks: list, set_count: int, drives_per_set: int
) -> tuple[FormatErasure, list]:
    """Boot-time format resolution (connectLoadInitFormats semantics).

    Returns (reference_format, disks ordered by recorded set positions).
    Fresh disks among formatted ones are left in place unformatted (the
    heal path stamps them - monitorLocalDisksAndHeal analogue); a fully
    fresh cluster is initialized.
    """
    formats = [read_format(d) if d is not None else None for d in disks]
    live = [f for f in formats if f is not None]
    if not live:
        init_format_erasure(disks, set_count, drives_per_set)
        formats = [read_format(d) for d in disks]
        live = [f for f in formats if f is not None]
    # quorum reference format: majority deployment id
    by_id: dict[str, int] = {}
    for f in live:
        by_id[f.id] = by_id.get(f.id, 0) + 1
    ref_id = max(by_id, key=by_id.get)
    if by_id[ref_id] <= len(disks) // 2:
        raise serrors.CorruptedFormat(
            f"no format quorum: {by_id}"
        )
    ref = next(f for f in live if f.id == ref_id)
    if len(ref.sets) != set_count or len(ref.sets[0]) != drives_per_set:
        raise serrors.CorruptedFormat(
            f"layout mismatch: format says "
            f"{len(ref.sets)}x{len(ref.sets[0])}, "
            f"args say {set_count}x{drives_per_set}"
        )
    # order disks into their recorded positions
    pos: dict[str, int] = {}
    for s, set_ids in enumerate(ref.sets):
        for d, disk_id in enumerate(set_ids):
            pos[disk_id] = s * drives_per_set + d
    ordered: list = [None] * len(disks)
    fresh: list = []
    for disk, fmt in zip(disks, formats):
        if disk is None:
            continue
        if fmt is None:
            fresh.append(disk)
            continue
        if fmt.id != ref_id or fmt.this not in pos:
            raise serrors.InconsistentDisk(
                f"disk {disk.endpoint()} belongs to another deployment"
            )
        idx = pos[fmt.this]
        if ordered[idx] is not None:
            raise serrors.InconsistentDisk(
                f"duplicate disk uuid {fmt.this}"
            )
        ordered[idx] = disk
        disk.set_disk_id(fmt.this)
    # fresh disks fill remaining holes in argument order (to be healed)
    holes = [i for i, d in enumerate(ordered) if d is None]
    for disk, idx in zip(fresh, holes):
        fmt = FormatErasure(
            id=ref_id,
            this=ref.sets[idx // drives_per_set][idx % drives_per_set],
            sets=ref.sets,
        )
        write_format(disk, fmt)
        # flag for the fresh-disk monitor: this slot holds a replaced
        # drive whose set must be swept (healErasureSet) after boot
        disk._freshly_stamped = True
        ordered[idx] = disk
    return ref, ordered
