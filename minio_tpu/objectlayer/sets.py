"""ErasureSets: hash-routed collection of erasure sets (cmd/erasure-sets.go).

Data-parallel partitioning: S independent sets of N drives each; every
object deterministically lands in set crc32(key) % S (crcHashMod,
erasure-sets.go:560), so sets scale capacity and parallelism without
cross-set coordination.  Bucket operations fan out to every set; listings
merge lexically across sets (the lexicallySortedEntry merge,
erasure-sets.go:842).
"""

from __future__ import annotations

import binascii

from . import api
from .api import ListObjectsInfo, ObjectLayer
from .erasure_object import ErasureObjects

from ..utils.log import kv, logger

_log = logger("objectlayer")


def crc_hash_mod(key: str, cardinality: int) -> int:
    """Set index for an object key (crcHashMod, erasure-sets.go:576)."""
    if cardinality <= 0:
        return -1
    return binascii.crc32(key.encode()) % cardinality


class ErasureSets(ObjectLayer):
    def __init__(
        self,
        disks: list,
        set_count: int,
        drives_per_set: int,
        parity_blocks: "int | None" = None,
        block_size: "int | None" = None,
        nslock=None,
        format_ref=None,
    ):
        if len(disks) != set_count * drives_per_set:
            raise ValueError("disk count != sets * drives")
        from ..codec.erasure import BLOCK_SIZE_V1
        from ..dsync.namespace import NamespaceLock

        self.set_count = set_count
        self.drives_per_set = drives_per_set
        self.format_ref = format_ref  # FormatErasure (fresh-disk heal)
        nslock = nslock or NamespaceLock()
        self.sets: list[ErasureObjects] = [
            ErasureObjects(
                disks[i * drives_per_set : (i + 1) * drives_per_set],
                parity_blocks=parity_blocks,
                block_size=block_size or BLOCK_SIZE_V1,
                nslock=nslock,
            )
            for i in range(set_count)
        ]

    # -- routing ----------------------------------------------------------

    def set_for(self, object_name: str) -> ErasureObjects:
        return self.sets[crc_hash_mod(object_name, self.set_count)]

    # -- buckets (fan out to all sets) ------------------------------------

    def make_bucket(self, bucket: str) -> None:
        # one bucket lock over the whole fan-out so a concurrent
        # delete can't interleave between sets (erasure-sets.go:604
        # MakeBucketLocation); the per-set internals are unlocked
        # because all sets share this nslock and it isn't reentrant
        api.check_bucket_name(bucket)
        with self.sets[0].nslock.write(bucket, ""):
            made = []
            try:
                for s in self.sets:
                    s._make_bucket(bucket)
                    made.append(s)
            except Exception:
                for s in made:  # undo partial creation (undoMakeBucket)
                    try:
                        s._delete_bucket(bucket, force=True)
                    except Exception as exc:
                        _log.debug("undo bucket create failed", extra=kv(err=str(exc)))
                raise

    def get_bucket_info(self, bucket: str):
        return self.sets[0].get_bucket_info(bucket)

    def list_buckets(self):
        return self.sets[0].list_buckets()

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        with self.sets[0].nslock.write(bucket, ""):
            # validate emptiness across all sets first when not forcing
            if not force:
                for s in self.sets:
                    if s.list_objects(bucket, max_keys=1).objects:
                        raise api.BucketNotEmpty(bucket)
            for s in self.sets:
                try:
                    s._delete_bucket(bucket, force=True)
                except api.BucketNotFound:
                    pass

    # -- objects (route by key) -------------------------------------------

    def put_object(self, bucket, object_name, reader, size=-1, metadata=None,
                   versioned=False, compress=None, sse=None):
        return self.set_for(object_name).put_object(
            bucket, object_name, reader, size, metadata, versioned,
            compress, sse,
        )

    def get_object(self, bucket, object_name, writer, offset=0, length=-1,
                   version_id="", sse=None):
        return self.set_for(object_name).get_object(
            bucket, object_name, writer, offset, length, version_id,
            sse,
        )

    def get_object_info(self, bucket, object_name, version_id=""):
        return self.set_for(object_name).get_object_info(
            bucket, object_name, version_id
        )

    def device_scan_source(self, bucket, object_name):
        return self.set_for(object_name).device_scan_source(
            bucket, object_name
        )

    def update_object_meta(self, bucket, object_name, updates,
                           version_id=""):
        return self.set_for(object_name).update_object_meta(
            bucket, object_name, updates, version_id
        )

    def delete_object(self, bucket, object_name, version_id="",
                      versioned=False, version_suspended=False):
        return self.set_for(object_name).delete_object(
            bucket, object_name, version_id, versioned, version_suspended
        )

    def copy_object(self, src_bucket, src_object, dst_bucket, dst_object,
                    metadata=None, versioned=False, sse_src=None,
                    sse=None):
        src_set = self.set_for(src_object)
        dst_set = self.set_for(dst_object)
        if src_set is dst_set:
            return src_set.copy_object(
                src_bucket, src_object, dst_bucket, dst_object, metadata,
                versioned, sse_src, sse,
            )
        from ..utils.pipe import streaming_copy

        info = src_set.get_object_info(src_bucket, src_object)
        meta = api.prepare_copy_meta(info, metadata)
        return streaming_copy(
            lambda sink: src_set.get_object(
                src_bucket, src_object, sink, sse=sse_src
            ),
            lambda source: dst_set.put_object(
                dst_bucket, dst_object, source, info.size, meta,
                versioned=versioned, sse=sse,
            ),
        )

    def heal_object(self, bucket, object_name, version_id="", dry_run=False):
        return self.set_for(object_name).heal_object(
            bucket, object_name, version_id, dry_run
        )

    def probe_object_health(self, bucket, object_name, version_id=""):
        return self.set_for(object_name).probe_object_health(
            bucket, object_name, version_id
        )

    def heal_bucket(self, bucket, dry_run=False):
        """Tolerant fan-out: one bad set must not block healing the
        rest (erasure-healing.go healBucket sweeps every set)."""
        healed = []
        found = False
        for si, s in enumerate(self.sets):
            try:
                r = s.heal_bucket(bucket, dry_run)
                found = True
                healed.extend((si, i) for i in r["healed"])
            except api.BucketNotFound:
                continue
        if not found:
            raise api.BucketNotFound(bucket)
        return {"bucket": bucket, "healed": healed, "dry_run": dry_run}

    # -- listing (merge across sets) --------------------------------------

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000) -> ListObjectsInfo:
        results = [
            s.list_objects(bucket, prefix, marker, delimiter, max_keys)
            for s in self.sets
        ]
        return merge_list_results(results, max_keys)

    def has_object_versions(self, bucket, object_name) -> bool:
        return self.set_for(object_name).has_object_versions(
            bucket, object_name
        )

    def list_object_versions(self, bucket, prefix="", key_marker="",
                             version_id_marker="", delimiter="",
                             max_keys=1000):
        results = [
            s.list_object_versions(
                bucket, prefix, key_marker, version_id_marker,
                delimiter, max_keys,
            )
            for s in self.sets
        ]
        return merge_version_results(results, max_keys)

    # -- multipart (route by key) -----------------------------------------

    def new_multipart_upload(self, bucket, object_name, metadata=None,
                             sse=None):
        return self.set_for(object_name).new_multipart_upload(
            bucket, object_name, metadata, sse
        )

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        reader, size=-1, sse=None):
        return self.set_for(object_name).put_object_part(
            bucket, object_name, upload_id, part_number, reader, size,
            sse,
        )

    def list_object_parts(self, bucket, object_name, upload_id,
                          part_marker=0, max_parts=1000):
        return self.set_for(object_name).list_object_parts(
            bucket, object_name, upload_id, part_marker, max_parts
        )

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for s in self.sets:
            out.extend(s.list_multipart_uploads(bucket, prefix))
        out.sort(key=lambda u: (u.object, u.upload_id))
        return out

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self.set_for(object_name).abort_multipart_upload(
            bucket, object_name, upload_id
        )

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts, versioned=False):
        return self.set_for(object_name).complete_multipart_upload(
            bucket, object_name, upload_id, parts, versioned
        )

    def storage_info(self) -> dict:
        infos = [s.storage_info() for s in self.sets]
        return {
            "sets": infos,
            "disks": sum(i["disks"] for i in infos),
            "online": sum(i["online"] for i in infos),
            "offline": sum(i["offline"] for i in infos),
        }


def _truncation_boundary(results: list, marker_attr: str) -> "str | None":
    """Lowest last-emitted key among truncated inputs.  A merged page
    must not emit entries PAST a truncated input's boundary: that input
    has unreturned keys below them, and a resume marker beyond the
    boundary would skip those keys forever (review finding r3)."""
    bounds = [
        getattr(r, marker_attr)
        for r in results
        if r.is_truncated and getattr(r, marker_attr)
    ]
    return min(bounds) if bounds else None


def merge_version_results(results: list, max_keys: int):
    """Version-aware lexical merge across sets/zones: entries key on
    (object name, newest-first position) - each key's versions stay
    contiguous and ordered, truncation re-applied at max_keys and at
    the lowest truncated input's boundary."""
    per_key: "dict[str, list]" = {}
    prefixes: set[str] = set()
    for r in results:
        prefixes.update(r.prefixes)
        for oi in r.versions:
            per_key.setdefault(oi.name, []).append(oi)
    boundary = _truncation_boundary(results, "next_key_marker")
    out = api.ListObjectVersionsInfo()
    entries = sorted(
        [(name, "o") for name in per_key]
        + [(p, "p") for p in prefixes]
    )
    count = 0
    for name, kind in entries:
        if boundary is not None and name > boundary:
            out.is_truncated = True
            return out
        if kind == "p":
            if count >= max_keys:
                out.is_truncated = True
                return out
            out.prefixes.append(name)
            out.next_key_marker = name
            out.next_version_id_marker = ""
            count += 1
            continue
        versions = sorted(
            per_key[name], key=lambda o: -o.mod_time_ns
        )
        for oi in versions:
            if count >= max_keys:
                out.is_truncated = True
                return out
            out.versions.append(oi)
            count += 1
            out.next_key_marker = name
            out.next_version_id_marker = oi.version_id or "null"
    out.is_truncated = boundary is not None
    return out


def merge_list_results(
    results: list[ListObjectsInfo], max_keys: int
) -> ListObjectsInfo:
    """Lexical merge of per-set/per-zone listings, re-truncated to
    max_keys and to the lowest truncated input's boundary
    (lexicallySortedEntry, erasure-sets.go:842)."""
    objects = {o.name: o for r in results for o in r.objects}
    prefixes = {p for r in results for p in r.prefixes}
    boundary = _truncation_boundary(results, "next_marker")
    entries = sorted(
        [(name, "o") for name in objects] + [(p, "p") for p in prefixes]
    )
    out = ListObjectsInfo()
    last = ""
    for name, kind in entries:
        if boundary is not None and name > boundary:
            out.is_truncated = True
            out.next_marker = last
            return out
        if len(out.objects) + len(out.prefixes) >= max_keys:
            out.is_truncated = True
            out.next_marker = last
            return out
        if kind == "o":
            out.objects.append(objects[name])
        else:
            out.prefixes.append(name)
        last = name
    out.is_truncated = boundary is not None
    out.next_marker = last if out.is_truncated else ""
    return out
