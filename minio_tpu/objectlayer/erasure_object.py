"""ErasureObjects: one erasure set of N disks (cmd/erasure-object.go).

The core ObjectLayer: objects are striped across all disks of the set with
parity, committed via per-disk staging + atomic rename, read back through
metadata quorum + batched TPU decode.  Distribution, quorum and staging
semantics follow the reference call stack (SURVEY.md section 3.2/3.3);
the codec work itself is the batched device pass in codec/erasure.py.
"""

from __future__ import annotations

import os
import time
import uuid

from .. import cache as rcache
from ..codec import compress as compmod, erasure as ecodec, sse as ssemod
from ..codec.erasure import Erasure, QuorumError
from ..parallel import iopool
from ..parallel.iopool import tag_disk_stream
from ..storage import errors as serrors, health as disk_health
from ..storage.meta import (
    ErasureInfo,
    FileInfo,
    ObjectPartInfo,
    new_version_id,
    now_ns,
)
from ..utils.hashreader import HashReader
from . import api
from .api import (
    BucketExists,
    BucketInfo,
    BucketNotEmpty,
    BucketNotFound,
    ListObjectsInfo,
    ObjectInfo,
    ObjectLayer,
    ObjectNotFound,
    ReadQuorumError,
    WriteQuorumError,
    check_bucket_name,
    check_object_name,
)
from .metadata import (
    find_fileinfo_in_quorum,
    hash_order,
    object_quorum_from_meta,
    read_all_fileinfo,
    reduce_errs,
    shuffle_disks,
)

SYS_VOL = ".sys"


def _parity_ack_mode() -> str:
    """MINIO_TPU_PARITY_ACK = settle|early (default settle).

    settle: PUT returns only after every shard (parity included) is
    written, closed and renamed — the fully-deterministic path.
    early: PUT acks at DATA-shard write quorum; parity writes, closes
    and renames drain in a background ParityBand whose failures are
    heal-flagged through the MRF hook (quorum-early parity drain)."""
    v = os.environ.get("MINIO_TPU_PARITY_ACK", "settle").lower()
    return v if v in ("settle", "early") else "settle"


from .erasure_multipart import MultipartMixin

from ..utils.log import kv, logger

_log = logger("objectlayer")


class ErasureObjects(MultipartMixin, ObjectLayer):
    """One erasure set over ``disks`` (offline entries are None)."""

    def __init__(
        self,
        disks: list,
        parity_blocks: "int | None" = None,
        block_size: int = ecodec.BLOCK_SIZE_V1,
        nslock=None,
        min_part_size: "int | None" = None,
    ):
        if len(disks) < 2:
            raise ValueError("erasure set needs >= 2 disks")
        from ..storage import metered

        # per-disk API telemetry rides on every erasure set; wrap() is
        # idempotent, so construction sites that already stacked
        # DiskIDCheck(MeteredDisk(...)) pass through untouched
        self.disks = [metered.wrap(d) for d in disks]
        n = len(disks)
        self.parity_blocks = (
            parity_blocks if parity_blocks is not None else n // 2
        )
        self.data_blocks = n - self.parity_blocks
        if self.parity_blocks > n // 2:
            raise ValueError("parity cannot exceed half the disks")
        self.block_size = block_size
        if min_part_size is None:
            from .erasure_multipart import MIN_PART_SIZE

            min_part_size = MIN_PART_SIZE
        self.min_part_size = min_part_size
        from ..dsync.namespace import NamespaceLock

        self.nslock = nslock or NamespaceLock()
        # MRF seam (addPartial, erasure-object.go:999): called with
        # (bucket, object) when a write misses disks or a read detects
        # bitrot; wired to the background heal queue by the server
        self.heal_hook = None

    # ------------------------------------------------------------------
    # quorums (erasure-object.go:593-596)
    # ------------------------------------------------------------------

    @property
    def read_quorum(self) -> int:
        return self.data_blocks

    @property
    def write_quorum(self) -> int:
        wq = self.data_blocks
        if self.data_blocks == self.parity_blocks:
            wq += 1
        return wq

    def _online_disks(self) -> list:
        """Live disks, with breaker-tripped ones masked to None.

        This is the single choke point every path (GET preference,
        PUT fan-out ``writers[s]=None`` bookkeeping, metadata quorums,
        heal) derives its disk list from, so an open circuit breaker
        (storage/health.py) makes the disk vanish uniformly — zero
        metered calls reach it — until its backoff admits one probe.
        """
        return [
            d
            if (
                d is not None
                and not disk_health.should_skip(d)
                and d.is_online()
            )
            else None
            for d in self.disks
        ]

    # ------------------------------------------------------------------
    # buckets (cmd/erasure-bucket.go)
    # ------------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        # serialize against concurrent bucket create/delete on this
        # node: the bucket namespace key is "<bucket>/", disjoint from
        # every object key (erasure-sets.go:604 MakeBucketLocation
        # holds the per-bucket lock for the same reason)
        check_bucket_name(bucket)
        with self.nslock.write(bucket, ""):
            self._make_bucket(bucket)

    def _make_bucket(self, bucket: str) -> None:
        errs = []
        for d in self._online_disks():
            if d is None:
                errs.append(serrors.DiskNotFound("offline"))
                continue
            try:
                d.make_vol(bucket)
                errs.append(None)
            except serrors.VolumeExists as e:
                errs.append(e)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        if any(isinstance(e, serrors.VolumeExists) for e in errs):
            raise BucketExists(bucket)
        reduce_errs(errs, self.write_quorum, WriteQuorumError)

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        check_bucket_name(bucket)
        for d in self._online_disks():
            if d is None:
                continue
            try:
                vi = d.stat_vol(bucket)
                return BucketInfo(vi.name, vi.created_ns)
            except serrors.VolumeNotFound:
                raise BucketNotFound(bucket) from None
            except Exception:  # noqa: BLE001
                continue
        raise BucketNotFound(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        for d in self._online_disks():
            if d is None:
                continue
            try:
                return [
                    BucketInfo(v.name, v.created_ns)
                    for v in d.list_vols()
                ]
            except Exception:  # noqa: BLE001
                continue
        return []

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        with self.nslock.write(bucket, ""):
            self._delete_bucket(bucket, force)

    def _delete_bucket(self, bucket: str, force: bool = False) -> None:
        self.get_bucket_info(bucket)  # existence check
        errs = []
        nonempty = False
        for d in self._online_disks():
            if d is None:
                errs.append(serrors.DiskNotFound("offline"))
                continue
            try:
                d.delete_vol(bucket, force=force)
                errs.append(None)
            except serrors.VolumeNotEmpty as e:
                nonempty = True
                errs.append(e)
            except (serrors.VolumeNotFound, FileNotFoundError):
                # already gone (another node won the delete): a
                # bucket-level success, never a raw ENOENT in quorum
                errs.append(None)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        if nonempty:
            raise BucketNotEmpty(bucket)
        reduce_errs(errs, self.write_quorum, WriteQuorumError)

    def _require_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    # ------------------------------------------------------------------
    # put (erasure-object.go:570-765)
    # ------------------------------------------------------------------

    def put_object(
        self, bucket, object_name, reader, size=-1, metadata=None,
        versioned=False, compress=None, sse=None,
    ) -> ObjectInfo:
        check_object_name(object_name)
        self._require_bucket(bucket)
        with self.nslock.write(bucket, object_name):
            return self._put_object(
                bucket, object_name, reader, size, metadata, versioned,
                compress, sse,
            )

    def _old_null_data_dir(self, bucket, object_name) -> str:
        """Data dir of the existing *null* version, if any - the only
        version an unversioned overwrite replaces (and so the only data
        dir safe to reap; real versions keep theirs)."""
        try:
            fi, _ = self._read_quorum_fileinfo(
                bucket, object_name, "null"
            )
            return fi.data_dir
        except Exception:  # noqa: BLE001
            return ""

    def _put_object(
        self, bucket, object_name, reader, size, metadata,
        versioned=False, compress=None, sse=None,
    ) -> ObjectInfo:
        k, m, n = self.data_blocks, self.parity_blocks, len(self.disks)
        er = Erasure(k, m, self.block_size)
        hreader = (
            reader if isinstance(reader, HashReader) else HashReader(reader, size)
        )
        # transparent compression: the decision lives HERE so every
        # write path (PUT, POST-policy, CopyObject re-encode) shares it;
        # the codec sees STORED (deflate) bytes while the HashReader
        # keeps hashing the client payload so the ETag stays the
        # original MD5 (object-api-utils.go:434 seam)
        if compress is None:
            compress = compmod.should_compress(
                object_name,
                (metadata or {}).get("content-type", ""),
                size,
            )
        src = hreader
        if compress:
            src = compmod.CompressReader(hreader)
        # SSE sits OUTSIDE compression (encrypting first would destroy
        # compressibility): stored = encrypt(compress(plaintext))
        sse_meta: dict = {}
        if sse is not None:
            oek = ssemod.new_object_key()
            nb = ssemod.new_nonce_base()
            sse_meta = self._seal_sse_meta(
                sse, oek, nb, f"{bucket}/{object_name}",
                part_numbers=[1],
            )
            src = ssemod.EncryptReader(src, oek, nb)
        distribution = hash_order(f"{bucket}/{object_name}", n)
        disks = shuffle_disks(self._online_disks(), distribution)

        data_dir = uuid.uuid4().hex
        # mutation seam: every prior generation's cached groups die
        # (here and on every peer) BEFORE the new generation encodes,
        # so the PUT-side populate below never races its own stale keys
        self._invalidate_read_cache(bucket, object_name)
        rctx = rcache.context_for(bucket, object_name, data_dir, 1)
        tmp_ids = [uuid.uuid4().hex for _ in range(n)]
        writers: list = []
        for i, d in enumerate(disks):
            if d is None:
                writers.append(None)
                continue
            try:
                writers.append(
                    tag_disk_stream(
                        d.create_file(
                            SYS_VOL,
                            f"tmp/{tmp_ids[i]}/{data_dir}/part.1",
                        ),
                        d,
                    )
                )
            except Exception:  # noqa: BLE001
                writers.append(None)

        # quorum-early commit: the band adopts parity stragglers at
        # encode return, then carries parity close/rename past the ack
        band = (
            iopool.ParityBand()
            if _parity_ack_mode() == "early" and m > 0
            else None
        )
        try:
            total = er.encode(
                src, writers, self.write_quorum, parity_band=band,
                cache_ctx=rctx,
            )
        except QuorumError as e:
            self._invalidate_read_cache(bucket, object_name)
            # close writers FIRST: streaming remote writers own sender
            # threads that must terminate before staging is reaped
            for w in writers:
                if w is not None:
                    try:
                        w.close()
                    except Exception as exc:
                        _log.debug("shard writer close failed", extra=kv(err=str(exc)))
            self._cleanup_tmp(disks, tmp_ids)
            raise WriteQuorumError(str(e)) from e
        if band is not None and not band.adopted:
            band = None  # encode fell back to the legacy settle path
        # close (flush + fsync) shard files concurrently, one job per
        # disk queue: the commit pays the slowest disk's fsync, not the
        # sum over n disks.  Early mode closes only the DATA shards
        # here; parity closes ride the band, ordered after that disk's
        # writes by its queue
        close_inline = [
            w
            for s, w in enumerate(writers)
            if w is not None and (band is None or s < k)
        ]
        if band is not None:
            for s, w in enumerate(writers):
                if s >= k and w is not None:
                    band.submit(s, iopool.stream_io_key(w), w.close)
        for err in iopool.fanout(
            [(iopool.stream_io_key(w), w.close) for w in close_inline]
        ):
            if err is not None and not isinstance(err, OSError):
                raise err

        mod_time = now_ns()
        etag = hreader.etag()
        actual_size = hreader.bytes_read
        meta = dict(metadata or {})
        meta.setdefault("etag", etag)
        if compress:
            meta[compmod.META_COMPRESSION] = compmod.ALGORITHM
        if sse_meta:
            meta.update(sse_meta)
        if compress or sse_meta:
            meta[compmod.META_ACTUAL_SIZE] = str(actual_size)
        # versioned PUT mints a fresh id and preserves prior versions;
        # unversioned/suspended PUT overwrites the null version only
        # (xl-storage-format-v2 version journal semantics)
        version_id = new_version_id() if versioned else ""
        old_data_dir = (
            "" if versioned else self._old_null_data_dir(bucket, object_name)
        )

        # rename_data commits the version journal with its own fsync
        # per disk: fan the commits out on the disk queues and gather
        # per-slot errors in order.  Early mode renames only the data
        # shards before acking; parity renames ride the band (same
        # per-disk key as that disk's close, so ordering holds) and
        # their slot errors stay optimistically None until settle
        rename_ops = []
        errs: list = [None] * len(disks)
        for i, d in enumerate(disks):
            if d is None or writers[i] is None:
                errs[i] = serrors.DiskNotFound("offline")
                continue
            fi = FileInfo(
                volume=bucket,
                name=object_name,
                version_id=version_id,
                data_dir=data_dir,
                size=total,
                mod_time_ns=mod_time,
                metadata=meta,
                parts=[ObjectPartInfo(1, total, actual_size)],
                erasure=ErasureInfo(
                    data_blocks=k,
                    parity_blocks=m,
                    block_size=self.block_size,
                    index=i + 1,
                    distribution=distribution,
                ),
            )
            fn = lambda d=d, fi=fi, tmp=tmp_ids[i]: d.rename_data(  # noqa: E731
                SYS_VOL, f"tmp/{tmp}", fi, bucket, object_name
            )
            if band is not None and i >= k:
                band.submit(i, iopool.stream_io_key(writers[i]), fn)
                continue
            rename_ops.append((i, iopool.disk_io_key(d) or f"disk-{i}", fn))
        for (i, _k, _f), err in zip(
            rename_ops,
            iopool.fanout([(key, fn) for _i, key, fn in rename_ops]),
        ):
            errs[i] = err
        try:
            reduce_errs(errs, self.write_quorum, WriteQuorumError)
        except WriteQuorumError:
            self._invalidate_read_cache(bucket, object_name)
            self._cleanup_tmp(disks, tmp_ids)
            raise
        # MRF: quorum met but some disks missed the write - queue the
        # object for immediate background heal (addPartial)
        if self.heal_hook is not None and any(
            e is not None for e in errs
        ):
            try:
                self.heal_hook(bucket, object_name)
            except Exception as exc:
                _log.debug("partial-write heal hook failed", extra=kv(err=str(exc)))
        if band is not None:
            # settle the parity plane in the background; anything that
            # fails past this ack is heal-flagged through the MRF hook
            hook = self.heal_hook

            def _on_settled(b, _bucket=bucket, _obj=object_name):
                if b.heal_required and hook is not None:
                    try:
                        hook(_bucket, _obj)
                    except Exception as exc:
                        _log.debug(
                            "parity settle heal hook failed",
                            extra=kv(err=str(exc)),
                        )

            band.finish(on_done=_on_settled)
        # overwrite cleanup: drop the replaced data dir (best effort)
        if old_data_dir and old_data_dir != data_dir:
            for d in disks:
                if d is None:
                    continue
                try:
                    d.delete_file(
                        bucket,
                        f"{object_name}/{old_data_dir}",
                        recursive=True,
                    )
                except Exception as exc:
                    _log.debug("replaced data dir cleanup failed", extra=kv(err=str(exc)))
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=actual_size,  # clients always see the original size
            mod_time_ns=mod_time,
            etag=etag,
            content_type=meta.get("content-type", ""),
            version_id=version_id,
            user_defined=meta,
        )

    @staticmethod
    def _invalidate_read_cache(bucket, object_name) -> None:
        """The cache-invalidation seam (MTPU110): every path that
        mutates object data — PUT, overwrite, heal, delete, multipart
        commit — flows through here so the tiered read cache (local
        AND every peer's) never serves a dead generation."""
        try:
            rcache.invalidate_object(bucket, object_name)
        except Exception as exc:  # noqa: BLE001 - never fail the write
            _log.debug(
                "read-cache invalidate failed", extra=kv(err=str(exc))
            )

    def _cleanup_tmp(self, disks, tmp_ids) -> None:
        for i, d in enumerate(disks):
            if d is None:
                continue
            try:
                d.delete_file(SYS_VOL, f"tmp/{tmp_ids[i]}", recursive=True)
            except Exception as exc:
                _log.debug("tmp staging cleanup failed", extra=kv(err=str(exc)))

    # ------------------------------------------------------------------
    # get (erasure-object.go:141-331)
    # ------------------------------------------------------------------

    def _read_quorum_fileinfo(
        self, bucket, object_name, version_id=""
    ) -> tuple[FileInfo, list]:
        disks = self._online_disks()
        fis, errs = read_all_fileinfo(
            disks, bucket, object_name, version_id
        )
        not_found = sum(
            isinstance(e, (serrors.FileNotFound, serrors.VersionNotFound))
            for e in errs
        )
        if not_found > len(self.disks) - self.read_quorum:
            if version_id and any(
                isinstance(e, serrors.VersionNotFound) for e in errs
            ):
                raise api.VersionNotFound(f"{bucket}/{object_name}")
            raise ObjectNotFound(f"{bucket}/{object_name}")
        fi = find_fileinfo_in_quorum(fis, self.read_quorum)
        return fi, fis

    def get_object_info(
        self, bucket, object_name, version_id=""
    ) -> ObjectInfo:
        check_object_name(object_name)
        self._require_bucket(bucket)
        fi, _ = self._read_quorum_fileinfo(bucket, object_name, version_id)
        if fi.deleted:
            raise ObjectNotFound(f"{bucket}/{object_name}")
        return self._to_object_info(bucket, object_name, fi)

    def update_object_meta(
        self, bucket, object_name, updates: dict, version_id=""
    ) -> ObjectInfo:
        """Merge metadata updates into an existing version on every disk
        holding it - the PutObjectTags / PutObjectRetention seam
        (erasure-object.go PutObjectTags -> disk.UpdateMetadata).

        A key mapped to None is removed; other keys are set.  The quorum
        version is located first, then each agreeing disk rewrites its
        own FileInfo (preserving its per-disk erasure index)."""
        check_object_name(object_name)
        self._require_bucket(bucket)
        with self.nslock.write(bucket, object_name):
            disks = self._online_disks()
            fis, _errs = read_all_fileinfo(
                disks, bucket, object_name, version_id
            )
            not_found = sum(
                isinstance(e, (serrors.FileNotFound, serrors.VersionNotFound))
                for e in _errs
            )
            if not_found > len(self.disks) - self.read_quorum:
                if version_id and any(
                    isinstance(e, serrors.VersionNotFound) for e in _errs
                ):
                    raise api.VersionNotFound(f"{bucket}/{object_name}")
                raise ObjectNotFound(f"{bucket}/{object_name}")
            fi = find_fileinfo_in_quorum(fis, self.read_quorum)
            if fi.deleted:
                raise ObjectNotFound(f"{bucket}/{object_name}")
            merged = dict(fi.metadata)
            for k, v in updates.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = v
            qkey = (fi.mod_time_ns, fi.data_dir, fi.deleted)
            errs = []
            for i, d in enumerate(disks):
                dfi = fis[i]
                if (
                    d is None
                    or dfi is None
                    or (dfi.mod_time_ns, dfi.data_dir, dfi.deleted) != qkey
                ):
                    errs.append(serrors.DiskNotFound("offline"))
                    continue
                dfi.metadata = dict(merged)
                try:
                    d.update_metadata(bucket, object_name, dfi)
                    errs.append(None)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            reduce_errs(errs, self.write_quorum, WriteQuorumError)
            self._invalidate_read_cache(bucket, object_name)
            fi.metadata = merged
            return self._to_object_info(bucket, object_name, fi)

    @staticmethod
    def _seal_sse_meta(sse, oek: bytes, nonce_base: bytes, aad: str,
                       part_numbers: "list[int] | None" = None) -> dict:
        """Metadata carrying the sealed object key (SealObjectKey)."""
        import base64

        out = {
            ssemod.META_SSE_NONCE: base64.b64encode(nonce_base).decode(),
        }
        if part_numbers:
            out[ssemod.META_SSE_PARTS] = ",".join(
                str(n) for n in part_numbers
            )
        if sse.mode == "C":
            if not sse.key or len(sse.key) != 32:
                raise ssemod.SSEError("SSE-C key must be 32 bytes")
            sealed = ssemod.seal_key(sse.key, oek, aad)
            out.update(
                {
                    ssemod.META_SSE: "C",
                    ssemod.META_SSE_SEALED_KEY: base64.b64encode(
                        sealed
                    ).decode(),
                    ssemod.META_SSE_KEY_MD5: ssemod.key_md5_b64(sse.key),
                }
            )
            return out
        # SSE-S3 key hierarchy (cmd/crypto/kms.go): the KMS mints a
        # per-object data key; the OEK seals under the data key and
        # only the KMS-sealed data key is persisted, so an external
        # KMS (KES) never sees object keys and master rotation never
        # re-touches objects
        from ..codec import kms as kmsmod

        kms = kmsmod.get_kms()
        if kms is None:
            raise ssemod.SSEError(
                "SSE-S3 requires a KMS (MINIO_TPU_KMS_MASTER_KEY or "
                "MINIO_TPU_KMS_KES_ENDPOINT)"
            )
        kid = kms.default_key_id()
        try:
            dk, sealed_dk = kms.generate_key(kid, {"path": aad})
        except kmsmod.KMSError as e:
            raise ssemod.SSEError(str(e)) from None
        sealed = ssemod.seal_key(dk, oek, aad)
        out.update(
            {
                ssemod.META_SSE: "S3",
                ssemod.META_SSE_SEALED_KEY: base64.b64encode(
                    sealed
                ).decode(),
                ssemod.META_SSE_KMS_ID: kid,
                ssemod.META_SSE_KMS_SEALED_DK: base64.b64encode(
                    sealed_dk
                ).decode(),
            }
        )
        return out

    @staticmethod
    def _unseal_oek(fi_meta: dict, sse, aad: str) -> "tuple[bytes, bytes]":
        """(object key, nonce base) for a stored encrypted object;
        raises SSEError on a missing or mismatched key."""
        import base64

        mode = fi_meta.get(ssemod.META_SSE)
        sealed = base64.b64decode(
            fi_meta.get(ssemod.META_SSE_SEALED_KEY, "")
        )
        if mode == "C":
            if sse is None or not sse.key:
                raise ssemod.SSEError(
                    "object is encrypted with a customer key; the key "
                    "must be provided"
                )
            if ssemod.key_md5_b64(sse.key) != fi_meta.get(
                ssemod.META_SSE_KEY_MD5
            ):
                raise ssemod.SSEError(
                    "provided SSE-C key does not match the object key"
                )
            kek = sse.key
        elif fi_meta.get(ssemod.META_SSE_KMS_SEALED_DK):
            from ..codec import kms as kmsmod

            kms = kmsmod.get_kms()
            if kms is None:
                raise ssemod.SSEError(
                    "object is KMS-encrypted but no KMS is configured"
                )
            try:
                kek = kms.unseal_key(
                    fi_meta.get(ssemod.META_SSE_KMS_ID, ""),
                    base64.b64decode(
                        fi_meta[ssemod.META_SSE_KMS_SEALED_DK]
                    ),
                    {"path": aad},
                )
            except kmsmod.KMSError as e:
                raise ssemod.SSEError(str(e)) from None
        else:
            # legacy layout: OEK sealed directly under the local
            # master key (pre data-key objects)
            _, kek = ssemod.master_key()
        oek = ssemod.unseal_key(kek, sealed, aad)
        nb = base64.b64decode(fi_meta.get(ssemod.META_SSE_NONCE, ""))
        return oek, nb

    @staticmethod
    def _to_object_info(bucket, object_name, fi: FileInfo) -> ObjectInfo:
        size = fi.size
        if fi.metadata.get(compmod.META_COMPRESSION) or fi.metadata.get(
            ssemod.META_SSE
        ):
            # clients see the original payload size, not stored bytes
            size = int(fi.metadata.get(compmod.META_ACTUAL_SIZE, size))
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=size,
            mod_time_ns=fi.mod_time_ns,
            etag=fi.metadata.get("etag", ""),
            content_type=fi.metadata.get("content-type", ""),
            version_id=fi.version_id,
            delete_marker=fi.deleted,
            user_defined=dict(fi.metadata),
            parts=list(fi.parts),
        )

    def get_object(
        self, bucket, object_name, writer, offset=0, length=-1,
        version_id="", sse=None,
    ) -> ObjectInfo:
        check_object_name(object_name)
        self._require_bucket(bucket)
        with self.nslock.read(bucket, object_name):
            # latest-version GETs consult the read cache's FileInfo
            # side-car before fanning xl.meta reads across the set; the
            # namespace lock orders the store against any mutation's
            # post-commit invalidate, so a cached FileInfo is never
            # staler than what an uncached quorum read would return
            rc = rcache.read_cache() if not version_id else None
            fi = rc.meta_lookup(bucket, object_name) if rc else None
            if fi is None:
                fi, _ = self._read_quorum_fileinfo(
                    bucket, object_name, version_id
                )
                if rc is not None and not fi.deleted:
                    rc.meta_store(bucket, object_name, fi)
            if fi.deleted:
                raise ObjectNotFound(f"{bucket}/{object_name}")
            compressed = bool(fi.metadata.get(compmod.META_COMPRESSION))
            encrypted = bool(fi.metadata.get(ssemod.META_SSE))
            transformed = compressed or encrypted
            logical_size = fi.size
            if transformed:
                logical_size = int(
                    fi.metadata.get(compmod.META_ACTUAL_SIZE, fi.size)
                )
            if length < 0:
                length = logical_size - offset
            if offset < 0 or offset + length > logical_size:
                raise api.InvalidRange(
                    f"range {offset}+{length} of {logical_size}"
                )
            oek = nonce_base = None
            orig_part_nums: "list[int]" = []
            if encrypted:
                oek, nonce_base = self._unseal_oek(
                    fi.metadata, sse, f"{bucket}/{object_name}"
                )
                raw_nums = fi.metadata.get(ssemod.META_SSE_PARTS, "")
                orig_part_nums = [
                    int(x) for x in raw_nums.split(",") if x
                ] or [p.number for p in fi.parts]
            er = Erasure(
                fi.erasure.data_blocks,
                fi.erasure.parity_blocks,
                fi.erasure.block_size,
            )
            disks = shuffle_disks(
                self._online_disks(), fi.erasure.distribution
            )
            heal_required = False
            # stream the parts covering [offset, offset+length).  Ranges
            # address LOGICAL bytes; each transformed part is an
            # independent stream (deflate and/or DARE packages), so
            # overlapping parts are decoded whole into a skipping
            # decrypt/decompress chain (decompress-and-skip,
            # object-api-utils.go:686; DecryptBlocksReader) while plain
            # parts decode just the overlapping slice.
            part_off = 0
            remaining = length
            cur = offset
            for pi, part in enumerate(fi.parts):
                span = part.actual_size if transformed else part.size
                part_start = part_off
                part_end = part_off + span
                part_off = part_end
                if remaining <= 0:
                    break
                if part_end <= cur:
                    continue
                in_off = cur - part_start
                in_len = min(span - in_off, remaining)
                if transformed:
                    dec_off, dec_len = 0, part.size
                    if compressed:
                        sink = compmod.DecompressWriter(
                            writer, in_off, in_len
                        )
                    else:
                        sink = writer
                    if encrypted:
                        pn = (
                            orig_part_nums[pi]
                            if pi < len(orig_part_nums)
                            else part.number
                        )
                        sink = ssemod.DecryptWriter(
                            sink,
                            oek,
                            ssemod.part_nonce_base(nonce_base, pn),
                            0 if compressed else in_off,
                            -1 if compressed else in_len,
                        )
                else:
                    sink = writer
                    dec_off, dec_len = in_off, in_len
                rctx = rcache.context_for(
                    bucket, object_name, fi.data_dir, part.number
                )
                opened: list = []
                if rctx is None:
                    # cache off: today's eager-open path, bit for bit
                    readers = self._part_readers(
                        disks, bucket, object_name, fi, part.number
                    )
                    opened = readers
                else:
                    # lazy open: a part whose every group hits the
                    # cache never opens a shard stream — the "zero
                    # disk calls on hit" the chaos grid meters
                    def readers(
                        _opened=opened, _pn=part.number
                    ):
                        rs = self._part_readers(
                            disks, bucket, object_name, fi, _pn
                        )
                        _opened.extend(rs)
                        return rs
                try:
                    # decode returns early (heal verdict intact) once a
                    # downstream skipping writer's range is satisfied
                    _, healed = er.decode(
                        sink, readers, dec_off, dec_len, part.size,
                        cache_ctx=rctx,
                    )
                except QuorumError as e:
                    raise ReadQuorumError(str(e)) from e
                finally:
                    for r in opened:
                        if r is not None:
                            try:
                                r.close()
                            except Exception as exc:
                                _log.debug("shard reader close failed", extra=kv(err=str(exc)))
                heal_required = heal_required or healed
                if sink is not writer:
                    sink.finish()
                cur += in_len
                remaining -= in_len
            info = self._to_object_info(bucket, object_name, fi)
            if heal_required:
                info.user_defined["x-internal-heal-required"] = "true"
                # bitrot / missing shard seen on the read path: queue a
                # deep heal (deepHealObject, erasure-object.go:306-310)
                if self.heal_hook is not None:
                    try:
                        self.heal_hook(bucket, object_name)
                    except Exception as exc:
                        _log.debug("deep-heal hook failed", extra=kv(err=str(exc)))
            return info

    def device_scan_source(self, bucket, object_name):
        """Device-resident scan plane for the S3 Select pushdown, or
        None when the object cannot be served from the device cache
        tier (cache off/host-mode, transformed bytes, partial group
        coverage) — the caller then takes the spooled read path.

        A full hit assembles the object's cached (g, k, shard_len)
        group arrays into one contiguous byte plane with device-side
        slicing only: no shard reader opens, no host round-trip.
        Returns ``(plane, nbytes)`` ready for S3Select.evaluate's
        ``device_source``."""
        check_object_name(object_name)
        self._require_bucket(bucket)
        with self.nslock.read(bucket, object_name):
            rc = rcache.read_cache()
            if rc is None or rc.mode != "device":
                return None
            fi = rc.meta_lookup(bucket, object_name)
            if fi is None:
                try:
                    fi, _ = self._read_quorum_fileinfo(
                        bucket, object_name, ""
                    )
                except Exception:  # noqa: BLE001 - miss, not an error
                    return None
                if not fi.deleted:
                    rc.meta_store(bucket, object_name, fi)
            if fi.deleted or fi.size <= 0:
                return None
            if fi.metadata.get(compmod.META_COMPRESSION) or fi.metadata.get(
                ssemod.META_SSE
            ):
                # the cache holds stored bytes; a scan needs plaintext
                return None
            entries = rc.device_entries(bucket, object_name)
            if not entries:
                return None
            by_first = {(key[3], key[4]): key for key in entries}
            er = Erasure(
                fi.erasure.data_blocks,
                fi.erasure.parity_blocks,
                fi.erasure.block_size,
            )
            chunks = []
            for part in fi.parts:
                nblocks = er.block_count(part.size)
                b = 0
                while b < nblocks:
                    key = by_first.get((part.number, b))
                    if key is None or key[2] != fi.data_dir:
                        return None
                    g, shard_len = key[5], key[6]
                    data = entries[key]
                    if b + g > nblocks:
                        return None
                    for gi in range(g):
                        block_len = er._block_len(b + gi, part.size)
                        if er.shard_size_padded(block_len) != shard_len:
                            return None
                        ss = er.shard_size(block_len)
                        chunks.append(
                            data[gi, :, :ss].reshape(-1)[:block_len]
                        )
                    b += g
            from ..s3select import device as seldev

            try:
                return seldev.as_device_plane(chunks, fi.size)
            except Exception:  # noqa: BLE001 - never fail the select
                return None

    def _part_readers(
        self, disks, bucket, object_name, fi: FileInfo, part_number: int
    ) -> list:
        readers: list = []
        for d in disks:
            if d is None:
                readers.append(None)
                continue
            try:
                readers.append(
                    tag_disk_stream(
                        d.read_file_stream(
                            bucket,
                            f"{object_name}/{fi.data_dir}/part.{part_number}",
                        ),
                        d,
                    )
                )
            except Exception:  # noqa: BLE001
                readers.append(None)
        return readers

    # ------------------------------------------------------------------
    # delete (erasure-object.go:793+)
    # ------------------------------------------------------------------

    def delete_object(
        self, bucket, object_name, version_id="", versioned=False,
        version_suspended=False,
    ) -> ObjectInfo:
        check_object_name(object_name)
        self._require_bucket(bucket)
        with self.nslock.write(bucket, object_name):
            if not version_id and (versioned or version_suspended):
                return self._write_delete_marker(
                    bucket, object_name, versioned
                )
            fi, _ = self._read_quorum_fileinfo(
                bucket, object_name, version_id
            )
            errs = []
            for d in self._online_disks():
                if d is None:
                    errs.append(serrors.DiskNotFound("offline"))
                    continue
                try:
                    if version_id:
                        # delete only the requested version; the whole
                        # directory must survive (advisor finding r1)
                        d.delete_version(bucket, object_name, fi)
                    else:
                        d.delete_file(bucket, object_name, recursive=True)
                    errs.append(None)
                except (serrors.FileNotFound, serrors.VersionNotFound):
                    errs.append(None)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            reduce_errs(errs, self.write_quorum, WriteQuorumError)
            self._invalidate_read_cache(bucket, object_name)
            return ObjectInfo(
                bucket=bucket,
                name=object_name,
                version_id=version_id,
                delete_marker=fi.deleted if version_id else False,
            )

    def _write_delete_marker(
        self, bucket, object_name, versioned: bool
    ) -> ObjectInfo:
        """Unqualified DELETE on a versioning-configured bucket appends
        a delete marker instead of removing data
        (xl-storage-format-v2.go xlMetaV2DeleteMarker).  Suspended
        buckets write the *null* marker, replacing the null version."""
        marker_vid = new_version_id() if versioned else ""
        mod_time = now_ns()
        old_null_dir = (
            "" if versioned else self._old_null_data_dir(bucket, object_name)
        )
        fi = FileInfo(
            volume=bucket,
            name=object_name,
            version_id=marker_vid,
            deleted=True,
            mod_time_ns=mod_time,
        )
        errs = []
        disks = self._online_disks()
        for d in disks:
            if d is None:
                errs.append(serrors.DiskNotFound("offline"))
                continue
            try:
                d.write_metadata(bucket, object_name, fi)
                errs.append(None)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        reduce_errs(errs, self.write_quorum, WriteQuorumError)
        self._invalidate_read_cache(bucket, object_name)
        if old_null_dir:
            # the replaced null version's data is unreferenced now
            for d in disks:
                if d is None:
                    continue
                try:
                    d.delete_file(
                        bucket,
                        f"{object_name}/{old_null_dir}",
                        recursive=True,
                    )
                except Exception as exc:
                    _log.debug("null-version data dir cleanup failed", extra=kv(err=str(exc)))
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            version_id=marker_vid,
            delete_marker=True,
            mod_time_ns=mod_time,
        )

    # ------------------------------------------------------------------
    # copy
    # ------------------------------------------------------------------

    def copy_object(
        self, src_bucket, src_object, dst_bucket, dst_object,
        metadata=None, versioned=False, sse_src=None, sse=None,
    ) -> ObjectInfo:
        from ..utils.pipe import streaming_copy

        src_info = self.get_object_info(src_bucket, src_object)
        meta = api.prepare_copy_meta(src_info, metadata)
        if src_bucket == dst_bucket and src_object == dst_object:
            # self-copy (metadata rewrite): the concurrent pipe would
            # deadlock the namespace lock against itself - run the read
            # fully before the write (small objects; the S3 layer only
            # permits self-copy with REPLACE)
            import io

            buf = io.BytesIO()
            self.get_object(src_bucket, src_object, buf, sse=sse_src)
            buf.seek(0)
            return self.put_object(
                dst_bucket, dst_object, buf, src_info.size, meta,
                versioned=versioned, sse=sse,
            )
        # decode streams into a bounded pipe while the encoder consumes
        # it - constant memory for any object size (a 10 GiB copy no
        # longer materializes in RAM; advisor/VERDICT weak #4)
        return streaming_copy(
            lambda sink: self.get_object(
                src_bucket, src_object, sink, sse=sse_src
            ),
            lambda source: self.put_object(
                dst_bucket, dst_object, source, src_info.size, meta,
                versioned=versioned, sse=sse,
            ),
        )

    # ------------------------------------------------------------------
    # list (merged walk; cmd/erasure-sets.go listing semantics simplified)
    # ------------------------------------------------------------------

    def _merged_walk(
        self, bucket, prefix, marker, recursive, inclusive=False
    ):
        """K-way lazy merge of the per-disk ordered walks, deduplicated
        by name (lexicallySortedEntry, erasure-sets.go:842) - nothing is
        materialized; a page pulls only what it emits."""
        import heapq

        def safe(gen):
            # one bad disk ends its stream, not the listing
            while True:
                try:
                    yield next(gen)
                except StopIteration:
                    return
                except Exception:  # noqa: BLE001
                    return

        its = []
        for d in self._online_disks():
            if d is None:
                continue
            try:
                its.append(
                    safe(
                        d.walk_sorted(
                            bucket, prefix, marker,
                            recursive=recursive, inclusive=inclusive,
                        )
                    )
                )
            except Exception:  # noqa: BLE001
                continue
        last = None
        for name, is_prefix in heapq.merge(*its):
            if name == last:
                continue
            last = name
            yield name, is_prefix

    def _list_entries(
        self, bucket, prefix, marker, delimiter, inclusive=False
    ):
        """Shared listing front half: merged walk filtered down to
        ("prefix", name) / ("key", name) entries in lexical order, with
        delimiter folding.  Pagination/truncation stays with callers
        (they differ: one entry per key vs one per version)."""
        # delimiter "/" maps onto single-level directory reads; other
        # delimiters need the full recursive stream (tree-walk.go)
        recursive = delimiter != "/"
        seen_prefixes: set[str] = set()
        for name, is_prefix in self._merged_walk(
            bucket, prefix, marker, recursive, inclusive=inclusive
        ):
            if is_prefix:
                if name <= marker:
                    continue
                yield "prefix", name
                continue
            if prefix and not name.startswith(prefix):
                continue
            if delimiter and recursive:
                # non-"/" delimiter: fold names into common prefixes
                rest = name[len(prefix):]
                di = rest.find(delimiter)
                if di >= 0:
                    cp = prefix + rest[: di + len(delimiter)]
                    if cp <= marker:
                        continue
                    if cp not in seen_prefixes:
                        seen_prefixes.add(cp)
                        yield "prefix", cp
                    continue
            if marker and (name < marker or (name == marker and not inclusive)):
                continue
            yield "key", name

    def list_objects(
        self, bucket, prefix="", marker="", delimiter="", max_keys=1000,
    ) -> ListObjectsInfo:
        self._require_bucket(bucket)
        max_keys = max(0, min(max_keys, 1000))
        out = ListObjectsInfo()
        count = 0
        last_key = ""
        for kind, name in self._list_entries(
            bucket, prefix, marker, delimiter
        ):
            if count >= max_keys:
                out.is_truncated = True
                out.next_marker = last_key
                break
            if kind == "prefix":
                out.prefixes.append(name)
                count += 1
                last_key = name
                continue
            try:
                fi, _ = self._read_quorum_fileinfo(bucket, name)
            except Exception:  # noqa: BLE001
                continue
            if fi.deleted:
                continue
            out.objects.append(self._to_object_info(bucket, name, fi))
            count += 1
            last_key = name
        return out

    # ------------------------------------------------------------------
    # version listing (ListObjectVersions merge)
    # ------------------------------------------------------------------

    def _read_version_journal(
        self, bucket, object_name
    ) -> "list[FileInfo]":
        """Merged, quorum-checked version journal for one object: every
        disk's xl.meta read, versions grouped by id, kept when at least
        read_quorum disks agree, newest first."""
        groups: "dict[str, list[FileInfo]]" = {}
        for d in self._online_disks():
            if d is None:
                continue
            try:
                xl = d.read_xl(bucket, object_name)
            except Exception:  # noqa: BLE001
                continue
            for v in xl.versions:
                groups.setdefault(v.version_id or "null", []).append(v)
        out: list[FileInfo] = []
        for vid, vs in groups.items():
            if len(vs) < self.read_quorum:
                continue
            fi = vs[0]
            fi.volume, fi.name = bucket, object_name
            out.append(fi)
        out.sort(key=lambda v: -v.mod_time_ns)
        for i, fi in enumerate(out):
            fi.is_latest = i == 0
        return out

    def has_object_versions(self, bucket, object_name) -> bool:
        """Any journal entry at all (incl. delete markers) - used by the
        zone router, where get_object_info hides marker-latest keys."""
        return bool(self._read_version_journal(bucket, object_name))

    def list_object_versions(
        self, bucket, prefix="", key_marker="", version_id_marker="",
        delimiter="", max_keys=1000,
    ) -> api.ListObjectVersionsInfo:
        self._require_bucket(bucket)
        max_keys = max(0, min(max_keys, 1000))
        out = api.ListObjectVersionsInfo()
        count = 0
        last = (key_marker, version_id_marker)  # last emitted (key, vid)
        # the marker key itself is re-visited (version resume)
        for kind, name in self._list_entries(
            bucket, prefix, key_marker, delimiter, inclusive=True
        ):
            if kind == "prefix":
                if count >= max_keys:
                    out.is_truncated = True
                    out.next_key_marker = last[0]
                    out.next_version_id_marker = last[1]
                    return out
                out.prefixes.append(name)
                count += 1
                last = (name, "")
                continue
            versions = self._read_version_journal(bucket, name)
            resumed = False
            if name == key_marker and version_id_marker:
                # if the marker version vanished between pages (deleted
                # concurrently), emit the whole key again - duplicates
                # beat silently dropping every remaining version
                if not any(
                    (fi.version_id or "null") == version_id_marker
                    for fi in versions
                ):
                    resumed = True
            for fi in versions:
                vid = fi.version_id or "null"
                if name == key_marker and not resumed:
                    # resume inside this key's version list: skip up to
                    # and including the version-id marker (no marker =
                    # the whole key was emitted last page)
                    if not version_id_marker:
                        continue
                    if vid == version_id_marker:
                        resumed = True
                    continue
                if count >= max_keys:
                    out.is_truncated = True
                    out.next_key_marker, out.next_version_id_marker = last
                    return out
                oi = self._to_object_info(bucket, name, fi)
                oi.is_latest = fi.is_latest
                oi.version_id = vid
                out.versions.append(oi)
                count += 1
                last = (name, vid)
        return out

    # ------------------------------------------------------------------
    # heal (erasure-healing.go:227 healObject)
    # ------------------------------------------------------------------

    def heal_bucket(self, bucket: str, dry_run: bool = False) -> dict:
        """Recreate the bucket volume on online disks missing it
        (erasure-healing.go:105 healBucket): a replaced/wiped drive loses
        every volume, and object heal cannot rename into a volume that
        does not exist.  Quorum of present copies is required before we
        re-stamp the stragglers."""
        check_bucket_name(bucket)
        with self.nslock.write(bucket, ""):
            disks = self._online_disks()  # one snapshot for probe + repair
            present, missing = [], []
            for i, d in enumerate(disks):
                if d is None:
                    continue
                try:
                    d.stat_vol(bucket)
                    present.append(i)
                except serrors.VolumeNotFound:
                    missing.append(i)
                except Exception:  # noqa: BLE001
                    continue  # transient error: neither present nor missing
            if not present:
                raise BucketNotFound(bucket)
            result = {
                "bucket": bucket,
                "present": present,
                "healed": [],
                "dry_run": dry_run,
            }
            if len(present) < self.read_quorum:
                # bucket exists but too few confirmations to re-stamp
                # stragglers safely; report without mutating
                return result
            if dry_run:
                result["healed"] = missing
                return result
            for i in missing:
                try:
                    disks[i].make_vol(bucket)
                    result["healed"].append(i)
                except serrors.VolumeExists:
                    result["healed"].append(i)
                except Exception as exc:
                    _log.debug("bucket heal make_vol failed", extra=kv(err=str(exc)))
            return result

    def probe_object_health(
        self, bucket, object_name, version_id=""
    ) -> dict:
        """Metadata-only shard-health probe for the crawler's
        heal-on-crawl pass: per-disk xl.meta quorum compare, NO
        namespace lock, NO shard reads, NO heal_bucket fan-out - a
        full sweep must not serialize against live traffic.  A racy
        false positive only queues a heal that then finds nothing.

        ObjectNotFound/VersionNotFound propagate (cleanly absent,
        e.g. deleted mid-sweep); an object damaged PAST read quorum
        reports every disk outdated - those are the most urgent
        heals, not exceptions to swallow."""
        out = {"bucket": bucket, "object": object_name}
        try:
            fi, fis = self._read_quorum_fileinfo(
                bucket, object_name, version_id
            )
        except ReadQuorumError:
            return {
                **out,
                "outdated": list(range(len(self.disks))),
                "no_quorum": True,
            }
        disks = self._online_disks()
        out["outdated"] = [
            i
            for i, (d, f) in enumerate(zip(disks, fis))
            if d is not None
            and (
                f is None
                or f.mod_time_ns != fi.mod_time_ns
                or f.data_dir != fi.data_dir
            )
        ]
        return out

    def heal_object(
        self, bucket, object_name, version_id="", dry_run=False
    ) -> dict:
        # heal the bucket volume first (MakeVol on wiped disks) so the
        # shard rename below has a destination (erasure-healing.go:105)
        self.heal_bucket(bucket, dry_run=dry_run)
        with self.nslock.write(bucket, object_name):
            disks_raw = self._online_disks()
            fis, errs = read_all_fileinfo(
                disks_raw, bucket, object_name, version_id
            )
            fi = find_fileinfo_in_quorum(fis, self.read_quorum)
            disks = shuffle_disks(disks_raw, fi.erasure.distribution)
            fis_shuffled = shuffle_disks(fis, fi.erasure.distribution)
            er = Erasure(
                fi.erasure.data_blocks,
                fi.erasure.parity_blocks,
                fi.erasure.block_size,
            )
            # classify disks: ok / outdated (disksWithAllParts semantics)
            outdated: list[int] = []
            for i, d in enumerate(disks):
                f = fis_shuffled[i]
                if d is None:
                    continue  # offline: cannot heal
                if (
                    f is None
                    or f.mod_time_ns != fi.mod_time_ns
                    or f.data_dir != fi.data_dir
                ):
                    outdated.append(i)
                    continue
                try:
                    d.verify_file(bucket, object_name, fi)
                except Exception:  # noqa: BLE001
                    outdated.append(i)
            result = {
                "bucket": bucket,
                "object": object_name,
                "disks": len(self.disks),
                "outdated": list(outdated),
                "healed": [],
                "dry_run": dry_run,
            }
            if not outdated or dry_run:
                return result
            tmp_ids = {i: uuid.uuid4().hex for i in outdated}
            # a fully wiped disk lost its staging volume too
            for i in outdated:
                try:
                    disks[i].make_vol(SYS_VOL)
                except Exception as exc:
                    _log.debug("staging vol re-create failed on wiped disk", extra=kv(err=str(exc)))
            for part in fi.parts:
                readers = []
                for i, d in enumerate(disks):
                    if d is None or i in outdated:
                        readers.append(None)
                    else:
                        try:
                            readers.append(
                                tag_disk_stream(
                                    d.read_file_stream(
                                        bucket,
                                        f"{object_name}/{fi.data_dir}/part.{part.number}",
                                    ),
                                    d,
                                )
                            )
                        except Exception:  # noqa: BLE001
                            readers.append(None)
                writers = [None] * len(disks)
                for i in outdated:
                    writers[i] = tag_disk_stream(
                        disks[i].create_file(
                            SYS_VOL,
                            f"tmp/{tmp_ids[i]}/{fi.data_dir}/part.{part.number}",
                        ),
                        disks[i],
                    )
                try:
                    er.heal(readers, writers, part.size)
                except QuorumError as e:
                    raise ReadQuorumError(str(e)) from e
                finally:
                    for r in readers:
                        if r is not None:
                            r.close()
                    for w in writers:
                        if w is not None:
                            w.close()
            for i in outdated:
                hfi = FileInfo(**{**fi.__dict__})
                hfi.erasure = ErasureInfo(**fi.erasure.__dict__)
                hfi.erasure.index = i + 1
                disks[i].rename_data(
                    SYS_VOL, f"tmp/{tmp_ids[i]}", hfi, bucket, object_name
                )
                result["healed"].append(i)
            # heal rewrote shard files: even though the reconstructed
            # bytes are identical, cached generations must re-verify
            # against the fresh frames, so drop them everywhere
            self._invalidate_read_cache(bucket, object_name)
            return result

    def storage_info(self) -> dict:
        online = sum(d is not None for d in self._online_disks())
        return {
            "disks": len(self.disks),
            "online": online,
            "offline": len(self.disks) - online,
            "data": self.data_blocks,
            "parity": self.parity_blocks,
        }
