"""Per-bucket metadata subsystem (cmd/bucket-metadata-sys.go).

One JSON document per bucket at ``.sys/buckets/<bucket>/metadata.json``
(the .minio.sys/buckets/<bucket>/.metadata.bin analogue) holding every
bucket-scoped config: policy, versioning state, tagging, quota,
lifecycle, notification, object-lock.  Erasure-coded through the object
layer so all nodes converge on it; cached in memory per process with
read-through on miss.
"""

from __future__ import annotations

import dataclasses
import io
import json
import threading
import time

from ..iam.policy import Policy, PolicyError
from .api import META_BUCKET, BucketNotFound, ObjectNotFound

META_PREFIX = "buckets"
# without a peer control plane, remote config edits surface after the
# cache TTL (the stand-in for peer-RPC invalidation)
CACHE_TTL_S = 5.0


@dataclasses.dataclass
class BucketMetadata:
    """All bucket configs (cmd/bucket-metadata.go BucketMetadata)."""

    name: str = ""
    created_ns: int = 0
    policy_json: str = ""  # bucket (resource) policy document
    versioning: str = ""  # "" | "Enabled" | "Suspended"
    tagging_xml: str = ""
    quota_json: str = ""
    lifecycle_xml: str = ""
    notification_xml: str = ""
    object_lock_xml: str = ""
    sse_config_xml: str = ""
    replication_xml: str = ""
    # admin-registered remote replication targets (bucket-targets.go):
    # JSON list of {endpoint, access_key, secret_key, target_bucket}
    replication_targets_json: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "BucketMetadata":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @property
    def versioning_enabled(self) -> bool:
        return self.versioning == "Enabled"

    @property
    def versioning_suspended(self) -> bool:
        return self.versioning == "Suspended"

    def policy(self) -> "Policy | None":
        if not self.policy_json:
            return None
        cached = getattr(self, "_parsed_policy", None)
        if cached is not None:
            return cached
        try:
            parsed = Policy.from_json(self.policy_json)
        except PolicyError:
            return None
        # memoized per document: authorization runs per request (and
        # per key in multi-delete) - don't re-parse each time
        object.__setattr__(self, "_parsed_policy", parsed)
        return parsed


class BucketMetadataSys:
    """Read-through cache over the persisted per-bucket documents."""

    def __init__(self, object_layer, cache_ttl_s: "float | None" = None):
        import os

        self._ol = object_layer
        self._ttl = (
            cache_ttl_s
            if cache_ttl_s is not None
            else float(
                os.environ.get("MINIO_TPU_BUCKET_META_TTL_S") or CACHE_TTL_S
            )
        )
        self._mu = threading.RLock()
        self._cache: "dict[str, tuple[BucketMetadata, float]]" = {}
        # peer control plane: set in distributed mode so edits broadcast
        # an invalidation instead of waiting out peers' TTLs
        self.notifier = None

    def _path(self, bucket: str) -> str:
        return f"{META_PREFIX}/{bucket}/metadata.json"

    # -- reads ------------------------------------------------------------

    def get(self, bucket: str) -> BucketMetadata:
        """Metadata for the bucket; a default (empty) document when none
        was ever written.  BucketNotFound propagates from the layer.
        Entries expire after the TTL so edits made through another node
        take effect here without a peer broadcast."""
        now = time.monotonic()
        with self._mu:
            hit = self._cache.get(bucket)
            if hit is not None and now - hit[1] < self._ttl:
                return hit[0]
        bm = self._load(bucket)
        with self._mu:
            self._cache[bucket] = (bm, now)
        return bm

    def _load(self, bucket: str) -> BucketMetadata:
        buf = io.BytesIO()
        try:
            self._ol.get_object(META_BUCKET, self._path(bucket), buf)
            return BucketMetadata.from_dict(json.loads(buf.getvalue()))
        except ObjectNotFound:
            return BucketMetadata(name=bucket)
        except BucketNotFound:
            return BucketMetadata(name=bucket)
        except ValueError:
            return BucketMetadata(name=bucket)

    # -- writes -----------------------------------------------------------

    def update(self, bucket: str, **fields) -> BucketMetadata:
        """Persist new values for the given config fields."""
        # the bucket must exist (mirrors BucketMetadataSys.Update)
        self._ol.get_bucket_info(bucket)
        with self._mu:
            hit = self._cache.get(bucket)
            bm = hit[0] if hit else self._load(bucket)
            bm = dataclasses.replace(bm, name=bucket, **fields)
            if not bm.created_ns:
                bm.created_ns = time.time_ns()
            raw = json.dumps(bm.to_dict()).encode()
            self._ol.put_object(
                META_BUCKET, self._path(bucket), io.BytesIO(raw), len(raw)
            )
            self._cache[bucket] = (bm, time.monotonic())
        if self.notifier is not None:
            self.notifier.bucket_meta_changed(bucket)
        return bm

    def delete(self, bucket: str) -> None:
        """Drop the document when its bucket is deleted."""
        with self._mu:
            self._cache.pop(bucket, None)
        try:
            self._ol.delete_object(META_BUCKET, self._path(bucket))
        except (ObjectNotFound, BucketNotFound):
            pass
        if self.notifier is not None:
            self.notifier.bucket_meta_deleted(bucket)

    def invalidate(self, bucket: "str | None" = None) -> None:
        """Forget cached entries (peer-invalidation stand-in)."""
        with self._mu:
            if bucket is None:
                self._cache.clear()
            else:
                self._cache.pop(bucket, None)
