"""Multipart uploads for the erasure object layer (cmd/erasure-multipart.go).

Uploads are staged under the system volume:

    .sys/multipart/<upload_id>/xl.meta      upload metadata (journal)
    .sys/multipart/<upload_id>/part.N       framed erasure shards per part

Each part is erasure-encoded independently with the object's distribution
(deterministic from bucket/object, so every disk stages the shard it will
eventually serve).  CompleteMultipartUpload renames the chosen part files
into the final object data dir - no re-encoding, mirroring the
rename-based commit of CompleteMultipartUpload (erasure-multipart.go:642).

The multipart ETag is the S3 convention: md5(concat(part md5s)) + "-N".
"""

from __future__ import annotations

import hashlib
import uuid

from ..codec import compress as compmod, sse as ssemod
from ..codec.erasure import Erasure, QuorumError
from ..parallel import iopool
from ..parallel.iopool import tag_disk_stream
from ..storage import errors as serrors
from ..storage.meta import (
    ErasureInfo,
    FileInfo,
    ObjectPartInfo,
    new_version_id,
    now_ns,
)
from ..utils.hashreader import HashReader
from . import api
from .api import (
    CompletePart,
    InvalidPart,
    InvalidUploadID,
    ObjectInfo,
    PartInfo,
    WriteQuorumError,
    check_object_name,
)
from .metadata import (
    find_fileinfo_in_quorum,
    hash_order,
    read_all_fileinfo,
    reduce_errs,
    shuffle_disks,
)

from ..utils.log import kv, logger

_log = logger("objectlayer")

SYS_VOL = ".sys"
MP_DIR = "multipart"
# S3 minimum size for any part other than the last (globalMinPartSize)
MIN_PART_SIZE = 5 << 20


class MultipartMixin:
    """Multipart methods; mixed into ErasureObjects."""

    # -- helpers ---------------------------------------------------------

    def _mp_path(self, upload_id: str) -> str:
        return f"{MP_DIR}/{upload_id}"

    def _mp_read_meta(self, upload_id: str):
        disks = self._online_disks()
        fis, errs = read_all_fileinfo(
            disks, SYS_VOL, self._mp_path(upload_id)
        )
        alive = sum(f is not None for f in fis)
        if alive < self.read_quorum:
            raise InvalidUploadID(upload_id)
        return find_fileinfo_in_quorum(fis, self.read_quorum)

    # -- API -------------------------------------------------------------

    def new_multipart_upload(
        self, bucket, object_name, metadata=None, sse=None
    ) -> str:
        check_object_name(object_name)
        self._require_bucket(bucket)
        upload_id = uuid.uuid4().hex
        meta = dict(metadata or {})
        meta["x-internal-bucket"] = bucket
        meta["x-internal-object"] = object_name
        # compression is decided once per upload (part sizes are
        # unknown up front - streaming semantics) and every part
        # inherits it so the assembled object is uniformly coded
        if compmod.should_compress(
            object_name, meta.get("content-type", ""), -1
        ):
            meta[compmod.META_COMPRESSION] = compmod.ALGORITHM
        # one object key per upload, sealed at initiation; every part
        # encrypts under it with a part-derived nonce prefix
        if sse is not None:
            oek = ssemod.new_object_key()
            nb = ssemod.new_nonce_base()
            meta.update(
                self._seal_sse_meta(
                    sse, oek, nb, f"{bucket}/{object_name}"
                )
            )
        distribution = hash_order(
            f"{bucket}/{object_name}", len(self.disks)
        )
        mod_time = now_ns()
        errs = []
        for i, d in enumerate(self._online_disks()):
            if d is None:
                errs.append(serrors.DiskNotFound("offline"))
                continue
            fi = FileInfo(
                volume=SYS_VOL,
                name=self._mp_path(upload_id),
                data_dir="",
                size=0,
                mod_time_ns=mod_time,
                metadata=meta,
                erasure=ErasureInfo(
                    data_blocks=self.data_blocks,
                    parity_blocks=self.parity_blocks,
                    block_size=self.block_size,
                    index=i + 1,
                    distribution=distribution,
                ),
            )
            try:
                d.write_metadata(SYS_VOL, self._mp_path(upload_id), fi)
                errs.append(None)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        reduce_errs(errs, self.write_quorum, WriteQuorumError)
        return upload_id

    def put_object_part(
        self, bucket, object_name, upload_id, part_number, reader,
        size=-1, sse=None,
    ) -> PartInfo:
        if not (1 <= part_number <= 10000):
            raise InvalidPart(f"part number {part_number}")
        mfi = self._mp_read_meta(upload_id)
        er = Erasure(
            self.data_blocks, self.parity_blocks, self.block_size
        )
        hreader = HashReader(reader, size)
        # each part is an independent deflate stream: the GET path can
        # then skip whole parts by actual size and the part ETag stays
        # the plaintext MD5 the client computed
        compress = bool(mfi.metadata.get(compmod.META_COMPRESSION))
        src = compmod.CompressReader(hreader) if compress else hreader
        if sse is not None and mfi.metadata.get(ssemod.META_SSE) != "C":
            # a customer key on a part of an unencrypted OR SSE-S3
            # upload must fail, not be silently dropped (AWS rejects
            # the mode mismatch)
            raise ssemod.SSEError(
                "upload was not initiated with customer-key encryption"
            )
        if mfi.metadata.get(ssemod.META_SSE):
            bkt = mfi.metadata.get("x-internal-bucket", bucket)
            obj = mfi.metadata.get("x-internal-object", object_name)
            oek, nb = self._unseal_oek(
                mfi.metadata, sse, f"{bkt}/{obj}"
            )
            src = ssemod.EncryptReader(
                src, oek, ssemod.part_nonce_base(nb, part_number)
            )
        disks = shuffle_disks(
            self._online_disks(), mfi.erasure.distribution
        )
        tmp_ids = [uuid.uuid4().hex for _ in disks]
        writers: list = []
        for i, d in enumerate(disks):
            if d is None:
                writers.append(None)
                continue
            try:
                writers.append(
                    tag_disk_stream(
                        d.create_file(
                            SYS_VOL,
                            f"tmp/{tmp_ids[i]}/part.{part_number}",
                        ),
                        d,
                    )
                )
            except Exception:  # noqa: BLE001
                writers.append(None)
        try:
            total = er.encode(src, writers, self.write_quorum)
        except QuorumError as e:
            # close writers FIRST: streaming remote writers own sender
            # threads that must terminate before staging is reaped
            for w in writers:
                if w is not None:
                    try:
                        w.close()
                    except Exception as exc:
                        _log.debug("shard writer close failed", extra=kv(err=str(exc)))
            self._cleanup_tmp(disks, tmp_ids)
            raise WriteQuorumError(str(e)) from e
        # fan the shard-file closes (flush + fsync) out per disk queue
        for err in iopool.fanout(
            [
                (iopool.stream_io_key(w), w.close)
                for w in writers
                if w is not None
            ]
        ):
            if err is not None and not isinstance(err, OSError):
                raise err
        etag = hreader.etag()
        actual = hreader.bytes_read
        mod = now_ns()
        # commit shard into the upload dir + record part metadata, one
        # pool job per disk (each commit touches only its own disk)
        commit_ops = []
        errs: list = [None] * len(disks)
        for i, d in enumerate(disks):
            if d is None or writers[i] is None:
                errs[i] = serrors.DiskNotFound("offline")
                continue

            def commit(d=d, tmp=tmp_ids[i]):
                d.rename_file(
                    SYS_VOL,
                    f"tmp/{tmp}/part.{part_number}",
                    SYS_VOL,
                    f"{self._mp_path(upload_id)}/part.{part_number}",
                )
                d.write_all(
                    SYS_VOL,
                    f"{self._mp_path(upload_id)}/part.{part_number}.meta",
                    f"{total}:{etag}:{mod}:{actual}".encode(),
                )
                d.delete_file(SYS_VOL, f"tmp/{tmp}", recursive=True)

            commit_ops.append((i, iopool.disk_io_key(d) or f"disk-{i}", commit))
        for (i, _k, _f), err in zip(
            commit_ops,
            iopool.fanout([(key, fn) for _i, key, fn in commit_ops]),
        ):
            errs[i] = err
        reduce_errs(errs, self.write_quorum, WriteQuorumError)
        return PartInfo(
            part_number=part_number,
            etag=etag,
            size=actual,
            actual_size=actual,
            mod_time_ns=mod,
        )

    def _read_part_meta(
        self, upload_id: str, part_number: int
    ) -> "tuple[int, str, int, int] | None":
        """-> (stored_size, etag, mod_time, actual_size)."""
        for d in self._online_disks():
            if d is None:
                continue
            try:
                raw = d.read_all(
                    SYS_VOL,
                    f"{self._mp_path(upload_id)}/part.{part_number}.meta",
                ).decode()
                fields = raw.split(":")
                size, etag, mod = fields[0], fields[1], fields[2]
                actual = fields[3] if len(fields) > 3 else size
                return int(size), etag, int(mod), int(actual)
            except Exception:  # noqa: BLE001
                continue
        return None

    def list_object_parts(
        self, bucket, object_name, upload_id, part_marker=0,
        max_parts=1000,
    ) -> list[PartInfo]:
        self._mp_read_meta(upload_id)
        nums: set[int] = set()
        for d in self._online_disks():
            if d is None:
                continue
            try:
                for name in d.list_dir(SYS_VOL, self._mp_path(upload_id)):
                    if name.startswith("part.") and name.endswith(".meta"):
                        nums.add(int(name[5:-5]))
            except Exception:  # noqa: BLE001
                continue
        out = []
        for n in sorted(nums):
            if n <= part_marker:
                continue
            pm = self._read_part_meta(upload_id, n)
            if pm is None:
                continue
            _size, etag, mod, actual = pm
            # clients always see the plaintext (actual) part size
            out.append(
                PartInfo(n, etag, actual, actual, mod)
            )
            if len(out) >= max_parts:
                break
        return out

    def list_multipart_uploads(
        self, bucket, prefix=""
    ) -> list[api.MultipartInfo]:
        uploads = []
        seen = set()
        for d in self._online_disks():
            if d is None:
                continue
            try:
                ids = d.list_dir(SYS_VOL, MP_DIR)
            except Exception:  # noqa: BLE001
                continue
            for uid in ids:
                uid = uid.rstrip("/")
                if uid in seen:
                    continue
                seen.add(uid)
                try:
                    mfi = self._mp_read_meta(uid)
                except Exception:  # noqa: BLE001
                    continue
                b = mfi.metadata.get("x-internal-bucket", "")
                o = mfi.metadata.get("x-internal-object", "")
                if b != bucket or (prefix and not o.startswith(prefix)):
                    continue
                uploads.append(
                    api.MultipartInfo(b, o, uid, mfi.mod_time_ns)
                )
        uploads.sort(key=lambda u: (u.object, u.upload_id))
        return uploads

    def abort_multipart_upload(
        self, bucket, object_name, upload_id
    ) -> None:
        self._mp_read_meta(upload_id)  # validates
        for d in self._online_disks():
            if d is None:
                continue
            try:
                d.delete_file(
                    SYS_VOL, self._mp_path(upload_id), recursive=True
                )
            except Exception as exc:
                _log.debug("upload dir cleanup failed", extra=kv(err=str(exc)))

    def complete_multipart_upload(
        self, bucket, object_name, upload_id, parts: list[CompletePart],
        versioned=False,
    ) -> ObjectInfo:
        self._require_bucket(bucket)
        mfi = self._mp_read_meta(upload_id)
        # the upload id must belong to this bucket/object
        # (CompleteMultipartUpload validates uploadID against the object,
        # erasure-multipart.go:642)
        if (
            mfi.metadata.get("x-internal-bucket") != bucket
            or mfi.metadata.get("x-internal-object") != object_name
        ):
            raise InvalidUploadID(upload_id)
        if not parts:
            raise InvalidPart("no parts")
        # validate + collect part metadata
        infos: list[tuple[CompletePart, int, int]] = []
        md5s = hashlib.md5()
        total = 0
        total_actual = 0
        last = 0
        min_part = getattr(self, "min_part_size", MIN_PART_SIZE)
        for i, cp in enumerate(parts):
            if cp.part_number <= last:
                raise api.InvalidPartOrder("parts out of order")
            last = cp.part_number
            pm = self._read_part_meta(upload_id, cp.part_number)
            if pm is None:
                raise InvalidPart(f"part {cp.part_number} not found")
            size, etag, _, actual = pm
            if cp.etag and cp.etag.strip('"') != etag:
                raise InvalidPart(f"part {cp.part_number} etag mismatch")
            # S3 minimum part size applies to all but the last part and
            # to the CLIENT-visible bytes (a compressed part may store
            # far fewer; cmd/erasure-multipart.go checks ActualSize)
            if i != len(parts) - 1 and actual < min_part:
                raise api.EntityTooSmall(
                    f"part {cp.part_number} is {actual} bytes"
                )
            infos.append((cp, size, actual))
            md5s.update(bytes.fromhex(etag))
            total += size
            total_actual += actual
        final_etag = f"{md5s.hexdigest()}-{len(parts)}"
        mod_time = now_ns()
        data_dir = uuid.uuid4().hex
        distribution = mfi.erasure.distribution
        disks = shuffle_disks(self._online_disks(), distribution)
        meta = {
            k: v
            for k, v in mfi.metadata.items()
            if not k.startswith("x-internal-")
        }
        meta["etag"] = final_etag
        if mfi.metadata.get(compmod.META_COMPRESSION):
            meta[compmod.META_COMPRESSION] = compmod.ALGORITHM
        if mfi.metadata.get(ssemod.META_SSE):
            # carry the sealed key forward, plus the ORIGINAL part
            # numbers in completion order: chunk nonces derive from the
            # number each part was uploaded under, which the
            # renumbering below would otherwise lose
            for mk in (
                ssemod.META_SSE,
                ssemod.META_SSE_SEALED_KEY,
                ssemod.META_SSE_NONCE,
                ssemod.META_SSE_KEY_MD5,
                ssemod.META_SSE_KMS_ID,
                ssemod.META_SSE_KMS_SEALED_DK,
            ):
                if mk in mfi.metadata:
                    meta[mk] = mfi.metadata[mk]
            meta[ssemod.META_SSE_PARTS] = ",".join(
                str(cp.part_number) for cp, _s, _a in infos
            )
        if mfi.metadata.get(compmod.META_COMPRESSION) or mfi.metadata.get(
            ssemod.META_SSE
        ):
            meta[compmod.META_ACTUAL_SIZE] = str(total_actual)

        with self.nslock.write(bucket, object_name):
            version_id = new_version_id() if versioned else ""
            old_data_dir = (
                ""
                if versioned
                else self._old_null_data_dir(bucket, object_name)
            )
            errs = []
            staged: list[tuple] = []  # (disk, tmp) that moved parts out
            for i, d in enumerate(disks):
                if d is None:
                    errs.append(serrors.DiskNotFound("offline"))
                    continue
                tmp = uuid.uuid4().hex
                fi = FileInfo(
                    volume=bucket,
                    name=object_name,
                    version_id=version_id,
                    data_dir=data_dir,
                    size=total,
                    mod_time_ns=mod_time,
                    metadata=meta,
                    parts=[
                        ObjectPartInfo(idx + 1, size, actual)
                        for idx, (cp, size, actual) in enumerate(infos)
                    ],
                    erasure=ErasureInfo(
                        data_blocks=self.data_blocks,
                        parity_blocks=self.parity_blocks,
                        block_size=self.block_size,
                        index=i + 1,
                        distribution=distribution,
                    ),
                )
                try:
                    # move chosen parts into the staged data dir,
                    # renumbered consecutively (part.N -> part.idx+1)
                    for idx, (cp, _size, _actual) in enumerate(infos):
                        d.rename_file(
                            SYS_VOL,
                            f"{self._mp_path(upload_id)}/part.{cp.part_number}",
                            SYS_VOL,
                            f"tmp/{tmp}/{data_dir}/part.{idx + 1}",
                        )
                    staged.append((d, tmp))
                    d.rename_data(
                        SYS_VOL, f"tmp/{tmp}", fi, bucket, object_name
                    )
                    errs.append(None)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            try:
                reduce_errs(errs, self.write_quorum, WriteQuorumError)
            except WriteQuorumError:
                # roll the staged parts back into the upload dir so the
                # client can retry CompleteMultipartUpload
                for d, tmp in staged:
                    for idx, (cp, _size, _actual) in enumerate(infos):
                        try:
                            d.rename_file(
                                SYS_VOL,
                                f"tmp/{tmp}/{data_dir}/part.{idx + 1}",
                                SYS_VOL,
                                f"{self._mp_path(upload_id)}/part.{cp.part_number}",
                            )
                        except Exception as exc:
                            _log.debug("part un-rename during complete rollback failed", extra=kv(err=str(exc)))
                    try:
                        d.delete_file(
                            SYS_VOL, f"tmp/{tmp}", recursive=True
                        )
                    except Exception as exc:
                        _log.debug("tmp cleanup during complete rollback failed", extra=kv(err=str(exc)))
                raise
            # mutation seam: the completed upload is the object's new
            # generation — cached groups of the old one die everywhere
            self._invalidate_read_cache(bucket, object_name)
            if old_data_dir and old_data_dir != data_dir:
                for d in disks:
                    if d is None:
                        continue
                    try:
                        d.delete_file(
                            bucket,
                            f"{object_name}/{old_data_dir}",
                            recursive=True,
                        )
                    except Exception as exc:
                        _log.debug("replaced data dir cleanup failed", extra=kv(err=str(exc)))
        # drop the upload dir
        for d in self._online_disks():
            if d is None:
                continue
            try:
                d.delete_file(
                    SYS_VOL, self._mp_path(upload_id), recursive=True
                )
            except Exception as exc:
                _log.debug("upload dir cleanup failed", extra=kv(err=str(exc)))
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=total_actual,  # clients see plaintext bytes
            mod_time_ns=mod_time,
            etag=final_etag,
            content_type=meta.get("content-type", ""),
            version_id=version_id,
            user_defined=meta,
        )
