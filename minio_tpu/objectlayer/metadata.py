"""FileInfo quorum logic (cmd/erasure-metadata.go / erasure-metadata-utils.go).

The object layer never trusts a single disk's metadata: it reads xl.meta
from every disk, groups by (mod_time, data_dir) and requires agreement
from a read quorum (findFileInfoInQuorum, erasure-metadata.go:215), then
picks a FileInfo whose erasure.index belongs to an online disk
(pickValidFileInfo, :259).
"""

from __future__ import annotations

import binascii

from ..storage import errors as serrors
from ..storage.meta import FileInfo
from . import api


def hash_order(key: str, cardinality: int) -> list[int]:
    """1-based rotated disk order for an object key (hashOrder,
    cmd/erasure-metadata.go:324-340, crc32-seeded)."""
    if cardinality <= 0:
        return []
    start = binascii.crc32(key.encode()) % cardinality
    return [
        (start + i) % cardinality + 1 for i in range(cardinality)
    ]


def shuffle_disks(disks: list, distribution: list[int]) -> list:
    """Place disks so position i holds shard i+1 (shuffleDisks,
    erasure-object.go + erasure-metadata-utils.go:102)."""
    if not distribution:
        return list(disks)
    out = [None] * len(disks)
    for i, d in enumerate(disks):
        out[distribution[i] - 1] = d
    return out


def read_all_fileinfo(
    disks: list, volume: str, path: str, version_id: str = ""
) -> tuple[list, list]:
    """ReadVersion from every disk -> (fileinfos, errors) index-aligned
    (readAllFileInfo, erasure-metadata-utils.go)."""
    fis: list = [None] * len(disks)
    errs: list = [None] * len(disks)
    for i, disk in enumerate(disks):
        if disk is None:
            errs[i] = serrors.DiskNotFound("offline")
            continue
        try:
            fis[i] = disk.read_version(volume, path, version_id)
        except Exception as e:  # noqa: BLE001 - per-disk error slot
            errs[i] = e
    return fis, errs


def find_fileinfo_in_quorum(
    fis: list, quorum: int
) -> FileInfo:
    """Pick the FileInfo agreeing across >= quorum disks
    (findFileInfoInQuorum, erasure-metadata.go:215: mod_time + data_dir
    grouping)."""
    counts: dict = {}
    for fi in fis:
        if fi is None:
            continue
        key = (fi.mod_time_ns, fi.data_dir, fi.deleted)
        counts[key] = counts.get(key, 0) + 1
    best = None
    for fi in fis:
        if fi is None:
            continue
        key = (fi.mod_time_ns, fi.data_dir, fi.deleted)
        if counts[key] >= quorum:
            if best is None or fi.mod_time_ns > best.mod_time_ns:
                best = fi
    if best is None:
        raise api.ReadQuorumError(
            f"no metadata quorum ({quorum}) among {sum(f is not None for f in fis)} disks"
        )
    return best


def object_quorum_from_meta(
    fi: FileInfo, disk_count: int
) -> tuple[int, int]:
    """(read_quorum, write_quorum) from stored geometry
    (objectQuorumFromMeta, erasure-metadata.go:321 + erasure-object.go:593:
    write quorum gains +1 when data == parity)."""
    data = fi.erasure.data_blocks or disk_count // 2
    parity = fi.erasure.parity_blocks or disk_count - data
    write_quorum = data
    if data == parity:
        write_quorum += 1
    return data, write_quorum


def reduce_errs(errs: list, quorum: int, err_cls) -> None:
    """Raise err_cls unless >= quorum slots succeeded (reduceWriteQuorumErrs
    semantics, erasure-metadata-utils.go:56)."""
    ok = sum(e is None for e in errs)
    if ok < quorum:
        first = next((e for e in errs if e is not None), None)
        raise err_cls(
            f"quorum {quorum} not met: {ok} ok, first error: {first}"
        )
