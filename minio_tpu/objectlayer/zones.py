"""ErasureZones: capacity-routed server pools (cmd/erasure-zones.go).

The top-level ObjectLayer in server mode (newObjectLayer,
server-main.go:559): writes go to the zone with the most free space
(getAvailableZoneIdx, erasure-zones.go:113), reads/deletes query zones in
order, listings merge across zones.  Each zone is an ErasureSets.
"""

from __future__ import annotations

import threading
import time
import zlib

from . import api
from .api import ListObjectsInfo, ObjectLayer
from .sets import ErasureSets, merge_list_results
from ..crawler.updatetracker import object_path_updated

from ..utils.log import kv, logger

_log = logger("objectlayer")

# Stop placing new objects in a zone once it is this full
# (diskFillFraction, erasure-zones.go:37).
_DISK_FILL_FRACTION = 0.95
# Free-space snapshots are refreshed at most this often; placement
# between refreshes reuses the cached distribution, so PUTs do not
# stat every disk (the reference reads cached StorageUsageInfo from
# the crawler rather than statting per call).
_USAGE_TTL_S = 10.0


class ErasureZones(ObjectLayer):
    def __init__(self, zones: list[ErasureSets]):
        if not zones:
            raise ValueError("need at least one zone")
        self.zones = zones
        self._bucket_ops_lock = threading.Lock()
        self._usage_lock = threading.Lock()
        self._usage_ts = 0.0
        self._usage: "list[tuple[int, int]]" = []  # (free, total) per zone
        self._usage_refreshing = False

    # -- placement --------------------------------------------------------

    def _zone_space(self, zone: ErasureSets) -> "tuple[int, int]":
        free = total = 0
        for s in zone.sets:
            for d in s._online_disks():
                if d is None:
                    continue
                try:
                    di = d.disk_info()
                    free += di.free
                    total += di.total
                except Exception as exc:
                    _log.debug("disk_info probe failed", extra=kv(err=str(exc)))
        return free, total

    def _usage_snapshot(self) -> "list[tuple[int, int]]":
        """TTL-cached free/total per zone.  The disk statting runs
        OUTSIDE the lock: when the TTL lapses one caller refreshes
        while concurrent PUTs keep placing on the stale snapshot
        instead of queueing behind a cluster-wide stat (a down remote
        disk's timeout must not stall every placement)."""
        now = time.monotonic()
        with self._usage_lock:
            fresh = self._usage and now - self._usage_ts <= _USAGE_TTL_S
            if fresh or (self._usage_refreshing and self._usage):
                return self._usage
            self._usage_refreshing = True
        try:
            snap = [self._zone_space(z) for z in self.zones]
        finally:
            with self._usage_lock:
                self._usage_refreshing = False
        with self._usage_lock:
            self._usage = snap
            self._usage_ts = time.monotonic()
        return snap

    def _available_space(self, size: int) -> "list[int]":
        """Post-write available bytes per zone; 0 when the write would
        not fit or would push the zone past the fill fraction
        (getZonesAvailableSpace, erasure-zones.go:135-181)."""
        size = max(size, 0)
        out = []
        for free, total in self._usage_snapshot():
            if free < size:
                out.append(0)
                continue
            avail = free - size
            want_left = int(total * (1.0 - _DISK_FILL_FRACTION))
            out.append(0 if avail <= want_left else avail)
        return out

    def _put_zone_index(self, bucket: str, object_name: str,
                        size: int = 0) -> int:
        """Zone for a new write: existing object stays in its zone
        (erasure-zones.go getZoneIdx); otherwise the key is hashed onto
        the cumulative free-space distribution — proportional-to-free
        like the reference's getAvailableZoneIdx but deterministic per
        key, so placement is reproducible and testable."""
        if len(self.zones) == 1:
            return 0
        # probe every zone CONCURRENTLY: the existence check is on
        # the write path, so its wall cost must be one zone's RTT,
        # not the sum (r4 review: the serial walk was O(zones)
        # remote calls per new-object PUT)
        hits = [False] * len(self.zones)

        def probe(i, z):
            try:
                z.get_object_info(bucket, object_name)
                hits[i] = True
            except Exception as exc:
                _log.debug("zone object probe failed", extra=kv(err=str(exc)))

        threads = [
            threading.Thread(
                target=probe, args=(i, z), daemon=True
            )
            for i, z in enumerate(self.zones)
        ]
        for t in threads:
            t.start()
        # join in index order and return at the first hit: an early
        # zone that owns the object answers without waiting for a
        # slow/hung later zone (the serial walk's fast path, kept)
        for i, t in enumerate(threads):
            t.join()
            if hits[i]:  # lowest index wins, like the serial walk
                return i
        avail = self._available_space(size)
        total = sum(avail)
        if total <= 0:
            # every zone past the fill threshold: fall back to rawest
            # free space so writes degrade rather than fail
            snap = self._usage_snapshot()
            return max(range(len(snap)), key=lambda i: snap[i][0])
        frac = zlib.crc32(f"{bucket}/{object_name}".encode()) / 2**32
        choose = int(frac * total)
        acc = 0
        for i, a in enumerate(avail):
            acc += a
            if acc > choose and a > 0:
                return i
        return len(self.zones) - 1

    def _find_zone(self, bucket: str, object_name: str, version_id=""):
        last_err: Exception = api.ObjectNotFound(
            f"{bucket}/{object_name}"
        )
        for z in self.zones:
            try:
                z.get_object_info(bucket, object_name, version_id)
                return z
            except (api.ObjectNotFound, api.VersionNotFound) as e:
                last_err = e
        raise last_err

    # -- buckets ----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        # each zone owns a separate NamespaceLock, so the per-zone
        # bucket locks don't span the fan-out: a zones-level lock
        # keeps a concurrent delete from interleaving between zones
        # (the undoMakeBucket pattern of erasure-zones.go:331 plus
        # the per-bucket lock of erasure-sets.go:604)
        with self._bucket_ops_lock:
            made = []
            try:
                for z in self.zones:
                    z.make_bucket(bucket)
                    made.append(z)
            except Exception:
                for z in made:
                    try:
                        z.delete_bucket(bucket, force=True)
                    except Exception as exc:
                        _log.debug("undo bucket create failed", extra=kv(err=str(exc)))
                raise

    def get_bucket_info(self, bucket: str):
        return self.zones[0].get_bucket_info(bucket)

    def list_buckets(self):
        return self.zones[0].list_buckets()

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        with self._bucket_ops_lock:
            if not force:
                for z in self.zones:
                    if z.list_objects(bucket, max_keys=1).objects:
                        raise api.BucketNotEmpty(bucket)
            for z in self.zones:
                try:
                    z.delete_bucket(bucket, force=True)
                except api.BucketNotFound:
                    pass

    # -- objects ----------------------------------------------------------

    def put_object(self, bucket, object_name, reader, size=-1, metadata=None,
                   versioned=False, compress=None, sse=None):
        self.zones[0].get_bucket_info(bucket)  # bucket must exist
        zi = self._put_zone_index(bucket, object_name, max(size, 0))
        info = self.zones[zi].put_object(
            bucket, object_name, reader, size, metadata, versioned,
            compress, sse,
        )
        object_path_updated(f"{bucket}/{object_name}")
        return info

    def get_object(self, bucket, object_name, writer, offset=0, length=-1,
                   version_id="", sse=None):
        self.zones[0].get_bucket_info(bucket)
        z = self._find_zone(bucket, object_name, version_id)
        return z.get_object(
            bucket, object_name, writer, offset, length, version_id,
            sse,
        )

    def get_object_info(self, bucket, object_name, version_id=""):
        self.zones[0].get_bucket_info(bucket)
        z = self._find_zone(bucket, object_name, version_id)
        return z.get_object_info(bucket, object_name, version_id)

    def device_scan_source(self, bucket, object_name):
        self.zones[0].get_bucket_info(bucket)
        z = self._find_zone(bucket, object_name, "")
        return z.device_scan_source(bucket, object_name)

    def update_object_meta(self, bucket, object_name, updates,
                           version_id=""):
        self.zones[0].get_bucket_info(bucket)
        z = self._find_zone(bucket, object_name, version_id)
        out = z.update_object_meta(
            bucket, object_name, updates, version_id
        )
        object_path_updated(f"{bucket}/{object_name}")
        return out

    def _zone_with_versions(self, bucket, object_name):
        """First zone holding ANY journal entry for the key (incl.
        delete markers, which get_object_info cannot see)."""
        return next(
            (
                z
                for z in self.zones
                if z.has_object_versions(bucket, object_name)
            ),
            None,
        )

    def delete_object(self, bucket, object_name, version_id="",
                      versioned=False, version_suspended=False):
        self.zones[0].get_bucket_info(bucket)
        if not version_id and (versioned or version_suspended):
            # marker goes to the object's zone, or the write zone when
            # the key never existed (AWS still mints a marker)
            z = self._zone_with_versions(bucket, object_name)
            if z is None:
                z = self.zones[self._put_zone_index(bucket, object_name)]
            dinfo = z.delete_object(
                bucket, object_name, "", versioned, version_suspended
            )
            object_path_updated(f"{bucket}/{object_name}")
            return dinfo
        try:
            z = self._find_zone(bucket, object_name, version_id)
        except (api.ObjectNotFound, api.VersionNotFound):
            # the named version may be a delete marker, invisible to
            # get_object_info - fall back to the journal probe
            z = self._zone_with_versions(bucket, object_name)
            if z is None:
                raise
        dinfo = z.delete_object(bucket, object_name, version_id)
        object_path_updated(f"{bucket}/{object_name}")
        return dinfo

    def copy_object(self, src_bucket, src_object, dst_bucket, dst_object,
                    metadata=None, versioned=False, sse_src=None,
                    sse=None):
        from ..utils.pipe import streaming_copy

        src_zone = self._find_zone(src_bucket, src_object)
        if src_bucket == dst_bucket and src_object == dst_object:
            # self-copy: delegate down to the set, whose sequential
            # path avoids the namespace-lock deadlock
            info = src_zone.copy_object(
                src_bucket, src_object, dst_bucket, dst_object,
                metadata, versioned, sse_src, sse,
            )
            object_path_updated(f"{dst_bucket}/{dst_object}")
            return info
        info = src_zone.get_object_info(src_bucket, src_object)
        meta = api.prepare_copy_meta(info, metadata)
        return streaming_copy(
            lambda sink: src_zone.get_object(
                src_bucket, src_object, sink, sse=sse_src
            ),
            lambda source: self.put_object(
                dst_bucket, dst_object, source, info.size, meta,
                versioned=versioned, sse=sse,
            ),
        )

    def heal_object(self, bucket, object_name, version_id="", dry_run=False):
        z = self._find_zone(bucket, object_name, version_id)
        return z.heal_object(bucket, object_name, version_id, dry_run)

    def probe_object_health(self, bucket, object_name, version_id=""):
        # probe zones directly: routing via get_object_info would
        # itself fail on the damaged (below-quorum) objects the probe
        # exists to find
        last: Exception = api.ObjectNotFound(f"{bucket}/{object_name}")
        for z in self.zones:
            try:
                return z.probe_object_health(
                    bucket, object_name, version_id
                )
            except (api.ObjectNotFound, api.VersionNotFound) as e:
                last = e
        raise last

    def heal_bucket(self, bucket, dry_run=False):
        healed = []
        found = False
        for zi, z in enumerate(self.zones):
            try:
                r = z.heal_bucket(bucket, dry_run)
                found = True
                healed.extend((zi, *t) for t in r["healed"])
            except api.BucketNotFound:
                continue
        if not found:
            raise api.BucketNotFound(bucket)
        return {"bucket": bucket, "healed": healed, "dry_run": dry_run}

    # -- listing ----------------------------------------------------------

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000) -> ListObjectsInfo:
        self.zones[0].get_bucket_info(bucket)
        results = [
            z.list_objects(bucket, prefix, marker, delimiter, max_keys)
            for z in self.zones
        ]
        return merge_list_results(results, max_keys)

    def list_object_versions(self, bucket, prefix="", key_marker="",
                             version_id_marker="", delimiter="",
                             max_keys=1000):
        from .sets import merge_version_results

        self.zones[0].get_bucket_info(bucket)
        results = [
            z.list_object_versions(
                bucket, prefix, key_marker, version_id_marker,
                delimiter, max_keys,
            )
            for z in self.zones
        ]
        return merge_version_results(results, max_keys)

    # -- multipart (pin the upload's zone at initiate time) ---------------

    def new_multipart_upload(self, bucket, object_name, metadata=None,
                             sse=None):
        self.zones[0].get_bucket_info(bucket)
        zi = self._put_zone_index(bucket, object_name)
        uid = self.zones[zi].new_multipart_upload(
            bucket, object_name, metadata, sse
        )
        return f"{zi}.{uid}"

    def _upload_zone(self, upload_id: str):
        try:
            zi, uid = upload_id.split(".", 1)
            return self.zones[int(zi)], uid
        except (ValueError, IndexError):
            raise api.InvalidUploadID(upload_id) from None

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        reader, size=-1, sse=None):
        z, uid = self._upload_zone(upload_id)
        return z.put_object_part(
            bucket, object_name, uid, part_number, reader, size, sse
        )

    def list_object_parts(self, bucket, object_name, upload_id,
                          part_marker=0, max_parts=1000):
        z, uid = self._upload_zone(upload_id)
        return z.list_object_parts(
            bucket, object_name, uid, part_marker, max_parts
        )

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for zi, z in enumerate(self.zones):
            for u in z.list_multipart_uploads(bucket, prefix):
                u.upload_id = f"{zi}.{u.upload_id}"
                out.append(u)
        out.sort(key=lambda u: (u.object, u.upload_id))
        return out

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        z, uid = self._upload_zone(upload_id)
        return z.abort_multipart_upload(bucket, object_name, uid)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts, versioned=False):
        z, uid = self._upload_zone(upload_id)
        info = z.complete_multipart_upload(
            bucket, object_name, uid, parts, versioned
        )
        object_path_updated(f"{bucket}/{object_name}")
        return info

    def storage_info(self) -> dict:
        return {"zones": [z.storage_info() for z in self.zones]}
