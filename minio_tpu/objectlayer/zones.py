"""ErasureZones: capacity-routed server pools (cmd/erasure-zones.go).

The top-level ObjectLayer in server mode (newObjectLayer,
server-main.go:559): writes go to the zone with the most free space
(getAvailableZoneIdx, erasure-zones.go:113), reads/deletes query zones in
order, listings merge across zones.  Each zone is an ErasureSets.
"""

from __future__ import annotations

import random

from . import api
from .api import ListObjectsInfo, ObjectLayer
from .sets import ErasureSets, merge_list_results


class ErasureZones(ObjectLayer):
    def __init__(self, zones: list[ErasureSets]):
        if not zones:
            raise ValueError("need at least one zone")
        self.zones = zones

    # -- placement --------------------------------------------------------

    def _zone_free(self, zone: ErasureSets) -> int:
        free = 0
        for s in zone.sets:
            for d in s._online_disks():
                if d is None:
                    continue
                try:
                    free += d.disk_info().free
                except Exception:  # noqa: BLE001
                    pass
        return free

    def _put_zone_index(self, bucket: str, object_name: str) -> int:
        """Zone for a new write: existing object stays in its zone
        (erasure-zones.go getZoneIdx), else weighted by free space."""
        for i, z in enumerate(self.zones):
            try:
                z.get_object_info(bucket, object_name)
                return i
            except Exception:  # noqa: BLE001
                continue
        if len(self.zones) == 1:
            return 0
        frees = [self._zone_free(z) for z in self.zones]
        total = sum(frees)
        if total <= 0:
            return 0
        # deterministic-enough weighted choice (reference uses free
        # threshold ratios, erasure-zones.go:113-184)
        r = random.random() * total
        acc = 0
        for i, f in enumerate(frees):
            acc += f
            if r <= acc:
                return i
        return len(self.zones) - 1

    def _find_zone(self, bucket: str, object_name: str, version_id=""):
        last_err: Exception = api.ObjectNotFound(
            f"{bucket}/{object_name}"
        )
        for z in self.zones:
            try:
                z.get_object_info(bucket, object_name, version_id)
                return z
            except (api.ObjectNotFound, api.VersionNotFound) as e:
                last_err = e
        raise last_err

    # -- buckets ----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        made = []
        try:
            for z in self.zones:
                z.make_bucket(bucket)
                made.append(z)
        except Exception:
            for z in made:
                try:
                    z.delete_bucket(bucket, force=True)
                except Exception:  # noqa: BLE001
                    pass
            raise

    def get_bucket_info(self, bucket: str):
        return self.zones[0].get_bucket_info(bucket)

    def list_buckets(self):
        return self.zones[0].list_buckets()

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        if not force:
            for z in self.zones:
                if z.list_objects(bucket, max_keys=1).objects:
                    raise api.BucketNotEmpty(bucket)
        for z in self.zones:
            try:
                z.delete_bucket(bucket, force=True)
            except api.BucketNotFound:
                pass

    # -- objects ----------------------------------------------------------

    def put_object(self, bucket, object_name, reader, size=-1, metadata=None,
                   versioned=False, compress=None, sse=None):
        self.zones[0].get_bucket_info(bucket)  # bucket must exist
        zi = self._put_zone_index(bucket, object_name)
        return self.zones[zi].put_object(
            bucket, object_name, reader, size, metadata, versioned,
            compress, sse,
        )

    def get_object(self, bucket, object_name, writer, offset=0, length=-1,
                   version_id="", sse=None):
        self.zones[0].get_bucket_info(bucket)
        z = self._find_zone(bucket, object_name, version_id)
        return z.get_object(
            bucket, object_name, writer, offset, length, version_id,
            sse,
        )

    def get_object_info(self, bucket, object_name, version_id=""):
        self.zones[0].get_bucket_info(bucket)
        z = self._find_zone(bucket, object_name, version_id)
        return z.get_object_info(bucket, object_name, version_id)

    def update_object_meta(self, bucket, object_name, updates,
                           version_id=""):
        self.zones[0].get_bucket_info(bucket)
        z = self._find_zone(bucket, object_name, version_id)
        return z.update_object_meta(
            bucket, object_name, updates, version_id
        )

    def _zone_with_versions(self, bucket, object_name):
        """First zone holding ANY journal entry for the key (incl.
        delete markers, which get_object_info cannot see)."""
        return next(
            (
                z
                for z in self.zones
                if z.has_object_versions(bucket, object_name)
            ),
            None,
        )

    def delete_object(self, bucket, object_name, version_id="",
                      versioned=False, version_suspended=False):
        self.zones[0].get_bucket_info(bucket)
        if not version_id and (versioned or version_suspended):
            # marker goes to the object's zone, or the write zone when
            # the key never existed (AWS still mints a marker)
            z = self._zone_with_versions(bucket, object_name)
            if z is None:
                z = self.zones[self._put_zone_index(bucket, object_name)]
            return z.delete_object(
                bucket, object_name, "", versioned, version_suspended
            )
        try:
            z = self._find_zone(bucket, object_name, version_id)
        except (api.ObjectNotFound, api.VersionNotFound):
            # the named version may be a delete marker, invisible to
            # get_object_info - fall back to the journal probe
            z = self._zone_with_versions(bucket, object_name)
            if z is None:
                raise
        return z.delete_object(bucket, object_name, version_id)

    def copy_object(self, src_bucket, src_object, dst_bucket, dst_object,
                    metadata=None, versioned=False, sse_src=None,
                    sse=None):
        from ..utils.pipe import streaming_copy

        src_zone = self._find_zone(src_bucket, src_object)
        if src_bucket == dst_bucket and src_object == dst_object:
            # self-copy: delegate down to the set, whose sequential
            # path avoids the namespace-lock deadlock
            return src_zone.copy_object(
                src_bucket, src_object, dst_bucket, dst_object,
                metadata, versioned, sse_src, sse,
            )
        info = src_zone.get_object_info(src_bucket, src_object)
        meta = api.prepare_copy_meta(info, metadata)
        return streaming_copy(
            lambda sink: src_zone.get_object(
                src_bucket, src_object, sink, sse=sse_src
            ),
            lambda source: self.put_object(
                dst_bucket, dst_object, source, info.size, meta,
                versioned=versioned, sse=sse,
            ),
        )

    def heal_object(self, bucket, object_name, version_id="", dry_run=False):
        z = self._find_zone(bucket, object_name, version_id)
        return z.heal_object(bucket, object_name, version_id, dry_run)

    def heal_bucket(self, bucket, dry_run=False):
        healed = []
        found = False
        for zi, z in enumerate(self.zones):
            try:
                r = z.heal_bucket(bucket, dry_run)
                found = True
                healed.extend((zi, *t) for t in r["healed"])
            except api.BucketNotFound:
                continue
        if not found:
            raise api.BucketNotFound(bucket)
        return {"bucket": bucket, "healed": healed, "dry_run": dry_run}

    # -- listing ----------------------------------------------------------

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000) -> ListObjectsInfo:
        self.zones[0].get_bucket_info(bucket)
        results = [
            z.list_objects(bucket, prefix, marker, delimiter, max_keys)
            for z in self.zones
        ]
        return merge_list_results(results, max_keys)

    def list_object_versions(self, bucket, prefix="", key_marker="",
                             version_id_marker="", delimiter="",
                             max_keys=1000):
        from .sets import merge_version_results

        self.zones[0].get_bucket_info(bucket)
        results = [
            z.list_object_versions(
                bucket, prefix, key_marker, version_id_marker,
                delimiter, max_keys,
            )
            for z in self.zones
        ]
        return merge_version_results(results, max_keys)

    # -- multipart (pin the upload's zone at initiate time) ---------------

    def new_multipart_upload(self, bucket, object_name, metadata=None,
                             sse=None):
        self.zones[0].get_bucket_info(bucket)
        zi = self._put_zone_index(bucket, object_name)
        uid = self.zones[zi].new_multipart_upload(
            bucket, object_name, metadata, sse
        )
        return f"{zi}.{uid}"

    def _upload_zone(self, upload_id: str):
        try:
            zi, uid = upload_id.split(".", 1)
            return self.zones[int(zi)], uid
        except (ValueError, IndexError):
            raise api.InvalidUploadID(upload_id) from None

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        reader, size=-1, sse=None):
        z, uid = self._upload_zone(upload_id)
        return z.put_object_part(
            bucket, object_name, uid, part_number, reader, size, sse
        )

    def list_object_parts(self, bucket, object_name, upload_id,
                          part_marker=0, max_parts=1000):
        z, uid = self._upload_zone(upload_id)
        return z.list_object_parts(
            bucket, object_name, uid, part_marker, max_parts
        )

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for zi, z in enumerate(self.zones):
            for u in z.list_multipart_uploads(bucket, prefix):
                u.upload_id = f"{zi}.{u.upload_id}"
                out.append(u)
        out.sort(key=lambda u: (u.object, u.upload_id))
        return out

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        z, uid = self._upload_zone(upload_id)
        return z.abort_multipart_upload(bucket, object_name, uid)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts, versioned=False):
        z, uid = self._upload_zone(upload_id)
        return z.complete_multipart_upload(
            bucket, object_name, uid, parts, versioned
        )

    def storage_info(self) -> dict:
        return {"zones": [z.storage_info() for z in self.zones]}
