"""FSObjects: single-disk, non-erasure ObjectLayer (cmd/fs-v1.go,
fs-v1-multipart.go, fs-v1-metadata.go).

The standalone mode the reference selects for one endpoint
(server-main.go:561-564): objects live as plain files under
``root/<bucket>/<object>`` (browsable in place, like fs-v1), metadata
documents under ``root/.fs.sys/meta/<bucket>/<object>.json`` (the
fs.json analogue), multipart staging under ``root/.fs.sys/multipart``.
Writes stage to tmp then os.replace (atomic commit); there is no
erasure, bitrot framing, or versioning - exactly the reference's FS
contract (versioned calls raise NotImplementedError -> S3
NotImplemented).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import threading
import time
import uuid

from ..codec import compress as compmod
from ..utils.hashreader import HashReader
from ..crawler.updatetracker import object_path_updated
from . import api
from .api import (
    BucketExists,
    BucketInfo,
    BucketNotEmpty,
    BucketNotFound,
    CompletePart,
    ListObjectsInfo,
    ObjectInfo,
    ObjectLayer,
    ObjectNotFound,
    check_bucket_name,
    check_object_name,
    prepare_copy_meta,
)

SYS_DIR = ".fs.sys"


class FSObjects(ObjectLayer):
    """One-directory object store (NewFSObjectLayer)."""

    def __init__(self, root: str, min_part_size: "int | None" = None):
        self.root = os.path.abspath(root)
        os.makedirs(os.path.join(root, SYS_DIR, "tmp"), exist_ok=True)
        os.makedirs(os.path.join(root, SYS_DIR, "meta"), exist_ok=True)
        os.makedirs(
            os.path.join(root, SYS_DIR, "multipart"), exist_ok=True
        )
        if min_part_size is None:
            from .erasure_multipart import MIN_PART_SIZE

            min_part_size = MIN_PART_SIZE
        self.min_part_size = min_part_size
        self._mu = threading.RLock()

    # -- paths ------------------------------------------------------------

    def _bucket_dir(self, bucket: str) -> str:
        if bucket == api.META_BUCKET:
            # internal documents (IAM, bucket metadata) share the
            # data namespace under the sys dir
            return os.path.join(self.root, SYS_DIR, "metabucket")
        return os.path.join(self.root, bucket)

    def _obj_path(self, bucket: str, name: str) -> str:
        base = self._bucket_dir(bucket)  # absolute (root is abspath'd)
        p = os.path.normpath(os.path.join(base, name))
        # must stay strictly INSIDE the bucket dir: a trailing-sep
        # prefix check, so /root/bkt2 can't pass as inside /root/bkt
        if not p.startswith(base + os.sep):
            raise api.InvalidObjectName(name)
        return p

    def _meta_path(self, bucket: str, name: str) -> str:
        return os.path.join(
            self.root, SYS_DIR, "meta", bucket, name + ".fs.json"
        )

    # -- buckets ----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        check_bucket_name(bucket)
        d = self._bucket_dir(bucket)
        if os.path.isdir(d) and bucket != api.META_BUCKET:
            raise BucketExists(bucket)
        os.makedirs(d, exist_ok=True)

    def _require_bucket(self, bucket: str) -> str:
        d = self._bucket_dir(bucket)
        if bucket == api.META_BUCKET:
            os.makedirs(d, exist_ok=True)
            return d
        if not os.path.isdir(d):
            raise BucketNotFound(bucket)
        return d

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        d = self._require_bucket(bucket)
        try:
            return BucketInfo(bucket, int(os.stat(d).st_ctime_ns))
        except FileNotFoundError:
            # concurrent delete won between isdir and stat
            raise BucketNotFound(bucket) from None

    def list_buckets(self) -> "list[BucketInfo]":
        out = []
        for name in sorted(os.listdir(self.root)):
            if name.startswith("."):
                continue
            full = os.path.join(self.root, name)
            if os.path.isdir(full):
                out.append(
                    BucketInfo(name, int(os.stat(full).st_ctime_ns))
                )
        return out

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        d = self._require_bucket(bucket)
        if not force and any(os.scandir(d)):
            raise BucketNotEmpty(bucket)
        shutil.rmtree(d, ignore_errors=True)
        shutil.rmtree(
            os.path.join(self.root, SYS_DIR, "meta", bucket),
            ignore_errors=True,
        )

    # -- metadata ---------------------------------------------------------

    def _load_meta(self, bucket: str, name: str) -> dict:
        try:
            with open(self._meta_path(bucket, name), encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _store_meta(self, bucket: str, name: str, meta: dict) -> None:
        p = self._meta_path(bucket, name)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f)
        os.replace(tmp, p)

    # -- objects ----------------------------------------------------------

    def put_object(
        self, bucket, object_name, reader, size=-1, metadata=None,
        versioned=False, compress=None, sse=None,
    ) -> ObjectInfo:
        check_object_name(object_name)
        self._require_bucket(bucket)
        if sse is not None:
            raise NotImplementedError("SSE-C on the FS backend")
        hreader = (
            reader
            if isinstance(reader, HashReader)
            else HashReader(reader, size)
        )
        meta = dict(metadata or {})
        if compress is None:
            compress = compmod.should_compress(
                object_name, meta.get("content-type", ""), size
            )
        src = compmod.CompressReader(hreader) if compress else hreader
        tmp = os.path.join(
            self.root, SYS_DIR, "tmp", uuid.uuid4().hex
        )
        stored = 0
        with open(tmp, "wb") as f:
            while True:
                chunk = src.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
                stored += len(chunk)
        dst = self._obj_path(bucket, object_name)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(tmp, dst)
        etag = hreader.etag()
        actual = hreader.bytes_read
        meta.setdefault("etag", etag)
        if compress:
            meta[compmod.META_COMPRESSION] = compmod.ALGORITHM
            meta[compmod.META_ACTUAL_SIZE] = str(actual)
        mod = time.time_ns()
        self._store_meta(
            bucket, object_name,
            {"meta": meta, "size": stored, "actual": actual, "mod": mod},
        )
        object_path_updated(f"{bucket}/{object_name}")
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=actual,
            mod_time_ns=mod,
            etag=etag,
            content_type=meta.get("content-type", ""),
            user_defined=meta,
        )

    def _stat(self, bucket, object_name) -> "tuple[str, dict]":
        p = self._obj_path(bucket, object_name)
        if not os.path.isfile(p):
            raise ObjectNotFound(f"{bucket}/{object_name}")
        return p, self._load_meta(bucket, object_name)

    def get_object_info(
        self, bucket, object_name, version_id=""
    ) -> ObjectInfo:
        check_object_name(object_name)
        self._require_bucket(bucket)
        if version_id and version_id != "null":
            raise api.VersionNotFound(version_id)
        p, doc = self._stat(bucket, object_name)
        meta = doc.get("meta", {})
        st = os.stat(p)
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=doc.get("actual", st.st_size),
            mod_time_ns=doc.get("mod", int(st.st_mtime_ns)),
            etag=meta.get("etag", ""),
            content_type=meta.get("content-type", ""),
            user_defined=meta,
        )

    def get_object(
        self, bucket, object_name, writer, offset=0, length=-1,
        version_id="", sse=None,
    ) -> ObjectInfo:
        info = self.get_object_info(bucket, object_name, version_id)
        p, doc = self._stat(bucket, object_name)
        meta = doc.get("meta", {})
        logical = info.size
        if length < 0:
            length = logical - offset
        if offset < 0 or offset + length > logical:
            raise api.InvalidRange(f"{offset}+{length} of {logical}")
        compressed = bool(meta.get(compmod.META_COMPRESSION))
        with open(p, "rb") as f:
            if not compressed:
                f.seek(offset)
                remaining = length
                while remaining > 0:
                    chunk = f.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    writer.write(chunk)
                    remaining -= len(chunk)
            else:
                # decompress-and-skip, like the erasure read path
                dec = compmod.DecompressWriter(writer, offset, length)
                try:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        dec.write(chunk)
                    dec.finish()
                except compmod.RangeSatisfied:
                    pass
        return info

    def update_object_meta(
        self, bucket, object_name, updates: dict, version_id=""
    ) -> ObjectInfo:
        with self._mu:
            p, doc = self._stat(bucket, object_name)
            meta = doc.get("meta", {})
            for k, v in updates.items():
                if v is None:
                    meta.pop(k, None)
                else:
                    meta[k] = v
            doc["meta"] = meta
            self._store_meta(bucket, object_name, doc)
        object_path_updated(f"{bucket}/{object_name}")
        return self.get_object_info(bucket, object_name)

    def delete_object(
        self, bucket, object_name, version_id="", versioned=False,
        version_suspended=False,
    ) -> ObjectInfo:
        check_object_name(object_name)
        self._require_bucket(bucket)
        p = self._obj_path(bucket, object_name)
        if not os.path.isfile(p):
            raise ObjectNotFound(f"{bucket}/{object_name}")
        os.remove(p)
        try:
            os.remove(self._meta_path(bucket, object_name))
        except OSError:
            pass
        # prune now-empty parent dirs up to the bucket root
        d = os.path.dirname(p)
        stop = self._bucket_dir(bucket)
        while d != stop:
            try:
                os.rmdir(d)
            except OSError:
                break
            d = os.path.dirname(d)
        object_path_updated(f"{bucket}/{object_name}")
        return ObjectInfo(bucket=bucket, name=object_name)

    def copy_object(
        self, src_bucket, src_object, dst_bucket, dst_object,
        metadata=None, versioned=False, sse_src=None, sse=None,
    ) -> ObjectInfo:
        if sse is not None or sse_src is not None:
            # silently dropping an encryption demand would store
            # plaintext behind a 200
            raise NotImplementedError("SSE on the FS backend")
        src_info = self.get_object_info(src_bucket, src_object)
        meta = prepare_copy_meta(src_info, metadata)
        compmod.strip_internal_meta(meta)
        buf = io.BytesIO()
        self.get_object(src_bucket, src_object, buf)
        data = buf.getvalue()
        return self.put_object(
            dst_bucket, dst_object, io.BytesIO(data), len(data), meta
        )

    # -- listing ----------------------------------------------------------

    def _walk(self, bucket: str):
        base = self._bucket_dir(bucket)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames.sort()
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                yield os.path.relpath(full, base).replace(os.sep, "/")

    def list_objects(
        self, bucket, prefix="", marker="", delimiter="", max_keys=1000
    ) -> ListObjectsInfo:
        self._require_bucket(bucket)
        out = ListObjectsInfo()
        prefixes: "set[str]" = set()
        last_emitted = marker
        names = sorted(self._walk(bucket))
        for name in names:
            if not name.startswith(prefix) or name <= marker:
                continue
            if len(out.objects) + len(prefixes) >= max_keys:
                # keys AND CommonPrefixes count toward max-keys (S3
                # pagination contract)
                out.is_truncated = True
                out.next_marker = last_emitted
                break
            if delimiter:
                rest = name[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if cp not in prefixes and cp > marker:
                        prefixes.add(cp)
                        last_emitted = cp
                    continue
            out.objects.append(self.get_object_info(bucket, name))
            last_emitted = name
        out.prefixes = sorted(prefixes)
        return out

    def iter_all_objects(self, bucket: str):
        """Streaming full-bucket walk (crawler seam): yields
        ObjectInfo without materializing or re-sorting the namespace
        per page."""
        self._require_bucket(bucket)
        for name in self._walk(bucket):
            try:
                yield self.get_object_info(bucket, name)
            except ObjectNotFound:
                continue

    def has_object_versions(self, bucket, object_name) -> bool:
        try:
            self._stat(bucket, object_name)
            return True
        except ObjectNotFound:
            return False

    def list_object_versions(self, *a, **k):
        raise NotImplementedError("versioning on the FS backend")

    # -- multipart (fs-v1-multipart.go) ------------------------------------

    def _upload_dir(self, upload_id: str) -> str:
        return os.path.join(self.root, SYS_DIR, "multipart", upload_id)

    def new_multipart_upload(
        self, bucket, object_name, metadata=None, sse=None, **kw
    ) -> str:
        check_object_name(object_name)
        self._require_bucket(bucket)
        if sse is not None:
            raise NotImplementedError("SSE on the FS backend")
        uid = uuid.uuid4().hex
        d = self._upload_dir(uid)
        os.makedirs(d)
        with open(
            os.path.join(d, "upload.json"), "w", encoding="utf-8"
        ) as f:
            json.dump(
                {
                    "bucket": bucket,
                    "object": object_name,
                    "meta": dict(metadata or {}),
                    "started": time.time_ns(),
                },
                f,
            )
        return uid

    def _upload_doc(self, bucket, object_name, upload_id) -> dict:
        try:
            with open(
                os.path.join(self._upload_dir(upload_id), "upload.json"),
                encoding="utf-8",
            ) as f:
                doc = json.load(f)
        except OSError:
            raise api.InvalidUploadID(upload_id) from None
        if doc.get("bucket") != bucket or doc.get("object") != object_name:
            raise api.InvalidUploadID(upload_id)
        return doc

    def put_object_part(
        self, bucket, object_name, upload_id, part_number, reader,
        size=-1, sse=None, **kw
    ):
        from .api import PartInfo

        if sse is not None:
            raise NotImplementedError("SSE on the FS backend")
        self._upload_doc(bucket, object_name, upload_id)
        hreader = (
            reader
            if isinstance(reader, HashReader)
            else HashReader(reader, size)
        )
        tmp = os.path.join(
            self.root, SYS_DIR, "tmp", uuid.uuid4().hex
        )
        n = 0
        with open(tmp, "wb") as f:
            while True:
                chunk = hreader.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
                n += len(chunk)
        d = self._upload_dir(upload_id)
        etag = hreader.etag()
        # persist the etag next to the part: complete validates the
        # client's CompletePart etags against these, and listing never
        # re-reads part bytes to hash them
        with open(
            os.path.join(d, f"part.{part_number}.etag"), "w",
            encoding="utf-8",
        ) as f:
            f.write(etag)
        os.replace(tmp, os.path.join(d, f"part.{part_number}"))
        return PartInfo(part_number, etag, n, n, time.time_ns())

    def list_object_parts(
        self, bucket, object_name, upload_id, **kw
    ) -> list:
        from .api import PartInfo

        self._upload_doc(bucket, object_name, upload_id)
        out = []
        d = self._upload_dir(upload_id)
        for fn in sorted(os.listdir(d)):
            if not fn.startswith("part.") or fn.endswith(".etag"):
                continue
            num = int(fn.split(".", 1)[1])
            full = os.path.join(d, fn)
            etag = self._part_etag(d, num)
            size = os.path.getsize(full)
            out.append(
                PartInfo(
                    num, etag, size, size,
                    int(os.stat(full).st_mtime_ns),
                )
            )
        return sorted(out, key=lambda p: p.part_number)

    @staticmethod
    def _part_etag(upload_dir: str, num: int) -> str:
        try:
            with open(
                os.path.join(upload_dir, f"part.{num}.etag"),
                encoding="utf-8",
            ) as f:
                return f.read().strip()
        except OSError:
            return ""

    def list_multipart_uploads(self, bucket, prefix="") -> list:
        out = []
        base = os.path.join(self.root, SYS_DIR, "multipart")
        for uid in sorted(os.listdir(base)):
            try:
                with open(
                    os.path.join(base, uid, "upload.json"),
                    encoding="utf-8",
                ) as f:
                    doc = json.load(f)
            except OSError:
                continue
            if doc.get("bucket") == bucket and doc.get(
                "object", ""
            ).startswith(prefix):
                out.append(
                    {
                        "upload_id": uid,
                        "object": doc["object"],
                        "initiated_ns": doc.get("started", 0),
                    }
                )
        return out

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        self._upload_doc(bucket, object_name, upload_id)
        shutil.rmtree(self._upload_dir(upload_id), ignore_errors=True)

    def complete_multipart_upload(
        self, bucket, object_name, upload_id,
        parts: "list[CompletePart]", versioned=False, **kw
    ) -> ObjectInfo:
        doc = self._upload_doc(bucket, object_name, upload_id)
        d = self._upload_dir(upload_id)
        # validate order + sizes + etags (S3 complete-multipart rules)
        last = 0
        md5s = []
        total = 0
        for i, cp in enumerate(parts):
            if cp.part_number <= last:
                raise api.InvalidPartOrder(str(cp.part_number))
            last = cp.part_number
            p = os.path.join(d, f"part.{cp.part_number}")
            if not os.path.isfile(p):
                raise api.InvalidPart(str(cp.part_number))
            stored_etag = self._part_etag(d, cp.part_number)
            if cp.etag.strip('"') != stored_etag:
                raise api.InvalidPart(
                    f"part {cp.part_number} etag mismatch"
                )
            size = os.path.getsize(p)
            if i < len(parts) - 1 and size < self.min_part_size:
                raise api.EntityTooSmall(str(cp.part_number))
            md5s.append(bytes.fromhex(stored_etag))
            total += size
        tmp = os.path.join(self.root, SYS_DIR, "tmp", uuid.uuid4().hex)
        with open(tmp, "wb") as out:
            for cp in parts:
                with open(
                    os.path.join(d, f"part.{cp.part_number}"), "rb"
                ) as f:
                    shutil.copyfileobj(f, out)
        dst = self._obj_path(bucket, object_name)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(tmp, dst)
        etag = (
            hashlib.md5(b"".join(md5s)).hexdigest() + f"-{len(parts)}"
        )
        meta = dict(doc.get("meta", {}))
        meta["etag"] = etag
        mod = time.time_ns()
        self._store_meta(
            bucket, object_name,
            {"meta": meta, "size": total, "actual": total, "mod": mod},
        )
        shutil.rmtree(d, ignore_errors=True)
        object_path_updated(f"{bucket}/{object_name}")
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=total,
            mod_time_ns=mod,
            etag=etag,
            content_type=meta.get("content-type", ""),
            user_defined=meta,
        )

    # -- heal / info -------------------------------------------------------

    def heal_bucket(self, bucket: str, dry_run: bool = False) -> dict:
        self._require_bucket(bucket)
        return {"bucket": bucket, "healed": 0}

    def heal_object(self, bucket, object_name, version_id="",
                    dry_run=False) -> dict:
        self._stat(bucket, object_name)
        return {"object": object_name, "healed": 0}

    def storage_info(self) -> dict:
        st = os.statvfs(self.root)
        return {
            "backend": "fs",
            "disks": 1,
            "online": 1,
            "total": st.f_blocks * st.f_frsize,
            "free": st.f_bavail * st.f_frsize,
        }
