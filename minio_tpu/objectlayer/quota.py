"""Bucket quota (cmd/bucket-quota.go): hard quotas enforced on PUT,
FIFO quotas enforced by the crawler's eviction pass.

Config document (madmin BucketQuota JSON): ``{"quota": <bytes>,
"quotatype": "hard" | "fifo"}``, stored in the bucket metadata.
"""

from __future__ import annotations

import dataclasses
import json


class QuotaError(Exception):
    pass


@dataclasses.dataclass
class QuotaConfig:
    quota: int = 0  # bytes; 0 = unlimited
    quota_type: str = "hard"  # hard | fifo

    @classmethod
    def from_json(cls, raw: "str | bytes") -> "QuotaConfig":
        try:
            doc = json.loads(raw)
        except ValueError:
            raise QuotaError("malformed quota JSON") from None
        if not isinstance(doc, dict):
            raise QuotaError("quota document must be an object")
        try:
            quota = int(doc.get("quota", 0))
        except (TypeError, ValueError):
            raise QuotaError("quota must be an integer") from None
        if quota < 0:
            raise QuotaError("quota must be >= 0")
        qt = str(doc.get("quotatype", "hard")).lower()
        if qt not in ("hard", "fifo"):
            raise QuotaError(f"unknown quotatype {qt!r}")
        return cls(quota, qt)

    def to_json(self) -> str:
        return json.dumps(
            {"quota": self.quota, "quotatype": self.quota_type}
        )


def config_for(bucket_meta_sys, bucket: str) -> "QuotaConfig | None":
    try:
        raw = bucket_meta_sys.get(bucket).quota_json
    except Exception:  # noqa: BLE001
        return None
    if not raw:
        return None
    try:
        cfg = QuotaConfig.from_json(raw)
    except QuotaError:
        return None
    return cfg if cfg.quota > 0 else None


def bucket_size(server, bucket: str) -> int:
    """Current logical bytes in the bucket: crawler snapshot when one
    exists (enforceBucketQuota consults the dataUsageCache), else a
    direct list walk."""
    crawler = getattr(server, "crawler", None)
    if crawler is not None:
        bu = crawler.usage().buckets.get(bucket)
        if bu is not None:
            return bu.size
    total = 0
    marker = ""
    while True:
        res = server.object_layer.list_objects(
            bucket, "", marker, "", 1000
        )
        total += sum(o.size for o in res.objects if not o.is_dir)
        if not res.is_truncated:
            return total
        marker = res.next_marker


def enforce_put(server, bucket: str, add_size: int) -> None:
    """Raise when a hard quota would be exceeded by add_size bytes
    (enforceBucketQuota on PutObject)."""
    cfg = config_for(server.bucket_meta, bucket)
    if cfg is None or cfg.quota_type != "hard":
        return
    if add_size < 0:
        add_size = 0
    if bucket_size(server, bucket) + add_size > cfg.quota:
        from ..server.s3errors import S3Error

        raise S3Error("XMinioAdminBucketQuotaExceeded")
