"""Object lifecycle management (pkg/bucket/lifecycle)."""

from .lifecycle import (  # noqa: F401
    Action,
    Lifecycle,
    LifecycleError,
    ObjectOpts,
    Rule,
)
