"""Bucket lifecycle configuration + evaluation
(pkg/bucket/lifecycle/lifecycle.go ComputeAction,
pkg/bucket/lifecycle/rule.go validation).

Wire format is the S3 LifecycleConfiguration XML::

    <LifecycleConfiguration>
      <Rule>
        <ID>expire-logs</ID>
        <Status>Enabled</Status>
        <Filter><Prefix>logs/</Prefix></Filter>
        <Expiration><Days>30</Days></Expiration>
        <NoncurrentVersionExpiration>
          <NoncurrentDays>7</NoncurrentDays>
        </NoncurrentVersionExpiration>
        <AbortIncompleteMultipartUpload>
          <DaysAfterInitiation>3</DaysAfterInitiation>
        </AbortIncompleteMultipartUpload>
      </Rule>
    </LifecycleConfiguration>

Evaluation is pure: ``compute_action(opts)`` maps an object's state to
the action the crawler should take, exactly the ComputeAction seam the
reference's data crawler drives (cmd/data-crawler.go:877-907).
"""

from __future__ import annotations

import dataclasses
import datetime
import xml.etree.ElementTree as ET


class LifecycleError(Exception):
    """Malformed or invalid lifecycle configuration."""


class Action:
    NONE = "none"
    DELETE = "delete"  # expire the (unversioned/current) object
    DELETE_VERSION = "delete-version"  # expire a noncurrent version
    ABORT_MULTIPART = "abort-multipart"


def _local(tag: str) -> str:
    return tag.split("}")[-1]


def _child(el: "ET.Element | None", name: str) -> "ET.Element | None":
    if el is None:
        return None
    for c in el:
        if _local(c.tag) == name:
            return c
    return None


def _text(el: "ET.Element | None", name: str) -> str:
    c = _child(el, name)
    return (c.text or "").strip() if c is not None else ""


def _parse_days(el: "ET.Element | None", name: str) -> "int | None":
    raw = _text(el, name)
    if not raw:
        return None
    try:
        days = int(raw)
    except ValueError:
        raise LifecycleError(f"{name} must be an integer") from None
    if days <= 0:
        raise LifecycleError(f"{name} must be positive")
    return days


def _parse_date(el: "ET.Element | None") -> "float | None":
    raw = _text(el, "Date")
    if not raw:
        return None
    try:
        dt = datetime.datetime.fromisoformat(raw.replace("Z", "+00:00"))
    except ValueError:
        raise LifecycleError(f"bad Expiration Date {raw!r}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()


def _parse_tag(el: ET.Element) -> "tuple[str, str]":
    key = _text(el, "Key")
    if not key:
        raise LifecycleError("Filter Tag must carry a Key")
    return key, _text(el, "Value")


def _parse_filter(
    filt: "ET.Element | None",
) -> "tuple[str, list[tuple[str, str]]]":
    """(prefix, tags) from a <Filter> holding exactly one of
    Prefix | Tag | And (filter.go Validate)."""
    if filt is None:
        return "", []
    prefix_el = _child(filt, "Prefix")
    tag_el = _child(filt, "Tag")
    and_el = _child(filt, "And")
    populated = sum(
        1
        for el, check in (
            (prefix_el, prefix_el is not None and (prefix_el.text or "").strip()),
            (tag_el, tag_el is not None),
            (and_el, and_el is not None),
        )
        if check
    )
    if populated > 1:
        raise LifecycleError(
            "Filter must have exactly one of Prefix, Tag, or And"
        )
    if and_el is not None:
        tags = [
            _parse_tag(c) for c in and_el if _local(c.tag) == "Tag"
        ]
        keys = [k for k, _ in tags]
        if len(keys) != len(set(keys)):
            raise LifecycleError("duplicate Tag key in And")
        return _text(and_el, "Prefix"), tags
    if tag_el is not None:
        return "", [_parse_tag(tag_el)]
    return _text(filt, "Prefix"), []


@dataclasses.dataclass
class Rule:
    id: str = ""
    status: str = "Enabled"
    prefix: str = ""
    # tag scoping (pkg/bucket/lifecycle/filter.go TestTags): every
    # (key, value) here must appear among the object's tags
    tags: "list[tuple[str, str]]" = dataclasses.field(
        default_factory=list
    )
    expire_days: "int | None" = None
    expire_date_ts: "float | None" = None
    expire_delete_marker: bool = False
    noncurrent_days: "int | None" = None
    abort_multipart_days: "int | None" = None

    @property
    def enabled(self) -> bool:
        return self.status == "Enabled"

    def match_prefix(self, key: str) -> bool:
        return key.startswith(self.prefix)

    def match_tags(self, user_tags: str) -> bool:
        """user_tags is the URL-encoded x-amz-tagging form the object
        layer stores (the reference passes ObjectOpts.UserTags the
        same way, lifecycle.go:169)."""
        if not self.tags:
            return True
        import urllib.parse

        have = dict(
            urllib.parse.parse_qsl(user_tags, keep_blank_values=True)
        )
        return all(have.get(k) == v for k, v in self.tags)


@dataclasses.dataclass
class ObjectOpts:
    """Everything ComputeAction looks at (lifecycle.go ObjectOpts)."""

    name: str
    mod_time_ns: int = 0
    is_latest: bool = True
    delete_marker: bool = False
    num_versions: int = 1
    # URL-encoded object tags (ObjectOpts.UserTags)
    user_tags: str = ""
    # for noncurrent versions: when the version BECAME noncurrent
    # (successor mod time); falls back to the version's own mod time
    successor_mod_time_ns: int = 0


@dataclasses.dataclass
class Lifecycle:
    rules: "list[Rule]" = dataclasses.field(default_factory=list)

    # -- parsing ----------------------------------------------------------

    @classmethod
    def from_xml(cls, raw: bytes) -> "Lifecycle":
        try:
            root = ET.fromstring(raw)
        except ET.ParseError as e:
            raise LifecycleError(f"malformed XML: {e}") from None
        if _local(root.tag) not in (
            "LifecycleConfiguration",
            "BucketLifecycleConfiguration",
        ):
            raise LifecycleError(
                f"unexpected root element {_local(root.tag)}"
            )
        rules = []
        for rel in root:
            if _local(rel.tag) != "Rule":
                continue
            status = _text(rel, "Status")
            if status not in ("Enabled", "Disabled"):
                raise LifecycleError("Rule Status must be Enabled|Disabled")
            # Transition actions are unsupported - reject loudly like
            # the reference (errTransitionUnsupported, pkg/bucket/
            # lifecycle/transition.go), never silently drop an action
            # the user asked for
            for unsup in ("Transition", "NoncurrentVersionTransition"):
                if _child(rel, unsup) is not None:
                    raise LifecycleError(
                        f"Specifying <{unsup}> is not supported"
                    )
            # <Filter> holds exactly one of Prefix | Tag | And
            # (filter.go:66 Validate); legacy top-level <Prefix> also
            # accepted
            filt = _child(rel, "Filter")
            prefix, tags = _parse_filter(filt)
            if not prefix:
                prefix = _text(rel, "Prefix")
            exp = _child(rel, "Expiration")
            nve = _child(rel, "NoncurrentVersionExpiration")
            aimu = _child(rel, "AbortIncompleteMultipartUpload")
            rule = Rule(
                id=_text(rel, "ID"),
                status=status,
                prefix=prefix,
                tags=tags,
                expire_days=_parse_days(exp, "Days"),
                expire_date_ts=_parse_date(exp),
                expire_delete_marker=(
                    _text(exp, "ExpiredObjectDeleteMarker") == "true"
                ),
                noncurrent_days=_parse_days(nve, "NoncurrentDays"),
                abort_multipart_days=_parse_days(
                    aimu, "DaysAfterInitiation"
                ),
            )
            if rule.expire_days and rule.expire_date_ts:
                raise LifecycleError(
                    "Expiration takes Days OR Date, not both"
                )
            if not (
                rule.expire_days
                or rule.expire_date_ts
                or rule.expire_delete_marker
                or rule.noncurrent_days
                or rule.abort_multipart_days
            ):
                raise LifecycleError(
                    f"rule {rule.id!r} specifies no action"
                )
            rules.append(rule)
        if not rules:
            raise LifecycleError("no rules")
        if len(rules) > 1000:
            raise LifecycleError("too many rules (max 1000)")
        ids = [r.id for r in rules if r.id]
        if len(ids) != len(set(ids)):
            raise LifecycleError("duplicate rule ID")
        return cls(rules)

    def to_xml(self) -> bytes:
        root = ET.Element("LifecycleConfiguration")
        for r in self.rules:
            rel = ET.SubElement(root, "Rule")
            if r.id:
                ET.SubElement(rel, "ID").text = r.id
            ET.SubElement(rel, "Status").text = r.status
            f = ET.SubElement(rel, "Filter")
            if r.tags and (r.prefix or len(r.tags) > 1):
                a = ET.SubElement(f, "And")
                if r.prefix:
                    ET.SubElement(a, "Prefix").text = r.prefix
                for k, v in r.tags:
                    t = ET.SubElement(a, "Tag")
                    ET.SubElement(t, "Key").text = k
                    ET.SubElement(t, "Value").text = v
            elif r.tags:
                t = ET.SubElement(f, "Tag")
                ET.SubElement(t, "Key").text = r.tags[0][0]
                ET.SubElement(t, "Value").text = r.tags[0][1]
            elif r.prefix:
                ET.SubElement(f, "Prefix").text = r.prefix
            if r.expire_days or r.expire_date_ts or r.expire_delete_marker:
                e = ET.SubElement(rel, "Expiration")
                if r.expire_days:
                    ET.SubElement(e, "Days").text = str(r.expire_days)
                if r.expire_date_ts:
                    ET.SubElement(e, "Date").text = (
                        datetime.datetime.fromtimestamp(
                            r.expire_date_ts, tz=datetime.timezone.utc
                        ).strftime("%Y-%m-%dT%H:%M:%SZ")
                    )
                if r.expire_delete_marker:
                    ET.SubElement(
                        e, "ExpiredObjectDeleteMarker"
                    ).text = "true"
            if r.noncurrent_days:
                n = ET.SubElement(rel, "NoncurrentVersionExpiration")
                ET.SubElement(n, "NoncurrentDays").text = str(
                    r.noncurrent_days
                )
            if r.abort_multipart_days:
                a = ET.SubElement(rel, "AbortIncompleteMultipartUpload")
                ET.SubElement(a, "DaysAfterInitiation").text = str(
                    r.abort_multipart_days
                )
        return (
            b'<?xml version="1.0" encoding="UTF-8"?>\n'
            + ET.tostring(root)
        )

    # -- evaluation -------------------------------------------------------

    def compute_action(
        self, opts: ObjectOpts, now_ns: "int | None" = None
    ) -> str:
        """The crawler seam (lifecycle.go:237 ComputeAction)."""
        import time as _t

        now = now_ns if now_ns is not None else _t.time_ns()
        day_ns = 86400 * 10**9
        for r in self.rules:
            if not r.enabled or not r.match_prefix(opts.name):
                continue
            if not opts.is_latest:
                # tag gate applies here too.  DELIBERATE DIVERGENCE:
                # the reference's FilterActionableRules exempts
                # NoncurrentVersionExpiration from the tag test
                # (lifecycle.go:165-167), which lets a tag-scoped rule
                # destroy noncurrent versions of objects the user
                # scoped OUT - AWS applies the filter, and so do we
                if r.noncurrent_days and r.match_tags(opts.user_tags):
                    since = (
                        opts.successor_mod_time_ns or opts.mod_time_ns
                    )
                    if now - since >= r.noncurrent_days * day_ns:
                        return Action.DELETE_VERSION
                continue
            if opts.delete_marker:
                # a marker whose older versions are all gone is litter
                if r.expire_delete_marker and opts.num_versions == 1:
                    return Action.DELETE_VERSION
                continue
            # tag scoping applies to the expiration family only; the
            # delete-marker and noncurrent actions above act per-key
            # regardless of tags (FilterActionableRules,
            # lifecycle.go:141-173)
            if not r.match_tags(opts.user_tags):
                continue
            if r.expire_date_ts and now >= r.expire_date_ts * 10**9:
                return Action.DELETE
            if (
                r.expire_days
                and opts.mod_time_ns
                and now - opts.mod_time_ns >= r.expire_days * day_ns
            ):
                return Action.DELETE
        return Action.NONE

    def abort_multipart_before_ns(
        self, key: str, now_ns: "int | None" = None
    ) -> "int | None":
        """Cutoff before which an incomplete upload for ``key`` should
        be aborted, or None when no rule applies."""
        import time as _t

        now = now_ns if now_ns is not None else _t.time_ns()
        day_ns = 86400 * 10**9
        cutoffs = [
            now - r.abort_multipart_days * day_ns
            for r in self.rules
            if r.enabled and r.abort_multipart_days and r.match_prefix(key)
        ]
        return max(cutoffs) if cutoffs else None
