"""CLI entry: ``python -m minio_tpu.server [--address host:port] args...``

The `minio server` analogue (cmd/server-main.go): each positional arg is
one zone; ellipses patterns expand to that zone's drives
(``/data/disk{1...8}``), drives are partitioned into erasure sets
(endpoint-ellipses.go GCD math), format.json is created/quorum-loaded per
zone, and the object layer is Zones(Sets(Objects)) exactly like
newObjectLayer (server-main.go:559-567).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def build_object_layer(zone_args: list[str], parity: "int | None" = None):
    """Expand args -> formatted, ordered disks -> zones object layer."""
    from ..objectlayer.format import load_or_init_format
    from ..objectlayer.sets import ErasureSets
    from ..objectlayer.zones import ErasureZones
    from ..storage.xl import XLStorage
    from ..utils import ellipses

    zones = []
    for zarg in zone_args:
        paths = ellipses.expand(zarg)
        if len(paths) < 2:
            raise SystemExit(
                f"zone {zarg!r} expands to {len(paths)} drives; need >= 2"
            )
        set_count, drives_per_set = ellipses.layout(len(paths))
        disks = [XLStorage(p) for p in paths]
        _, ordered = load_or_init_format(
            disks, set_count, drives_per_set
        )
        zones.append(
            ErasureSets(
                ordered, set_count, drives_per_set, parity_blocks=parity
            )
        )
    return ErasureZones(zones)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="minio-tpu server")
    p.add_argument(
        "zones",
        nargs="+",
        help="one arg per zone; ellipses expand: /data/disk{1...8}",
    )
    p.add_argument("--address", default="0.0.0.0:9000")
    p.add_argument(
        "--access-key",
        default=os.environ.get("MINIO_ACCESS_KEY", "minioadmin"),
    )
    p.add_argument(
        "--secret-key",
        default=os.environ.get("MINIO_SECRET_KEY", "minioadmin"),
    )
    p.add_argument("--region", default="us-east-1")
    p.add_argument(
        "--parity", type=int, default=None,
        help="parity drives per set (default: half)",
    )
    args = p.parse_args(argv)

    from .http import S3Server

    ol = build_object_layer(args.zones, args.parity)
    srv = S3Server(
        ol,
        address=args.address,
        access_key=args.access_key,
        secret_key=args.secret_key,
        region=args.region,
    ).start()
    si = ol.storage_info()
    print(
        f"minio-tpu serving {len(ol.zones)} zone(s) "
        f"{[z['disks'] for z in si['zones']]} drives at {srv.endpoint}"
    )
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}, shutting down")
    srv.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
