"""CLI entry: ``python -m minio_tpu.server [--address host:port] args...``

The `minio server` analogue (cmd/server-main.go): each positional arg is
one zone; ellipses patterns expand to that zone's drives - bare paths
(``/data/disk{1...8}``) for single-node mode or URLs
(``http://host{1...2}:9000/data/disk{1...4}``) for distributed mode.
Local drives are served to peers over the storage REST plane; remote
drives are reached through StorageRESTClient; format.json is
created/quorum-loaded per zone with a boot retry loop, and the object
layer is Zones(Sets(Objects)) exactly like newObjectLayer
(server-main.go:559-567).  HTTP serving starts before the object layer
is ready (503 ServerNotInitialized until then), mirroring
server-main.go:477-484.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def group_zone_args(zone_args: list[str]) -> list[list[str]]:
    """Group CLI drive args into zones (createServerEndpoints,
    endpoint-ellipses.go:331): args WITHOUT ellipses all join one zone
    (verify-healing.sh lists endpoints individually); each arg WITH an
    ellipses pattern is its own zone (server-pool syntax).  Mixing the
    two styles is rejected, like the reference."""
    from ..utils import ellipses

    with_e = [a for a in zone_args if ellipses.has_ellipses(a)]
    if not with_e:
        return [list(zone_args)]
    if len(with_e) != len(zone_args):
        raise SystemExit(
            "all drive args must use ellipses patterns, or none"
        )
    return [ellipses.expand(a) for a in zone_args]


def build_object_layer(zone_args: list[str], parity: "int | None" = None):
    """Single-node convenience: expand bare-path args -> zones layer."""
    ol, _ = build_cluster(zone_args, local_port=0, secret="", parity=parity)
    return ol


def build_cluster(
    zone_args: list[str],
    local_port: int,
    secret: str,
    parity: "int | None" = None,
    format_timeout_s: float = 120.0,
    local_disk_map: "dict | None" = None,
    nslock=None,
):
    """Expand args -> local XLStorage + remote REST disks -> zones layer.

    Returns (object_layer, local_disks) where local_disks is every
    XLStorage this node owns (to be served on the storage REST plane).
    """
    from ..cluster.endpoints import resolve_endpoints
    from ..objectlayer.format import wait_for_format
    from ..objectlayer.sets import ErasureSets
    from ..objectlayer.zones import ErasureZones
    from ..storage.rest_client import StorageRESTClient
    from ..storage.xl import XLStorage
    from ..utils import ellipses

    # standalone FS mode: exactly one local drive and no cluster
    # topology (newObjectLayer FS selection, server-main.go:561-564).
    # A drive already carrying an erasure format must never be
    # reinterpreted as FS (that would misread xl-layout data).
    flat = [a for g in group_zone_args(zone_args) for a in g]
    if len(flat) == 1 and "://" not in flat[0]:
        import os as _os

        if _os.path.exists(
            _os.path.join(flat[0], ".sys", "format.json")
        ):
            raise SystemExit(
                f"{flat[0]} holds an erasure format; a single-drive FS "
                "server cannot serve it (add the original drives)"
            )
        from ..objectlayer.fs import FSObjects

        return FSObjects(flat[0]), []

    zones = []
    local_disks: list = []
    for specs in group_zone_args(zone_args):
        eps = resolve_endpoints(specs, local_port)
        if len(eps) < 2:
            raise SystemExit(
                f"zone {specs!r} expands to {len(eps)} drives; need >= 2"
            )
        set_count, drives_per_set = ellipses.layout(len(eps))
        disks = []
        for ep in eps:
            if ep.is_local:
                d = (local_disk_map or {}).get(ep.path)
                if d is None:
                    d = XLStorage(ep.path, endpoint=ep.raw)
                local_disks.append(d)
                disks.append(d)
            else:
                disks.append(
                    StorageRESTClient(ep.host, ep.port, ep.path, secret)
                )
        # only the owner of the first endpoint may mint a fresh cluster
        init_allowed = eps[0].is_local
        ref_fmt, ordered = wait_for_format(
            disks,
            set_count,
            drives_per_set,
            init_allowed=init_allowed,
            timeout_s=format_timeout_s,
        )
        # per-op disk identity validation on local drives
        # (xl-storage-disk-id-check.go): a swapped drive fails fast.
        # Metering sits INSIDE the identity check so the heal
        # subsystem's one-hop `unwrapped` probe of unformatted drives
        # still reaches the raw disk (storage/metered.py docstring).
        from ..storage import metered
        from ..storage.diskcheck import DiskIDCheck

        guarded = []
        for i, d in enumerate(ordered):
            if d is not None and d.is_local():
                s_idx, d_idx = divmod(i, drives_per_set)
                guarded.append(
                    DiskIDCheck(
                        metered.wrap(d), ref_fmt.sets[s_idx][d_idx]
                    )
                )
            else:
                guarded.append(d)
        zones.append(
            ErasureSets(
                guarded,
                set_count,
                drives_per_set,
                parity_blocks=parity,
                nslock=nslock,
                format_ref=ref_fmt,
            )
        )
    return ErasureZones(zones), local_disks


def start_background_heal(ol):
    """MRF queue + heal routine + fresh-disk monitor over the object
    layer (startBackgroundOps analogue, server-main.go:524).  Returns
    (routine, monitor); both are daemon threads."""
    from ..heal.background import FreshDiskMonitor, HealQueue, HealRoutine

    queue = HealQueue()
    routine = HealRoutine(
        ol,
        queue,
        throttle_s=float(
            os.environ.get("MINIO_TPU_HEAL_THROTTLE_S") or 0.0
        ),
    ).start()
    monitor = FreshDiskMonitor(
        ol,
        queue,
        interval_s=float(
            os.environ.get("MINIO_TPU_FRESH_DISK_INTERVAL_S") or 10.0
        ),
    ).start()
    for zone in ol.zones:
        for eset in zone.sets:
            eset.heal_hook = queue.push_object
    return routine, monitor


def cluster_nodes(zone_args: list[str], local_port: int):
    """Sorted unique (host, port, is_local) across every URL endpoint -
    the lock-plane topology (one locker per node, like newLockAPI per
    endpoint host)."""
    from ..cluster.endpoints import resolve_endpoints

    nodes: dict = {}
    for specs in group_zone_args(zone_args):
        for ep in resolve_endpoints(specs, local_port):
            if ep.is_url:
                nodes[(ep.host, ep.port)] = (
                    nodes.get((ep.host, ep.port), False) or ep.is_local
                )
    return [
        (h, p, nodes[(h, p)]) for h, p in sorted(nodes)
    ]


def build_lock_plane(
    zone_args: list[str], local_port: int, secret: str
):
    """(nslock, lock_rest_server, maintenance) for this topology.

    Single-node (or bare-path) layouts use the in-process NamespaceLock;
    multi-node layouts get dsync quorum locks over the lock REST plane
    with refresh + expiry recovery (see dsync/drwmutex.py).
    """
    from ..dsync import drwmutex
    from ..dsync.local_locker import LocalLocker, LockMaintenance
    from ..dsync.lock_rest import LockRESTClient, LockRESTServer
    from ..dsync.namespace import DistNamespaceLock, NamespaceLock

    nodes = cluster_nodes(zone_args, local_port)
    if len(nodes) <= 1:
        return NamespaceLock(), None, None
    refresh_s = float(
        os.environ.get("MINIO_TPU_LOCK_REFRESH_S")
        or drwmutex.REFRESH_INTERVAL_S
    )
    expiry_s = float(
        os.environ.get("MINIO_TPU_LOCK_EXPIRY_S") or drwmutex.EXPIRY_S
    )
    local = LocalLocker(endpoint=f"local:{local_port}")
    lockers = [
        local
        if is_local
        else LockRESTClient(host, port, secret)
        for host, port, is_local in nodes
    ]
    ds = drwmutex.Dsync(lockers, refresh_interval_s=refresh_s)
    maint = LockMaintenance(
        local, interval_s=max(1.0, expiry_s / 3), expiry_s=expiry_s
    ).start()
    return (
        DistNamespaceLock(ds),
        LockRESTServer(local, secret),
        maint,
    )


def run_gateway(args) -> int:
    """Serve the S3 API over a non-erasure backend
    (cmd/gateway/gateway-main.go).  No storage/lock planes, no heal,
    no crawler - the backend owns durability."""
    from .http import S3Server

    if len(args.zones) != 3:
        raise SystemExit(
            "usage: server gateway {nas <path> | s3 <endpoint-url>}"
        )
    kind, target = args.zones[1], args.zones[2]
    if kind == "nas":
        from ..objectlayer.fs import FSObjects

        ol = FSObjects(target)
        desc = f"NAS gateway over {target}"
    elif kind == "s3":
        from ..gateway.s3 import S3Objects

        ol = S3Objects(
            target,
            os.environ.get("MINIO_TPU_GATEWAY_ACCESS_KEY")
            or args.access_key,
            os.environ.get("MINIO_TPU_GATEWAY_SECRET_KEY")
            or args.secret_key,
            region=args.region,
        )
        desc = f"S3 gateway to {target}"
    else:
        raise SystemExit(f"unknown gateway backend {kind!r}")
    srv = S3Server(
        ol,
        address=args.address,
        access_key=args.access_key,
        secret_key=args.secret_key,
        region=args.region,
    )
    from ..iam.sys import IAMSys

    # IAM rides the backend for nas (persistent), memory for s3 (the
    # upstream bucket namespace is not ours to write into)
    iam = IAMSys(
        args.access_key,
        args.secret_key,
        ol if kind == "nas" else None,
    )
    srv.attach_iam(iam)
    srv.start()
    print(f"minio-tpu serving {desc} at {srv.endpoint}")
    sys.stdout.flush()
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}, shutting down")
    srv.shutdown()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="minio-tpu server")
    p.add_argument(
        "zones",
        nargs="+",
        help=(
            "one arg per zone; ellipses expand: /data/disk{1...8} or "
            "http://host{1...2}:9000/data/disk{1...4}"
        ),
    )
    p.add_argument("--address", default="0.0.0.0:9000")
    p.add_argument(
        "--access-key",
        default=os.environ.get("MINIO_ACCESS_KEY", "minioadmin"),
    )
    p.add_argument(
        "--secret-key",
        default=os.environ.get("MINIO_SECRET_KEY", "minioadmin"),
    )
    p.add_argument("--region", default="us-east-1")
    p.add_argument(
        "--parity", type=int, default=None,
        help="parity drives per set (default: half)",
    )
    p.add_argument(
        "--format-timeout", type=float, default=120.0,
        help="seconds to wait for peers during format bootstrap",
    )
    args = p.parse_args(argv)

    # sigwait only *claims* a signal that is blocked; an unblocked
    # SIGTERM races the default disposition (immediate termination) and
    # usually loses, skipping the graceful drain below.  Block both
    # before any thread spawns so every thread inherits the mask.
    signal.pthread_sigmask(
        signal.SIG_BLOCK, {signal.SIGINT, signal.SIGTERM}
    )

    from ..utils import log

    log.setup(os.environ.get("MINIO_TPU_LOG_LEVEL", "info"))

    # gateway mode (cmd/gateway/): `server gateway nas /path` or
    # `server gateway s3 http://upstream:9000`
    if args.zones and args.zones[0] == "gateway":
        return run_gateway(args)

    from ..cluster.endpoints import resolve_endpoints
    from ..storage.rest_server import StorageRESTServer
    from ..storage.rest_common import PREFIX as STORAGE_PREFIX
    from ..storage.xl import XLStorage
    from ..utils import ellipses
    from .http import S3Server

    local_port = int(args.address.rsplit(":", 1)[1])

    # Discover local drives first so the storage plane can serve peers
    # BEFORE format bootstrap (reference starts HTTP at
    # server-main.go:477, then waits for disks).
    # With MINIO_TPU_FAULT_INJECTION=1 each local drive is wrapped in a
    # FaultDisk at the bottom of the wrap chain
    # (DiskIDCheck(Metered(Fault(XL)))), and the admin fault endpoint
    # can schedule delay/error/corrupt/hang rules on it remotely - the
    # chaos-grid harness degrades nodes it does not share memory with.
    fault_on = (os.environ.get("MINIO_TPU_FAULT_INJECTION") or "") in (
        "1",
        "on",
        "true",
    )
    fault_seed = int(os.environ.get("MINIO_TPU_FAULT_SEED") or 0)
    fault_disks: dict = {}
    pre_local: list = []
    local_map: dict = {}
    for specs in group_zone_args(args.zones):
        for ep in resolve_endpoints(specs, local_port):
            if ep.is_local:
                d = XLStorage(ep.path, endpoint=ep.raw)
                if fault_on:
                    from ..storage.faults import FaultDisk

                    d = FaultDisk(
                        d, seed=fault_seed + len(fault_disks)
                    )
                    fault_disks[str(d.unwrapped.root)] = d
                pre_local.append(d)
                local_map[ep.path] = d

    srv = S3Server(
        None,  # object layer attaches after bootstrap
        address=args.address,
        access_key=args.access_key,
        secret_key=args.secret_key,
        region=args.region,
        internode_secret=args.secret_key,
    )
    if fault_disks:
        srv.fault_disks = fault_disks
    # readiness gate: /minio/health/ready stays 503 until every
    # subsystem flips its flag, so a harness polls instead of sleeping
    srv.boot_status = {
        "lock_plane": False,
        "boot": False,
        "server_loops": False,
    }
    storage_rest = StorageRESTServer(pre_local, args.secret_key)
    srv.register_internode(STORAGE_PREFIX, storage_rest.handle)
    nslock, lock_rest, _lock_maint = build_lock_plane(
        args.zones, local_port, args.secret_key
    )
    if lock_rest is not None:
        from ..dsync.lock_rest import PREFIX as LOCK_PREFIX

        srv.register_internode(LOCK_PREFIX, lock_rest.handle)
    srv.boot_status["lock_plane"] = True

    # peer control plane + bootstrap handshake (distributed mode):
    # every node serves /minio-tpu/peer/v1 and verifies the cluster
    # config fingerprint against every peer before joining
    from ..cluster import peer as peer_mod

    fingerprint = peer_mod.cluster_fingerprint(
        args.zones, args.access_key, args.secret_key
    )
    peers = [
        peer_mod.PeerRESTClient(host, port, args.secret_key)
        for host, port, is_local in cluster_nodes(args.zones, local_port)
        if not is_local
    ]
    peer_rest = peer_mod.PeerRESTServer(
        srv,
        args.secret_key,
        fingerprint=fingerprint,
        local_locker=lock_rest.locker if lock_rest is not None else None,
    )
    srv.register_internode(peer_mod.PREFIX, peer_rest.handle)
    srv.peer_rest = peer_rest  # shutdown() closes its sweeper
    srv.local_locker = lock_rest.locker if lock_rest is not None else None
    if peers:
        srv.peer_notifier = peer_mod.PeerNotifier(peers)
        # tiered read cache: object mutations on this node drop every
        # peer's cached groups through the notifier fan-out
        from .. import cache as rcache_mod

        rcache_mod.set_broadcast(
            srv.peer_notifier.read_cache_invalidated
        )

    srv.start()
    # listener shards are up (async plane: every MINIO_TPU_SERVER_LOOPS
    # loop accepting; readiness() additionally reports per-loop state)
    srv.boot_status["server_loops"] = (
        srv._plane is None or srv._plane.loops_ready()
    )
    print(f"minio-tpu listening at {srv.endpoint} (bootstrapping)")
    if peers:
        peer_mod.verify_cluster(
            peers, fingerprint, timeout_s=args.format_timeout
        )
        print(f"bootstrap handshake ok with {len(peers)} peer(s)")

    ol, _ = build_cluster(
        args.zones,
        local_port,
        args.secret_key,
        args.parity,
        format_timeout_s=args.format_timeout,
        local_disk_map=local_map,
        nslock=nslock,
    )
    # optional SSD read cache in front of the object layer
    # (disk-cache.go CacheObjectLayer, server-main.go:531-540)
    from ..objectlayer.cache import cache_from_env

    ol_front = cache_from_env(ol)
    if ol_front is not ol:
        print("disk cache enabled")
    srv.object_layer = ol_front
    # federation: a shared record dir plays etcd's role for bucket
    # DNS (cmd/config/etcd/dns); every cluster pointing at the same
    # dir shares one global bucket namespace
    fed_dir = os.environ.get("MINIO_TPU_FEDERATION_DIR", "")
    if fed_dir:
        from ..cluster.dns import BucketDNS, FileDNSStore

        adv_host = (
            os.environ.get("MINIO_TPU_FEDERATION_HOST")
            or args.address.rsplit(":", 1)[0]
        )
        if adv_host in ("0.0.0.0", ""):
            adv_host = "127.0.0.1"
        srv.bucket_dns = BucketDNS(
            FileDNSStore(fed_dir),
            adv_host,
            local_port,
            scheme=(
                "https"
                if (os.environ.get("MINIO_TPU_TLS") or "").lower()
                in ("1", "on", "true")
                else "http"
            ),
        )
        print(f"federation: bucket DNS at {fed_dir} as "
              f"{adv_host}:{local_port}")
    # once formats are known, the storage REST plane serves the
    # DiskIDCheck-wrapped disks too: peer I/O must not write onto a
    # swapped drive either (xl-storage-disk-id-check.go applies to the
    # server side of the plane)
    from ..storage.diskcheck import DiskIDCheck as _DIC

    guarded_map = {}
    for zone in getattr(ol, "zones", []):
        for eset in zone.sets:
            for d in eset.disks:
                if isinstance(d, _DIC):
                    guarded_map[d.unwrapped.root] = d
    storage_rest.guard_disks(guarded_map)
    # persisted KV config: load + apply before subsystems read their
    # env seams (initSafeMode config load, server-main.go:526)
    srv.config.apply()
    # store-backed IAM after the object layer is up (iam.go:419 Init)
    from ..iam.sys import IAMSys

    iam = IAMSys(args.access_key, args.secret_key, ol)
    srv.attach_iam(iam)
    if peers:
        iam.start_refresher(
            float(os.environ.get("MINIO_TPU_IAM_REFRESH_S") or 120.0)
        )
    if getattr(ol, "zones", None):
        _heal_routine, _disk_monitor = start_background_heal(ol)
        srv.heal_routine = _heal_routine
        srv.heal_queue = _heal_routine.queue
        srv.disk_monitor = _disk_monitor  # reloadformat peer RPC
    # data-update tracker: object mutations mark a persisted bloom
    # journal the crawler uses to skip clean buckets
    # (data-update-tracker.go:63)
    from ..crawler import updatetracker as ut_mod

    tracker_root = next(iter(guarded_map), None) or getattr(
        ol, "root", None
    )
    tracker = ut_mod.DataUpdateTracker(
        path=os.path.join(tracker_root, ".sys", "update-tracker.bin")
        if tracker_root
        else None
    )
    ut_mod.install_tracker(tracker)
    srv.update_tracker = tracker
    notifier = getattr(srv, "peer_notifier", None)

    def _cluster_bloom(oldest: int, current: int):
        """Union of this node's filter and every peer's; any
        unreachable/trackerless peer poisons completeness so the
        crawler falls back to a full sweep."""
        resp = tracker.cycle_filter(oldest, current)
        if notifier is not None:
            for wire in notifier.cycle_blooms(oldest, current):
                if wire is None:
                    resp.complete = False
                    continue
                peer_resp = ut_mod.BloomResponse.from_wire(wire)
                resp.complete = resp.complete and peer_resp.complete
                try:
                    resp.filter.union_into(peer_resp.filter)
                except ValueError:
                    resp.complete = False
        return resp

    # data crawler: usage accounting + lifecycle enforcement
    # (runDataCrawler, server-main.go:524 startBackgroundOps)
    from ..crawler import DataCrawler
    from ..objectlayer.api import META_BUCKET

    srv.crawler = DataCrawler(
        ol,
        srv.bucket_meta,
        interval_s=float(
            os.environ.get("MINIO_TPU_CRAWL_INTERVAL_S") or 60.0
        ),
        events=srv.events,
        ensure_event_rules=srv.ensure_event_rules,
        replication=srv.replication,
        cycle_bloom=_cluster_bloom,
        # heal-on-crawl: full sweeps probe shard health and feed the
        # MRF heal queue (data scanner healObject path)
        heal_hook=(
            srv.heal_queue.push_object
            if getattr(srv, "heal_queue", None) is not None
            else None
        ),
        # distributed: elect one sweeping node per cycle via the lock
        # plane (single node: the local _crawl_mu already serializes)
        leader_lock=(
            (
                lambda: nslock.write(
                    META_BUCKET, "data-crawler/leader", timeout=2.0
                )
            )
            if peers
            else None
        ),
    ).start()
    si = ol.storage_info()
    if "zones" in si:
        desc = (
            f"{len(ol.zones)} zone(s) "
            f"{[z['disks'] for z in si['zones']]} drives"
        )
        zcount = len(ol.zones)
    else:
        desc = "standalone FS backend (1 drive)"
        zcount = 0
    srv.boot_status["boot"] = True
    print(f"minio-tpu serving {desc} at {srv.endpoint}")
    sys.stdout.flush()
    log.logger("server").info(
        "online",
        extra=log.kv(endpoint=srv.endpoint, zones=zcount),
    )
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}, shutting down")
    # graceful teardown order: drain in-flight requests first (their
    # handlers release their own locks), stop heal/crawler/monitor
    # threads (inside srv.shutdown), THEN unwind whatever dsync grants
    # remain so peers see clean releases instead of waiting out the
    # expiry window on orphaned entries.
    tracker.save()  # flush marks recorded since the last rotation
    srv.shutdown()
    if _lock_maint is not None:
        _lock_maint.stop()
    if hasattr(nslock, "release_all"):
        released = nslock.release_all()
        if released:
            print(f"released {released} held lock(s)")
    print("shutdown complete")
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
