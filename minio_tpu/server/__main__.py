"""CLI entry: ``python -m minio_tpu.server [--address host:port] disk...``

The `minio server` analogue (cmd/server-main.go): builds the object layer
from disk paths (single path -> still erasure with minimum disks is not
possible, so 1 path runs a 1-disk FS-style layout only when provided 1
path; >=4 paths build one erasure set; sets/zones routing arrives with
the distributed plane).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="minio-tpu server")
    p.add_argument("disks", nargs="+", help="disk paths (>= 2)")
    p.add_argument("--address", default="0.0.0.0:9000")
    p.add_argument(
        "--access-key",
        default=os.environ.get("MINIO_ACCESS_KEY", "minioadmin"),
    )
    p.add_argument(
        "--secret-key",
        default=os.environ.get("MINIO_SECRET_KEY", "minioadmin"),
    )
    p.add_argument("--region", default="us-east-1")
    args = p.parse_args(argv)

    from ..objectlayer.erasure_object import ErasureObjects
    from ..storage.xl import XLStorage
    from .http import S3Server

    if len(args.disks) < 2:
        print("need at least 2 disk paths", file=sys.stderr)
        return 2
    disks = [XLStorage(d) for d in args.disks]
    ol = ErasureObjects(disks)
    srv = S3Server(
        ol,
        address=args.address,
        access_key=args.access_key,
        secret_key=args.secret_key,
        region=args.region,
    ).start()
    print(
        f"minio-tpu serving {len(disks)} disks "
        f"(EC {ol.data_blocks}+{ol.parity_blocks}) at {srv.endpoint}"
    )
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}, shutting down")
    srv.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
