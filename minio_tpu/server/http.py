"""The S3 HTTP server: router + handlers (L6/L7 of the layer map).

One threaded stdlib HTTP server hosting the S3 API surface
(cmd/api-router.go routes + cmd/object-handlers.go / bucket-handlers.go
glue).  Requests are authenticated with SigV4 (auth.py), dispatched on
(method, path-shape, query), and translated to ObjectLayer calls; errors
render as S3 XML (s3errors.py / response.py).

The reference funnels every handler through middleware
(maxClients(collectAPIStats(httpTrace(...))), api-router.go:94); here the
equivalent cross-cutting layer lives in _Handler.route(): auth, tracing
hooks, error rendering, request IDs.
"""

from __future__ import annotations

import base64
import datetime
import email.utils
import hashlib
import io
import os
import re
import socket
import threading
import time as _time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..iam.sys import IAMSys
from ..objectlayer.api import CompletePart, ObjectInfo
from ..objectlayer.bucket_meta import BucketMetadataSys
from ..utils.hashreader import HashReader
from . import auth as authmod, authz, response as xmlr, s3errors
from .auth import (
    AuthError,
    Credentials,
    SigV4ChunkedReader,
    SigV4Verifier,
)
from .s3errors import S3Error

from ..utils.log import kv, logger

_log = logger("http")

MAX_IN_MEMORY_BODY = 1 << 30  # buffered-body cap (XML configs, POST forms)
MAX_OBJECT_SIZE = 5 << 40  # globalMaxObjectSize (cmd/globals.go)
# internode requests are metadata or bounded shard flushes (4 MiB); a
# larger body is an attack, not a peer (advisor finding r2)
MAX_INTERNODE_BODY = 64 << 20
# multi-delete bodies carry at most 10k keys (maxDeleteList)
MAX_MULTI_DELETE_BODY = 1 << 20

# request-plane mode (ROADMAP item 4): the asyncio event-loop plane is
# the default; MINIO_TPU_SERVER=threaded keeps the thread-per-request
# stdlib plane as the bisection oracle (house style of
# MINIO_TPU_PARITY_PLANE=off)
DEFAULT_SERVER_MODE = "async"


class _ChunkedReader:
    """Decode a chunked transfer-encoded body from the socket.

    The stdlib server leaves chunked TE undecoded; the internode shard
    plane uses it so CreateFile bodies stream end-to-end without either
    side buffering a whole shard (storage-rest-server.go CreateFile).
    """

    MAX_CHUNK = 16 << 20

    def __init__(self, raw):
        self._raw = raw
        self._remaining = 0
        self._done = False

    def _read_line(self) -> bytes:
        line = self._raw.readline(1024)
        if not line.endswith(b"\r\n"):
            raise OSError("bad chunk framing")
        return line[:-2]

    def read(self, n: int = -1) -> bytes:
        out = bytearray()
        while not self._done and (n < 0 or len(out) < n):
            if self._remaining == 0:
                size_s = self._read_line().split(b";")[0]
                try:
                    size = int(size_s, 16)
                except ValueError:
                    raise OSError("bad chunk size") from None
                if size > self.MAX_CHUNK:
                    raise OSError("chunk too large")
                if size == 0:
                    # consume optional trailers until the blank line
                    while self._read_line():
                        pass
                    self._done = True
                    break
                self._remaining = size
            want = self._remaining if n < 0 else min(
                self._remaining, n - len(out)
            )
            chunk = self._raw.read(want)
            if not chunk:
                raise OSError("truncated chunked body")
            out += chunk
            self._remaining -= len(chunk)
            if self._remaining == 0:
                if self._raw.read(2) != b"\r\n":
                    raise OSError("missing chunk CRLF")
        return bytes(out)

    def drain(self) -> None:
        while not self._done:
            if not self.read(1 << 20):
                break


class _LimitedReader:
    """Reads at most ``limit`` bytes from the underlying socket file."""

    def __init__(self, raw, limit: int):
        self._raw = raw
        self.remaining = limit

    def read(self, n: int = -1) -> bytes:
        if self.remaining <= 0:
            return b""
        if n < 0 or n > self.remaining:
            n = self.remaining
        chunk = self._raw.read(n)
        self.remaining -= len(chunk)
        return chunk


class S3Server:
    """Owns the listener + object layer; one per process (xhttp.NewServer
    analogue, cmd/http/server.go:185)."""

    def __init__(
        self,
        object_layer,
        address: str = "127.0.0.1:9000",
        access_key: str = "minioadmin",
        secret_key: str = "minioadmin",
        region: str = "us-east-1",
        iam=None,
        internode_secret: str = "",
    ):
        self.object_layer = object_layer
        # when set, internode-plane requests must carry a valid JWT
        # BEFORE the server reads their body (advisor finding r2)
        self.internode_secret = internode_secret
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.region = region
        # every server has an IAMSys; without one injected, a local
        # (non-persisted) system holding just the root credential
        self.iam = iam or IAMSys(access_key, secret_key)
        self.verifier = SigV4Verifier(self.iam.lookup_secret, region)
        self._bucket_meta: "BucketMetadataSys | None" = None
        from .metrics import Metrics

        self.metrics = Metrics()
        # "public" opens the scrape endpoint (MINIO_PROMETHEUS_AUTH_TYPE)
        self.metrics_public = (
            os.environ.get("MINIO_TPU_PROMETHEUS_AUTH_TYPE", "jwt")
            == "public"
        )
        self.heal_routine = None  # attached by the server main
        self.heal_queue = None
        # readiness gate (healthcheck ready-parity): the server main
        # populates this dict as subsystems come up, so the ready
        # endpoint reports object-layer + lock-plane init complete and
        # a cluster harness can poll instead of sleeping.  None (the
        # embedded/test default) keeps the legacy semantics: ready as
        # soon as an object layer is attached.
        self.boot_status: "dict[str, bool] | None" = None
        # federation bucket DNS (cluster/dns.BucketDNS); None when
        # this deployment is not federated
        self.bucket_dns = None
        # peer control plane (distributed mode): PeerNotifier fanning
        # out cache invalidations + aggregating node info
        self.peer_notifier = None
        # bucket event notifications (pkg/event): targets from env,
        # rules loaded lazily per bucket from the metadata subsystem
        from ..event import EventNotifier, targets_from_env

        self.events = EventNotifier(targets_from_env()).start()
        self._event_rules_loaded: "set[str]" = set()
        # tracing / audit / profiling / console capture (SURVEY §5)
        from ..utils.profiling import Profiler
        from .trace import AuditLog, ConsoleCapture, Tracer

        self.tracer = Tracer(node=address)
        self.audit = AuditLog()
        self.profiler = Profiler()
        self.console = ConsoleCapture(node=address).install()
        self._httpd: "ThreadingHTTPServer | None" = None
        self._thread: "threading.Thread | None" = None
        self.tls = False
        # admission control (handler-api.go:85 maxClients): bounded
        # concurrent S3 requests; excess waits up to the deadline then
        # gets 503.  0 = unlimited.
        self._inflight = 0
        # set at shutdown: long-lived streams (listen notifications)
        # must end so the drain window isn't spent waiting on them
        self.draining = False
        self._adm_mu = threading.Lock()
        self._adm_cv = threading.Condition(self._adm_mu)
        # internode planes (storage/lock/peer/bootstrap REST, the
        # registerDistErasureRouters analogue, routers.go:25-38):
        # prefix -> handler(method_tail, query, body, headers)
        #           returning (status, body, extra_headers)
        self.internode: "dict[str, object]" = {}
        # server-plane telemetry + tenant/quota admission, shared by
        # both server modes (server/admission.py)
        from .admission import AdmissionController, PlaneStats

        self.plane_stats = PlaneStats()
        self.admission = AdmissionController(self, self.plane_stats)

        def _codec_depth() -> int:
            from ..parallel.iopool import queued_depth

            return queued_depth()

        self.plane_stats.register_stage("codec", _codec_depth)
        self._plane = None  # AsyncPlane when server_mode == "async"
        self.server_mode = "threaded"

    def _requests_max(self) -> int:
        try:
            return int(os.environ.get("MINIO_TPU_REQUESTS_MAX") or 0)
        except ValueError:
            return 0

    def _requests_deadline(self) -> float:
        try:
            return float(
                os.environ.get("MINIO_TPU_REQUESTS_DEADLINE_S") or 10.0
            )
        except ValueError:
            return 10.0

    def admit(self) -> bool:
        """Take an admission slot (True) or time out (False -> 503)."""
        limit = self._requests_max()
        with self._adm_cv:
            if limit <= 0:
                self._inflight += 1
                return True
            deadline = _time.monotonic() + self._requests_deadline()
            while self._inflight >= limit:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._adm_cv.wait(remaining)
            self._inflight += 1
            return True

    def release(self) -> None:
        with self._adm_cv:
            self._inflight = max(0, self._inflight - 1)
            self._adm_cv.notify()

    def attach_iam(self, iam: IAMSys) -> None:
        """Swap in a store-backed IAMSys once the object layer is up
        (startBackgroundIAMLoad ordering, server-main.go:529)."""
        self.iam = iam
        iam.notifier = self.peer_notifier
        self.verifier = SigV4Verifier(iam.lookup_secret, self.region)

    def register_internode(self, prefix: str, handler) -> None:
        """Mount an internode REST plane under a path prefix."""
        self.internode[prefix] = handler

    def ensure_event_rules(self, bucket: str) -> None:
        """Lazily hydrate a bucket's notification rules from the
        persisted document (bucketRulesMap load, notification.go)."""
        if bucket in self._event_rules_loaded or self.object_layer is None:
            return
        try:
            raw = self.bucket_meta.get(bucket).notification_xml
        except Exception:  # noqa: BLE001
            # transient metadata-read failure: do NOT mark loaded, so
            # the next event retries instead of dropping forever
            return
        try:
            self.events.load_bucket_config(bucket, raw)
        except Exception as exc:
            _log.debug("bad persisted notification doc: no rules loaded", extra=kv(err=str(exc)))
        self._event_rules_loaded.add(bucket)

    def mark_event_rules_loaded(self, bucket: str) -> None:
        self._event_rules_loaded.add(bucket)

    def invalidate_event_rules(self, bucket: str) -> None:
        """Peer invalidation path: re-read the config on next event."""
        self._event_rules_loaded.discard(bucket)

    @property
    def replication(self):
        """Async replication pool, lazily started (bucket-replication)."""
        rp = getattr(self, "_replication_pool", None)
        if rp is None or rp.s3 is not self:
            from ..replication.replicate import ReplicationPool

            rp = ReplicationPool(self).start()
            self._replication_pool = rp
        return rp

    @property
    def config(self):
        """Runtime KV config subsystem, lazily bound to the object
        layer (cmd/config ConfigSys analogue)."""
        cs = getattr(self, "_config_sys", None)
        if cs is None or cs._ol is not self.object_layer:
            from ..config import ConfigSys

            cs = ConfigSys(self.object_layer)
            self._config_sys = cs
        cs.notifier = self.peer_notifier
        return cs

    @property
    def bucket_meta(self) -> BucketMetadataSys:
        """Bucket metadata subsystem, lazily bound once the object
        layer attaches (it persists through the layer)."""
        if (
            self._bucket_meta is None
            or self._bucket_meta._ol is not self.object_layer
        ):
            self._bucket_meta = BucketMetadataSys(self.object_layer)
            self._bucket_meta.notifier = self.peer_notifier
        return self._bucket_meta

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "S3Server":
        from ..utils import tlsconf

        server = self

        class Handler(_Handler):
            s3 = server

        self.tls = tlsconf.enabled()
        mode = (
            os.environ.get("MINIO_TPU_SERVER") or DEFAULT_SERVER_MODE
        ).lower()
        self.server_mode = "async" if mode == "async" else "threaded"
        if self.server_mode == "async":
            from . import aio

            ssl_ctx = tlsconf.server_context() if self.tls else None
            self._plane = aio.AsyncPlane(self)
            self._plane.start(Handler, self.host, self.port, ssl_ctx)
            self.port = self._plane.port
            return self
        # slow-loris guard for the threaded oracle: a per-connection
        # socket timeout covers the header/body read (the stdlib drops
        # the connection without a response on expiry)
        idle = os.environ.get("MINIO_TPU_IDLE_TIMEOUT_S")
        if idle:
            try:
                Handler.timeout = float(idle)
            except ValueError:
                pass
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), Handler
        )
        if self.tls:
            # TLS listener (the reference's xhttp server takes the
            # same certs for S3 and internode traffic)
            self._httpd.socket = tlsconf.server_context().wrap_socket(
                self._httpd.socket, server_side=True
            )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="s3-server", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, drain_s: float = 10.0) -> None:
        """Stop accepting, then drain in-flight requests up to
        ``drain_s`` (the reference's graceful shutdown,
        cmd/http/server.go:116 request draining).  Idempotent: SIGTERM
        followed by an embedder's own shutdown() (or a double signal)
        must not re-stop half-torn-down subsystems — every loop drains
        exactly once."""
        self.draining = True
        if getattr(self, "_shutdown_done", False):
            return
        self._shutdown_done = True
        if self._plane is not None:
            self._plane.stop(drain_s)
        if self._httpd:
            self._httpd.shutdown()  # stop accepting new connections
        deadline = _time.monotonic() + drain_s
        while self._inflight > 0 and _time.monotonic() < deadline:
            _time.sleep(0.05)
        if self._httpd:
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        self.events.shutdown()
        # background maintenance threads (heal routine, fresh-disk
        # monitor, crawler) stop AFTER the drain so an in-flight PUT's
        # heal hooks land, but before lock unwinding so they cannot
        # take new namespace locks during teardown
        for attr in ("crawler", "disk_monitor", "heal_routine"):
            worker = getattr(self, attr, None)
            if worker is not None and hasattr(worker, "stop"):
                try:
                    worker.stop()
                except Exception as exc:
                    _log.debug(
                        "background worker stop failed",
                        extra=kv(worker=attr, err=str(exc)),
                    )
        # replication workers are per-server threads, not process
        # singletons: leaving them running after shutdown is a leak
        # (caught by the tests' leakcheck fixture)
        repl = getattr(self, "_replication_pool", None)
        if repl is not None and hasattr(repl, "stop"):
            try:
                repl.stop()
            except Exception as exc:
                _log.debug("replication pool stop failed", extra=kv(err=str(exc)))
        peer_rest = getattr(self, "peer_rest", None)
        if peer_rest is not None and hasattr(peer_rest, "close"):
            try:
                peer_rest.close()
            except Exception as exc:
                _log.debug("peer REST close failed", extra=kv(err=str(exc)))
        # detach the console ring from the shared package logger: a
        # process constructing several servers (tests, embedders) must
        # not accumulate one live handler per dead server
        self.console.uninstall()

    def readiness(self) -> "tuple[bool, bytes]":
        """(ready, JSON body) for /minio/health/ready: object layer
        attached, every boot_status subsystem up, and not draining."""
        import json as _json

        doc = {"object_layer": self.object_layer is not None}
        if self.boot_status is not None:
            doc.update(self.boot_status)
        plane = self._plane
        if plane is not None:
            # every server loop must be accepting before ready flips
            doc["server_loops"] = plane.loops_ready()
        ok = all(doc.values()) and not self.draining
        doc["draining"] = self.draining
        if plane is not None:
            # per-loop detail rides after the ok computation (like
            # "draining"): states are strings, not readiness gates
            doc["loops"] = {
                str(row["loop"]): row["state"]
                for row in plane.describe()["per_loop"]
            }
        return ok, _json.dumps(doc, sort_keys=True).encode()

    @property
    def endpoint(self) -> str:
        scheme = "https" if getattr(self, "tls", False) else "http"
        return f"{scheme}://{self.host}:{self.port}"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    s3: S3Server = None  # injected subclass attribute

    # silence default stderr logging
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    # -- plumbing ---------------------------------------------------------

    def _parse(self):
        parsed = urllib.parse.urlsplit(self.path)
        path = urllib.parse.unquote(parsed.path)
        query = urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True
        )
        return path, query

    def _body_size(self) -> int:
        """Declared body size; rejects framing we cannot stream safely."""
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        if te and te != "identity":
            # stdlib does not decode chunked TE; reading it as raw bytes
            # would desync the connection (advisor finding r1)
            self.close_connection = True
            raise S3Error("MissingContentLength")
        cl = self.headers.get("Content-Length")
        if cl is None:
            if self.command in ("PUT", "POST"):
                self.close_connection = True
                raise S3Error("MissingContentLength")
            return 0
        try:
            length = int(cl)
        except ValueError:
            self.close_connection = True
            raise S3Error("InvalidArgument", "Content-Length") from None
        if length < 0:
            self.close_connection = True
            raise S3Error("InvalidArgument", "Content-Length")
        return length

    def _open_body(self):
        """(reader, decoded_size): the auth-appropriate body stream.

        For aws-chunked requests the wire bytes are Content-Length long
        but the object data is x-amz-decoded-content-length long, framed
        and signature-verified by SigV4ChunkedReader.
        """
        length = self._body_size()
        # the framing is valid and a handler wants the body: release
        # the deferred 100 so a waiting client starts transmitting
        self._maybe_send_continue()
        raw = _LimitedReader(self.rfile, length)
        self._raw_body = raw
        ctx = self._auth
        if ctx is not None and ctx.streaming:
            decoded = self.headers.get("x-amz-decoded-content-length")
            if decoded is None:
                raise S3Error("MissingContentLength")
            return (
                SigV4ChunkedReader(raw, ctx, int(decoded)),
                int(decoded),
            )
        return raw, length

    def _hash_reader(self, reader, size: int) -> HashReader:
        """Wrap the body in the MD5/SHA256-verifying reader
        (pkg/hash PutObjReader): Content-MD5 and the signed
        x-amz-content-sha256 are checked as bytes stream through."""
        md5_hdr = self.headers.get("Content-MD5", "")
        md5_hex = ""
        if md5_hdr:
            try:
                md5_hex = base64.b64decode(md5_hdr).hex()
            except Exception:  # noqa: BLE001
                raise S3Error("InvalidDigest") from None
        sha_hex = ""
        ctx = self._auth
        if ctx is not None and ctx.content_sha256:
            sha_hex = ctx.content_sha256
        return HashReader(reader, size, md5_hex=md5_hex, sha256_hex=sha_hex)

    def _read_body(self) -> bytes:
        """Fully buffer a (bounded) body - XML/config payloads."""
        reader, size = self._open_body()
        if size > MAX_IN_MEMORY_BODY:
            self.close_connection = True
            raise S3Error("EntityTooLarge")
        hr = self._hash_reader(reader, size)
        chunks = []
        while True:
            c = hr.read(1 << 20)
            if not c:
                break
            chunks.append(c)
        body = b"".join(chunks)
        if len(body) != size:
            self.close_connection = True
            raise S3Error("IncompleteBody")
        return body

    def _respond(
        self,
        status: int,
        body: bytes = b"",
        headers: "dict | None" = None,
        content_type: str = "application/xml",
    ):
        self.send_response(status)
        self.send_header("Server", "MinIO-TPU")
        self.send_header(
            "x-amz-request-id", uuid.uuid4().hex[:16].upper()
        )
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        if body or status not in (204, 304):
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
        else:
            self.send_header("Content-Length", "0")
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)
            self._resp_bytes += len(body)

    def _error(self, err: s3errors.APIError, resource: str):
        if err.status >= 500:
            from ..utils import log

            log.logger("http").error(
                "request failed",
                extra=log.kv(
                    code=err.code,
                    status=err.status,
                    resource=resource,
                    method=self.command,
                ),
            )
        if err.status == 304:  # Not Modified carries no body
            self._respond(304)
            return
        body = xmlr.error_xml(
            err.code, err.message, resource, uuid.uuid4().hex[:16]
        )
        self._respond(err.status, body)

    # -- entry ------------------------------------------------------------

    def end_headers(self):
        self._headers_sent = True
        super().end_headers()

    def send_response(self, code, message=None):
        self._last_status = code  # metrics middleware reads this
        # first status line of the request = first byte on the wire
        # (the TTFB sample; streaming bodies start right after it)
        if (
            getattr(self, "_t_start", None) is not None
            and getattr(self, "_ttfb", None) is None
        ):
            self._ttfb = _time.monotonic() - self._t_start
        super().send_response(code, message)

    def _finish_body(self) -> None:
        """Keep-alive hygiene: drain small unread remainders, otherwise
        mark the connection dirty so it is closed rather than desynced."""
        if getattr(self, "_expect_100", False) and not getattr(
            self, "_continue_sent", True
        ):
            # the client never got its 100 and is still holding the
            # body: there is nothing on the wire to drain — a drain
            # here would deadlock against a conforming client, so cut
            # the connection after the final status (RFC 7231 §5.1.1
            # permits closing instead of reading the unsent body)
            try:
                if int(self.headers.get("Content-Length") or 0) > 0:
                    self.close_connection = True
            except ValueError:
                self.close_connection = True
            return
        raw = getattr(self, "_raw_body", None)
        if raw is not None:
            if raw.remaining > (1 << 20):
                self.close_connection = True
            elif raw.remaining:
                raw.read(raw.remaining)
            return
        cl = self.headers.get("Content-Length")
        if cl and cl not in ("0", ""):
            try:
                n = int(cl)
            except ValueError:
                n = -1
            if 0 <= n <= (1 << 20):
                self.rfile.read(n)  # drain small, keep the connection
            else:
                self.close_connection = True

    def _is_post_policy(self, path: str, query) -> bool:
        return (
            self.command == "POST"
            and "/" not in path.lstrip("/").rstrip("/")
            and "delete" not in query
            and (self.headers.get("Content-Type") or "").startswith(
                "multipart/form-data"
            )
        )

    def handle_expect_100(self):
        """RFC 7231 §5.1.1: defer the interim 100 until a handler
        actually solicits the body (``_maybe_send_continue``) — a
        request rejected on its headers gets its final status with NO
        interim 100, and the body the client never sent is never
        "drained".  The stdlib default commits 100 at parse time,
        before auth or framing checks have run."""
        self._expect_100_req = True
        return True

    def _maybe_send_continue(self) -> None:
        """First body solicitation: release the deferred interim 100 so
        a conforming client that genuinely waits starts transmitting."""
        if getattr(self, "_expect_100", False) and not self._continue_sent:
            self._continue_sent = True
            self.wfile.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            self.wfile.flush()

    def route(self):
        path, query = self._parse()
        self._headers_sent = False
        self._raw_body = None
        self._auth = None
        self._action = ""
        self._last_status = 0
        self._resp_bytes = 0
        self._t_start = None
        self._ttfb = None
        # Expect: 100-continue deferral (one instance serves a whole
        # keep-alive connection: the pending flag is per-request)
        self._expect_100 = self.__dict__.pop("_expect_100_req", False)
        self._continue_sent = False
        if self.command not in ("GET", "PUT", "POST", "DELETE", "HEAD"):
            # non-S3 verbs (PATCH, OPTIONS, PROPFIND, ...) answer the
            # S3 MethodNotAllowed document - with the body drained for
            # keep-alive hygiene, not the stdlib's bare 501 HTML
            self._finish_body()
            return self._error(s3errors.get("MethodNotAllowed"), path)
        for prefix, handler in self.s3.internode.items():
            if path.startswith(prefix + "/"):
                return self._route_internode(
                    handler, path[len(prefix) + 1 :], query
                )
        # health endpoints are unauthenticated (healthcheck-handler.go:26-66)
        if path == "/minio/health/live":
            self._finish_body()  # keep-alive hygiene on early return
            return self._respond(200, content_type="text/plain")
        if path in ("/minio/health/ready", "/minio/health/cluster"):
            self._finish_body()
            ready, doc = self.s3.readiness()
            return self._respond(
                200 if ready else 503,
                doc,
                content_type="application/json",
            )
        if path == "/minio-tpu/prometheus/metrics":
            self._finish_body()
            if not self.s3.metrics_public:
                # authenticated scrapes only by default (the reference
                # guards /minio/prometheus/metrics with JWT)
                try:
                    ctx = self.s3.verifier.verify_stream(
                        self.command, path, query,
                        dict(self.headers.items()),
                    )
                except AuthError:
                    return self._respond(
                        403, b"forbidden", content_type="text/plain"
                    )
                if ctx.anonymous:
                    return self._respond(
                        403, b"forbidden", content_type="text/plain"
                    )
            return self._respond(
                200,
                self.s3.metrics.render(
                    self.s3.object_layer,
                    self.s3.heal_routine,
                    self.s3.heal_queue,
                    audit=self.s3.audit,
                    plane=self.s3.plane_stats.snapshot(),
                ),
                content_type="text/plain; version=0.0.4",
            )
        # tenant/quota admission (server/admission.py): the async plane
        # runs this loop-side before enqueueing; the threaded oracle
        # runs it here so both modes shed with the same semantics
        tenant = None
        if not getattr(self, "_plane_admitted", False):
            adm = self.s3.admission
            if adm.quota_rejects_put(self.command, path, self.headers):
                self.s3.plane_stats.shed_inc("quota")
                self.s3.metrics.observe("Shed", 503, 0.0)
                self.close_connection = True
                return self._error(s3errors.get("SlowDown"), path)
            tenant = adm.tenant_of(self.headers)
            if not adm.try_enter_tenant(tenant):
                self.s3.plane_stats.shed_inc("tenant")
                self.s3.metrics.observe("Shed", 503, 0.0)
                self.close_connection = True
                return self._error(s3errors.get("SlowDown"), path)
        # admission control (maxClients, handler-api.go:85): overload
        # answers 503 instead of spawning unbounded work
        if not self.s3.admit():
            if tenant is not None:
                self.s3.admission.leave_tenant(tenant)
            self.s3.plane_stats.shed_inc("queue")
            self.s3.metrics.observe("Shed", 503, 0.0)
            self.close_connection = True
            self._error(s3errors.get("SlowDown"), path)
            return
        # multi-loop async plane: attribute the inflight gauge to the
        # owning loop's lock-free cell (threaded oracle: loop=None)
        _loop_ix = getattr(self, "_loop_index", None)
        self.s3.plane_stats.enter(loop=_loop_ix)
        t0 = _time.monotonic()
        self._t_start = t0
        try:
            from . import web as webmod

            if (
                path == webmod.RPC_PATH
                or path == webmod.CONSOLE_PATH
                or path.startswith(webmod.WEB_PREFIX + "/")
            ):
                # web plane: JWT-authenticated (not SigV4), its own
                # error envelope (web-router.go)
                self._action = "Web"
                try:
                    webmod.handle(self, path, query)
                except Exception as e:  # noqa: BLE001
                    if not self._headers_sent:
                        self._error(s3errors.from_exception(e), path)
                    else:
                        self.close_connection = True
                self._finish_body()
            else:
                self._route_authed(path, query)
        finally:
            self.s3.release()
            self.s3.plane_stats.leave(loop=_loop_ix)
            if tenant is not None:
                self.s3.admission.leave_tenant(tenant)
            # collectAPIStats analogue: every authed-path request lands
            # in the metrics registry
            try:
                cl = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                cl = 0
            dur = _time.monotonic() - t0
            self.s3.metrics.observe(
                self._action or "Unknown",
                self._last_status or 0,
                dur,
                bytes_in=cl,
                bytes_out=self._resp_bytes,
                ttfb=self._ttfb,
            )
            self._emit_trace_audit(path, query, dur, cl)

    def _emit_trace_audit(self, path, query, dur, bytes_in) -> None:
        """httpTrace + logger.AuditLog tail of every request."""
        from . import trace as tracemod

        client = self.client_address[0] if self.client_address else ""
        if self.s3.tracer.active:
            self.s3.tracer.publish(
                tracemod.trace_info(
                    self.s3.tracer.node,
                    self.command,
                    path,
                    "&".join(f"{k}={v[0]}" for k, v in query.items()),
                    self._last_status or 0,
                    dur,
                    bytes_in,
                    self._resp_bytes,
                    client,
                    self._action or "Unknown",
                )
            )
        if self.s3.audit.enabled:
            parts = path.lstrip("/").split("/", 1)
            self.s3.audit.log(
                {
                    "api": {
                        "name": self._action or "Unknown",
                        "bucket": parts[0],
                        "object": parts[1] if len(parts) > 1 else "",
                        "statusCode": self._last_status or 0,
                        "timeToResponse_ms": round(dur * 1e3, 3),
                    },
                    "remotehost": client,
                    "userAgent": self.headers.get("User-Agent", ""),
                    "accessKey": (
                        self._auth.access_key
                        if self._auth and not self._auth.anonymous
                        else ""
                    ),
                    "rx": bytes_in,
                    "tx": self._resp_bytes,
                }
            )

    def _route_authed(self, path: str, query) -> None:
        try:
            # safe mode: every S3 request is 503 until the object layer
            # attaches, even unauthenticated ones (server-main.go safe
            # mode; advisor finding r2 — this must precede the anonymous
            # AccessDenied so bootstrap is observable from outside)
            if self.s3.object_layer is None:
                raise S3Error("ServerNotInitialized")
            # body-framing validity precedes auth, matching the generic
            # middleware order (requestValidityHandler, routers.go:41-79)
            self._body_size()
            # authenticate on headers only (setAuthHandler analogue);
            # payload hashes are verified as the body streams through
            ctx = self.s3.verifier.verify_stream(
                self.command, path, query, dict(self.headers.items())
            )
            self._auth = ctx
            # temp credentials must present their session token; static
            # credentials must not carry one (checkClaimsFromToken)
            if not ctx.anonymous:
                from ..iam.sys import InvalidToken

                token = self.headers.get(
                    "x-amz-security-token"
                ) or query.get("X-Amz-Security-Token", [""])[0]
                try:
                    self.s3.iam.validate_session_token(
                        ctx.access_key, token or None
                    )
                except InvalidToken as e:
                    raise S3Error("InvalidTokenId", str(e)) from None
            from . import admin as adminmod

            if path.startswith(adminmod.PREFIX + "/"):
                return self._route_admin(
                    path[len(adminmod.PREFIX) + 1 :], query, ctx
                )
            # STS plane: POST / with a form body carrying Action
            # (registerSTSRouter mounts on the root path)
            if (
                self.command == "POST"
                and path == "/"
                and (self.headers.get("Content-Type") or "").startswith(
                    "application/x-www-form-urlencoded"
                )
            ):
                from . import sts as stsmod

                form = stsmod.parse_form(self._read_body())
                if "Action" in form:
                    self._action = f"STS.{form.get('Action', '')}"
                    stsmod.handle_sts(self, form)
                    self._finish_body()
                    return
            self._authorize(path, query, ctx)
            self._dispatch(path, query)
        except Exception as e:  # noqa: BLE001
            if self._headers_sent:
                # mid-stream failure: a second response would be read as
                # body bytes (advisor finding r1) - just cut the stream
                self.close_connection = True
                return
            self._finish_body()
            self._error(s3errors.from_exception(e), path)
        else:
            self._finish_body()

    def _route_admin(self, tail: str, query, ctx) -> None:
        """Admin plane: SigV4-authenticated, owner-only
        (adminAPIHandlers privilege default)."""
        from .admin import AdminAPI, map_admin_error

        # metrics label only after the owner check: unauthenticated
        # garbage paths must not mint registry keys (cardinality)
        self._action = "Admin"
        if ctx.anonymous or not self.s3.iam.is_owner(ctx.access_key):
            raise S3Error("AccessDenied", "admin requires the owner")
        self._action = f"Admin.{tail}"
        if tail in ("trace", "console"):
            self._finish_body()
            return self._admin_stream(tail, query)
        body = b""
        if self.command in ("PUT", "POST"):
            body = self._read_body()
        q1 = {k: v[0] for k, v in query.items()}
        try:
            status, payload = AdminAPI(self.s3).handle(
                self.command, tail, q1, body
            )
        except Exception as e:  # noqa: BLE001
            mapped = map_admin_error(e)
            if mapped is None:
                raise
            raise mapped from e
        self._finish_body()
        self._respond(status, payload, content_type="application/json")

    def _admin_stream(self, kind: str, query) -> None:
        """`mc admin trace` / `mc admin console`: stream JSON lines for
        ``duration`` seconds, merging this node's ring with every
        peer's (TraceHandler + peerRESTClient.Trace aggregation,
        cmd/admin-handlers.go:1007)."""
        import json as _json

        try:
            duration = float(query.get("duration", ["10"])[0])
        except ValueError:
            duration = 10.0
        duration = max(0.1, min(duration, 300.0))
        local_ring = (
            self.s3.tracer.ring
            if kind == "trace"
            else self.s3.console.ring
        )
        peers = (
            self.s3.peer_notifier.clients
            if self.s3.peer_notifier is not None
            else []
        )
        self.send_response(200)
        self.send_header("Server", "MinIO-TPU")
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()
        # poll positions: ours + one per peer
        local_seq, _ = self.s3.tracer.poll(1 << 62) if kind == "trace" \
            else local_ring.since(1 << 62)
        # peers start from NOW, not their whole ring history: a None
        # cursor means "not handshaken yet" and triggers a probe with
        # since=1<<62 (whose items are discarded) on the next loop
        # turn - an unreachable peer simply stays None until it
        # answers, never replaying its ring from cursor 0
        peer_seq: "dict[int, int | None]" = {id(p): None for p in peers}
        deadline = _time.monotonic() + duration
        while _time.monotonic() < deadline:
            batch: list = []
            if kind == "trace":
                local_seq, items = self.s3.tracer.poll(local_seq)
            else:
                local_seq, items = local_ring.since(local_seq)
            batch.extend(items)
            for p in peers:
                pseq = peer_seq[id(p)]
                try:
                    res = p.call(
                        f"{kind}buf",
                        {"since": str(1 << 62 if pseq is None else pseq)},
                    )
                except Exception:  # noqa: BLE001
                    continue
                if "seq" in res:
                    peer_seq[id(p)] = res["seq"]
                if pseq is not None:
                    batch.extend(res.get("items", []))
            batch.sort(key=lambda e: e.get("time", 0))
            try:
                for item in batch:
                    line = (_json.dumps(item) + "\n").encode()
                    self.wfile.write(line)
                    self._resp_bytes += len(line)
                self.wfile.flush()
            except OSError:
                return  # client went away
            _time.sleep(0.5)

    do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = route

    def __getattr__(self, name):
        """ANY verb reaches route() (which answers MethodNotAllowed
        for non-S3 ones with full per-request init and body drain);
        without this, unknown verbs fall through to the stdlib's bare
        501 HTML."""
        if name.startswith("do_"):
            return self.route
        raise AttributeError(name)

    # -- authorization (checkRequestAuthType, auth-handler.go:272) --------

    def _bucket_policy(self, bucket: str):
        try:
            return self.s3.bucket_meta.get(bucket).policy()
        except Exception:  # noqa: BLE001 - missing bucket -> no policy
            return None

    def _check_action(
        self, action: str, bucket: str, key: str, account: str
    ) -> bool:
        """One policy decision (used per-key by multi-delete too)."""
        cond = authz.condition_values(
            {k: v for k, v in self._query.items()},
            dict(self.headers.items()),
            self.client_address[0] if self.client_address else "",
        )
        return authz.authorize(
            self.s3.iam,
            self._bucket_policy(bucket) if bucket else None,
            account,
            action,
            bucket,
            key,
            cond,
        )

    def _authorize(self, path: str, query, ctx) -> None:
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        self._query = query
        if bucket and authz.is_reserved_bucket(bucket):
            raise S3Error("AllAccessDisabled")
        if ctx.anonymous and self._is_post_policy(path, query):
            # POST form uploads carry their own signature; authorization
            # happens after the form parses (access key known then)
            return
        if self.command == "POST" and not key and "delete" in query:
            # multi-delete authorizes each named key inside the handler
            # (DeleteMultipleObjectsHandler); anonymous callers with no
            # bucket policy at all are cut off before the body is read
            if ctx.anonymous and self._bucket_policy(bucket) is None:
                raise S3Error("AccessDenied")
            return
        action = authz.action_for_request(
            self.command, bucket, key, query, dict(self.headers.items())
        )
        self._action = action.partition(":")[2]  # metrics API label
        if not self._check_action(action, bucket, key, ctx.access_key):
            raise S3Error("AccessDenied")
        # CopyObject/UploadPartCopy additionally need read access on the
        # source object
        if (
            self.command == "PUT"
            and key
            and "x-amz-copy-source" in self.headers
        ):
            sb, sk = self._parse_copy_source()
            if authz.is_reserved_bucket(sb):
                raise S3Error("AllAccessDisabled")
            if not self._check_action(
                "s3:GetObject", sb, sk, ctx.access_key
            ):
                raise S3Error("AccessDenied")

    def _route_internode(self, handler, method_tail: str, query) -> None:
        """Dispatch an internode-plane request.

        The bearer JWT is checked BEFORE the body is read and body size
        is capped, so an unauthenticated client cannot make this node
        buffer arbitrary bytes (advisor finding r2); plane handlers
        re-verify on their dispatch path (storage-rest-server.go:63-104)
        as defense in depth.
        """
        try:
            length = int(self.headers.get("Content-Length") or 0)
            if length > MAX_INTERNODE_BODY:
                self.close_connection = True
                self._respond(413, b"body too large", content_type="text/plain")
                return
            if self.s3.internode_secret:
                from ..utils import jwt as _jwt

                authz = self.headers.get("Authorization", "")
                try:
                    if not authz.startswith("Bearer "):
                        raise _jwt.JWTError("missing bearer token")
                    _jwt.verify(
                        authz[len("Bearer "):], self.s3.internode_secret
                    )
                except Exception:  # noqa: BLE001
                    self.close_connection = True
                    self._respond(
                        401, b"unauthorized", content_type="text/plain"
                    )
                    return
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if te == "chunked":
                # streaming shard plane: hand the decoded stream to the
                # plane handler - nothing buffers the whole body
                plane = getattr(handler, "__self__", None)
                stream_fn = getattr(plane, "handle_stream", None)
                if stream_fn is None:
                    self.close_connection = True
                    self._respond(
                        411, b"length required", content_type="text/plain"
                    )
                    return
                reader = _ChunkedReader(self.rfile)
                status, payload, extra = stream_fn(
                    method_tail, query, reader,
                    dict(self.headers.items()),
                )
                try:
                    reader.drain()  # keep-alive hygiene
                except OSError:
                    self.close_connection = True
                self._respond(
                    status, payload, extra,
                    content_type="application/octet-stream",
                )
                return
            body = self.rfile.read(length) if length else b""
            status, payload, extra = handler(
                method_tail, query, body, dict(self.headers.items())
            )
        except Exception as e:  # noqa: BLE001
            self.close_connection = True
            self._respond(
                500, str(e).encode(), content_type="text/plain"
            )
            return
        self._respond(
            status, payload, extra, content_type="application/octet-stream"
        )

    # -- dispatch (api-router.go route table) -----------------------------

    # Every S3 sub-resource keyword that selects a *different handler*.
    # After the explicit routes below, any of these still present means
    # the request asked for something this server does not serve - it
    # must fail loudly, never fall through to the default handler
    # (VERDICT r3 weak #1; the reference's router matches these with
    # mux .Queries() so a miss lands on proper error handlers).
    _OBJECT_SUBRESOURCES = frozenset(
        (
            "acl", "tagging", "retention", "legal-hold", "torrent",
            "restore", "select", "attributes", "uploads", "uploadId",
            "partNumber",
        )
    )
    _BUCKET_SUBRESOURCES = frozenset(
        (
            "acl", "cors", "website", "accelerate", "requestPayment",
            "logging", "inventory", "metrics", "analytics", "replication",
            "tagging", "encryption", "object-lock", "policy",
            "versioning", "notification", "lifecycle", "location",
            "uploads", "versions", "delete", "events", "publicAccessBlock",
            "ownershipControls", "intelligent-tiering",
        )
    )

    def _reject_subresources(self, query, vocab) -> None:
        unknown = vocab & set(query)
        if unknown:
            raise S3Error(
                "NotImplemented", f"?{sorted(unknown)[0]} is not supported"
            )

    def _dispatch(self, path: str, query):
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        m = self.command
        ol = self.s3.object_layer
        if ol is None:  # still bootstrapping (server-main.go safe mode)
            raise S3Error("ServerNotInitialized")

        if not bucket:
            if m == "GET":
                return self._list_buckets()
            raise S3Error("MethodNotAllowed")

        if self.s3.bucket_dns is not None and self._federated_redirect(
            bucket, key, m, query
        ):
            return

        if key:
            if m == "GET":
                if "uploadId" in query:
                    return self._list_parts(bucket, key, query)
                if "tagging" in query:
                    return self._get_object_tagging(bucket, key, query)
                if "retention" in query:
                    return self._get_object_retention(bucket, key, query)
                if "legal-hold" in query:
                    return self._get_object_legal_hold(bucket, key, query)
                if "acl" in query:
                    return self._get_acl(bucket, key)
                self._reject_subresources(
                    query, self._OBJECT_SUBRESOURCES
                )
                return self._get_object(bucket, key, query)
            if m == "HEAD":
                return self._head_object(bucket, key, query)
            if m == "PUT":
                if "partNumber" in query and "uploadId" in query:
                    return self._put_part(bucket, key, query)
                if "tagging" in query:
                    return self._put_object_tagging(bucket, key, query)
                if "retention" in query:
                    return self._put_object_retention(bucket, key, query)
                if "legal-hold" in query:
                    return self._put_object_legal_hold(bucket, key, query)
                if "acl" in query:
                    return self._put_acl(bucket, key)
                self._reject_subresources(
                    query, self._OBJECT_SUBRESOURCES
                )
                if "x-amz-copy-source" in self.headers:
                    return self._copy_object(bucket, key)
                return self._put_object(bucket, key)
            if m == "POST":
                if "uploads" in query:
                    return self._initiate_multipart(bucket, key)
                if "uploadId" in query:
                    return self._complete_multipart(
                        bucket, key, query, self._read_body()
                    )
                if "select" in query:
                    return self._select_object(bucket, key, query)
                self._reject_subresources(
                    query, self._OBJECT_SUBRESOURCES
                )
            if m == "DELETE":
                if "uploadId" in query:
                    return self._abort_multipart(bucket, key, query)
                if "tagging" in query:
                    return self._delete_object_tagging(bucket, key, query)
                self._reject_subresources(
                    query, self._OBJECT_SUBRESOURCES
                )
                return self._delete_object(bucket, key, query)
            raise S3Error("MethodNotAllowed")

        # bucket-level
        if m == "GET":
            if "events" in query:
                return self._listen_notification(bucket, query)
            if "location" in query:
                return self._respond(200, xmlr.location_xml(""))
            if "policy" in query:
                return self._get_bucket_policy(bucket)
            if "versions" in query:
                return self._list_object_versions(bucket, query)
            if "uploads" in query:
                return self._list_uploads(bucket, query)
            if "versioning" in query:
                ol.get_bucket_info(bucket)
                state = self.s3.bucket_meta.get(bucket).versioning
                inner = (
                    f"<Status>{state}</Status>" if state else ""
                ).encode()
                return self._respond(
                    200,
                    b'<?xml version="1.0" encoding="UTF-8"?>\n'
                    b'<VersioningConfiguration xmlns="'
                    + xmlr.S3_NS.encode()
                    + b'">' + inner + b"</VersioningConfiguration>",
                )
            if "notification" in query:
                return self._get_bucket_notification(bucket)
            if "lifecycle" in query:
                return self._get_bucket_lifecycle(bucket)
            if "tagging" in query:
                return self._get_bucket_tagging(bucket)
            if "object-lock" in query:
                return self._get_bucket_object_lock(bucket)
            if "encryption" in query:
                return self._get_bucket_encryption(bucket)
            if "acl" in query:
                return self._get_acl(bucket, "")
            # dummy configs the reference serves statically
            # (cmd/dummy-handlers.go): empty-but-valid documents
            if "accelerate" in query:
                ol.get_bucket_info(bucket)
                return self._respond(
                    200,
                    b'<?xml version="1.0" encoding="UTF-8"?>'
                    b"<AccelerateConfiguration "
                    b'xmlns="' + xmlr.S3_NS.encode() + b'"/>',
                )
            if "requestPayment" in query:
                ol.get_bucket_info(bucket)
                return self._respond(
                    200,
                    b'<?xml version="1.0" encoding="UTF-8"?>'
                    b'<RequestPaymentConfiguration xmlns="'
                    + xmlr.S3_NS.encode()
                    + b'"><Payer>BucketOwner</Payer>'
                    b"</RequestPaymentConfiguration>",
                )
            if "logging" in query:
                ol.get_bucket_info(bucket)
                return self._respond(
                    200,
                    b'<?xml version="1.0" encoding="UTF-8"?>'
                    b'<BucketLoggingStatus xmlns="'
                    + xmlr.S3_NS.encode()
                    + b'" />',
                )
            if "cors" in query:
                ol.get_bucket_info(bucket)
                raise S3Error("NoSuchCORSConfiguration")
            if "website" in query:
                ol.get_bucket_info(bucket)
                raise S3Error("NoSuchWebsiteConfiguration")
            if "replication" in query:
                return self._get_bucket_replication(bucket)
            self._reject_subresources(query, self._BUCKET_SUBRESOURCES)
            return self._list_objects(bucket, query)
        if m == "HEAD":
            ol.get_bucket_info(bucket)
            return self._respond(200)
        if m == "PUT":
            if "policy" in query:
                return self._put_bucket_policy(bucket, self._read_body())
            if "versioning" in query:
                return self._put_bucket_versioning(
                    bucket, self._read_body()
                )
            if "notification" in query:
                return self._put_bucket_notification(
                    bucket, self._read_body()
                )
            if "lifecycle" in query:
                return self._put_bucket_lifecycle(
                    bucket, self._read_body()
                )
            if "tagging" in query:
                return self._put_bucket_tagging(bucket, self._read_body())
            if "object-lock" in query:
                return self._put_bucket_object_lock(
                    bucket, self._read_body()
                )
            if "encryption" in query:
                return self._put_bucket_encryption(
                    bucket, self._read_body()
                )
            if "acl" in query:
                return self._put_acl(bucket, "")
            if "replication" in query:
                return self._put_bucket_replication(
                    bucket, self._read_body()
                )
            self._reject_subresources(query, self._BUCKET_SUBRESOURCES)
            return self._make_bucket(bucket)
        if m == "DELETE":
            if "policy" in query:
                ol.get_bucket_info(bucket)
                self.s3.bucket_meta.update(bucket, policy_json="")
                return self._respond(204)
            if "lifecycle" in query:
                ol.get_bucket_info(bucket)
                self.s3.bucket_meta.update(bucket, lifecycle_xml="")
                return self._respond(204)
            if "tagging" in query:
                ol.get_bucket_info(bucket)
                self.s3.bucket_meta.update(bucket, tagging_xml="")
                return self._respond(204)
            if "encryption" in query:
                ol.get_bucket_info(bucket)
                self.s3.bucket_meta.update(bucket, sse_config_xml="")
                return self._respond(204)
            if "replication" in query:
                return self._delete_bucket_replication(bucket)
            self._reject_subresources(query, self._BUCKET_SUBRESOURCES)
            self._bucket_delete(bucket)
            return self._respond(204)
        if m == "POST":
            if "delete" in query:
                # multi-delete bodies are key lists, not data: cap far
                # below the generic buffered-body limit before reading
                if self._body_size() > MAX_MULTI_DELETE_BODY:
                    raise S3Error("EntityTooLarge")
                return self._delete_multiple(bucket, self._read_body())
            if self._is_post_policy(path, query):
                return self._post_policy(bucket)
        raise S3Error("MethodNotAllowed")

    def _federated_redirect(self, bucket, key, m, query) -> bool:
        """Federation: requests for a bucket owned by ANOTHER cluster
        are answered 307 to its endpoint.  DELIBERATE DIVERGENCE from
        the reference, which relies on external DNS routing
        (bucket.domain) and only proxies the web plane - a redirect
        keeps path-style clients working without CoreDNS.  Returns
        True when the response was written."""
        from ..cluster.dns import DNSError, NoEntriesFound
        from ..objectlayer.api import BucketNotFound

        if not key and m == "PUT" and not query:
            return False  # bucket creation negotiates ownership itself
        try:
            self.s3.object_layer.get_bucket_info(bucket)
            return False  # ours: serve locally
        except BucketNotFound:
            pass
        except Exception:  # noqa: BLE001
            return False
        try:
            recs = self.s3.bucket_dns.lookup(bucket)
        except (NoEntriesFound, DNSError):
            return False  # genuinely absent: the normal 404 path
        if self.s3.bucket_dns.owned_by_us(recs):
            return False
        r = recs[0]
        # the OWNER's scheme rides the record - the local listener's
        # TLS mode says nothing about the remote cluster's
        self._respond(
            307,
            headers={
                "Location": f"{r.scheme}://{r.host}:{r.port}{self.path}"
            },
        )
        return True

    def _bucket_create(self, bucket: str) -> None:
        """Bucket creation incl. federation negotiation - ONE
        implementation for the S3 and web planes (a web create must
        be just as globally unique as an S3 one)."""
        dns = self.s3.bucket_dns
        if dns is not None:
            from ..cluster.dns import NoEntriesFound

            try:
                recs = dns.lookup(bucket)
            except NoEntriesFound:
                recs = None
            if recs is not None:
                # bucket names are globally unique across the
                # federation (bucket-handlers.go:601-609)
                raise S3Error(
                    "BucketAlreadyOwnedByYou"
                    if dns.owned_by_us(recs)
                    else "BucketAlreadyExists"
                )
        self.s3.object_layer.make_bucket(bucket)
        if dns is not None:
            from ..cluster.dns import RecordExists

            try:
                dns.register(bucket)
            except RecordExists:
                # lost the exclusive-create race to another cluster:
                # the bucket must not exist half-federated
                # (MakeBucket rollback, bucket-handlers.go:572)
                self.s3.object_layer.delete_bucket(bucket, force=True)
                raise S3Error("BucketAlreadyExists") from None
            except Exception:  # noqa: BLE001
                self.s3.object_layer.delete_bucket(bucket, force=True)
                raise S3Error(
                    "InternalError", "failed to register bucket in DNS"
                ) from None

    def _bucket_delete(self, bucket: str) -> None:
        """Bucket deletion incl. DNS unregistration and config/event
        cleanup - shared by the S3 and web planes."""
        self.s3.object_layer.delete_bucket(bucket)
        if self.s3.bucket_dns is not None:
            try:
                self.s3.bucket_dns.unregister(bucket)
            except Exception as exc:
                _log.debug("bucket DNS unregister failed; stale record", extra=kv(err=str(exc)))
        self.s3.bucket_meta.delete(bucket)
        # a recreated bucket must not inherit the old rules
        self.s3.events.remove_bucket(bucket)
        self.s3.invalidate_event_rules(bucket)

    def _make_bucket(self, bucket: str):
        """CreateBucket, honoring x-amz-bucket-object-lock-enabled
        (bucket-handlers.go:528): lock-enabled buckets are born
        versioned and carry a basic ObjectLockConfiguration."""
        from ..objectlayer import objectlock as olock

        lock_hdr = (
            self.headers.get("x-amz-bucket-object-lock-enabled") or ""
        ).lower()
        if lock_hdr and lock_hdr not in ("true", "false"):
            raise S3Error("InvalidRequest")
        self._bucket_create(bucket)
        if lock_hdr == "true":
            self.s3.bucket_meta.update(
                bucket,
                versioning="Enabled",
                object_lock_xml=olock.ObjectLockConfig().to_xml().decode(),
            )
        self._respond(200, headers={"Location": f"/{bucket}"})

    # -- service ----------------------------------------------------------

    def _listen_notification(self, bucket: str, query) -> None:
        """ListenBucketNotification (listen-notification-handlers.go):
        stream matching events to the client as JSON lines with
        whitespace keep-alives, until it disconnects.

        CLUSTER-WIDE: the subscription fans out over the peer plane
        (listenon/listenbuf/listenoff RPCs - the Listen peer RPC of
        cmd/notification.go:440), so a watcher on this node sees
        events originated on every node; remote records are polled by
        per-peer threads and merged into the same stream.
        """
        import json as _json
        import uuid as _uuid

        from ..event.event import EventName
        from ..event.event import matches_filter as ev_matches
        from ..event.event import to_listen_record

        self.s3.object_layer.get_bucket_info(bucket)
        prefix = query.get("prefix", [""])[0]
        suffix = query.get("suffix", [""])[0]
        names: "set[str]" = set()
        for raw in query.get("events", [""]):
            for part in raw.split(","):
                part = part.strip()
                if not part:
                    continue
                if not EventName.valid(part):
                    raise S3Error(
                        "InvalidArgument", f"unknown event {part!r}"
                    )
                names.update(EventName.expand(part))
        self._finish_body()
        sub = self.s3.events.subscribe_listener(bucket)
        # remote fan-out: register on every peer, poll each from its
        # own thread so one slow peer never stalls the stream
        import collections as _collections
        import threading as _threading

        remote_lines: "_collections.deque" = _collections.deque(
            maxlen=10_000
        )
        stop_remote = _threading.Event()
        pollers: "list[_threading.Thread]" = []
        lid = _uuid.uuid4().hex
        notifier = getattr(self.s3, "peer_notifier", None)

        def poll_peer(client):
            registered = False
            while not stop_remote.is_set():
                try:
                    if not registered:
                        client.listen_on(
                            lid, bucket, prefix, suffix, names
                        )
                        registered = True
                    for rec in client.listen_buf(lid):
                        remote_lines.append(
                            _json.dumps(rec).encode() + b"\n"
                        )
                except Exception:  # noqa: BLE001
                    registered = False  # peer bounced; re-register
                stop_remote.wait(0.25)
            if registered:
                try:
                    client.listen_off(lid)
                except Exception as exc:
                    _log.debug("remote listen_off failed", extra=kv(err=str(exc)))

        for client in getattr(notifier, "clients", []):
            t = _threading.Thread(
                target=poll_peer, args=(client,), daemon=True,
                name=f"listen-poll-{client.host}:{client.port}",
            )
            t.start()
            pollers.append(t)
        self.send_response(200)
        self.send_header("Server", "MinIO-TPU")
        self.send_header("Content-Type", "application/json")
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()
        self._last_status = 200
        last_keepalive = _time.monotonic()
        try:
            while not self.s3.draining:
                ev = sub.get(timeout=0.5)
                now = _time.monotonic()
                # keep-alive on EVERY idle-enough iteration: a steady
                # stream of filtered-out events must not starve the
                # client of bytes (proxies kill silent connections)
                if now - last_keepalive >= 5.0:
                    self.wfile.write(b" ")
                    self.wfile.flush()
                    last_keepalive = now
                while remote_lines:
                    line = remote_lines.popleft()
                    self.wfile.write(line)
                    self.wfile.flush()
                    self._resp_bytes += len(line)
                    last_keepalive = now
                if ev is None:
                    continue
                if not ev_matches(ev, bucket, names, prefix, suffix):
                    continue
                line = _json.dumps(
                    to_listen_record(ev)
                ).encode() + b"\n"
                self.wfile.write(line)
                self.wfile.flush()
                self._resp_bytes += len(line)
                last_keepalive = now
        except OSError:
            pass  # client went away: the normal way this ends
        finally:
            stop_remote.set()
            # join so listen_off reliably fires before the handler
            # returns (each poller wakes within 0.25s)
            for t in pollers:
                t.join(timeout=2)
            self.s3.events.unsubscribe_listener(bucket, sub)

    def _list_buckets(self):
        buckets = self.s3.object_layer.list_buckets()
        if self.s3.bucket_dns is not None:
            # federated view: every cluster's buckets, deduped
            # (bucket-handlers.go:74 dnsBuckets merge)
            from ..objectlayer.api import BucketInfo

            have = {b.name for b in buckets}
            try:
                federated = self.s3.bucket_dns.federated_buckets()
            except Exception:  # noqa: BLE001
                federated = {}
            for name, recs in sorted(federated.items()):
                if name not in have:
                    buckets.append(
                        BucketInfo(
                            name=name,
                            created_ns=min(
                                (r.creation_ns for r in recs),
                                default=0,
                            ),
                        )
                    )
            buckets.sort(key=lambda b: b.name)
        self._respond(200, xmlr.list_buckets_xml(buckets))

    # -- bucket ops -------------------------------------------------------

    def _list_objects(self, bucket: str, query):
        q1 = {k: v[0] for k, v in query.items()}
        try:
            max_keys = int(q1.get("max-keys", 1000))
        except ValueError:
            raise S3Error("InvalidArgument", "max-keys") from None
        if max_keys < 0:
            raise S3Error("InvalidArgument", "max-keys negative")
        prefix = q1.get("prefix", "")
        delimiter = q1.get("delimiter", "")
        encode = q1.get("encoding-type", "") == "url"
        if q1.get("list-type") == "2":
            token = q1.get("continuation-token", "")
            start_after = q1.get("start-after", "")
            try:
                marker = (
                    base64.urlsafe_b64decode(token.encode()).decode()
                    if token
                    else start_after
                )
            except Exception:  # noqa: BLE001
                raise S3Error(
                    "InvalidArgument", "continuation-token"
                ) from None
            res = self.s3.object_layer.list_objects(
                bucket, prefix, marker, delimiter, max_keys
            )
            body = xmlr.list_objects_v2_xml(
                bucket, prefix, delimiter, max_keys, start_after,
                token, res, encode,
            )
        else:
            marker = q1.get("marker", "")
            res = self.s3.object_layer.list_objects(
                bucket, prefix, marker, delimiter, max_keys
            )
            body = xmlr.list_objects_v1_xml(
                bucket, prefix, marker, delimiter, max_keys, res, encode
            )
        self._respond(200, body)

    # -- versioning (bucket-versioning-handler.go) ------------------------

    def _versioning(self, bucket: str) -> "tuple[bool, bool]":
        """(versioned, suspended) for the bucket."""
        try:
            bm = self.s3.bucket_meta.get(bucket)
        except Exception:  # noqa: BLE001
            return False, False
        return bm.versioning_enabled, bm.versioning_suspended

    def _put_bucket_versioning(self, bucket: str, body: bytes):
        self.s3.object_layer.get_bucket_info(bucket)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML") from None
        ns = (
            root.tag[: root.tag.index("}") + 1]
            if root.tag.startswith("{")
            else ""
        )
        status = (root.findtext(f"{ns}Status") or "").strip()
        if status not in ("Enabled", "Suspended"):
            raise S3Error("MalformedXML", "bad versioning Status")
        # suspending versioning on a lock-enabled bucket would let PUTs
        # overwrite retained versions (AWS rejects with 409)
        if (
            status == "Suspended"
            and self.s3.bucket_meta.get(bucket).object_lock_xml
        ):
            raise S3Error(
                "InvalidBucketState",
                "versioning cannot be suspended on object-lock buckets",
            )
        self.s3.bucket_meta.update(bucket, versioning=status)
        self._respond(200)

    def _list_object_versions(self, bucket: str, query):
        q1 = {k: v[0] for k, v in query.items()}
        try:
            max_keys = int(q1.get("max-keys", 1000))
        except ValueError:
            raise S3Error("InvalidArgument", "max-keys") from None
        if max_keys < 0:
            raise S3Error("InvalidArgument", "max-keys negative")
        prefix = q1.get("prefix", "")
        delimiter = q1.get("delimiter", "")
        key_marker = q1.get("key-marker", "")
        vid_marker = q1.get("version-id-marker", "")
        encode = q1.get("encoding-type", "") == "url"
        res = self.s3.object_layer.list_object_versions(
            bucket, prefix, key_marker, vid_marker, delimiter, max_keys
        )
        self._respond(
            200,
            xmlr.list_versions_xml(
                bucket, prefix, key_marker, vid_marker, delimiter,
                max_keys, res, encode,
            ),
        )

    # -- bucket policy (PutBucketPolicyHandler, bucket-policy-handlers.go)

    def _get_bucket_policy(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        pj = self.s3.bucket_meta.get(bucket).policy_json
        if not pj:
            raise S3Error("NoSuchBucketPolicy")
        self._respond(200, pj.encode(), content_type="application/json")

    def _put_bucket_policy(self, bucket: str, body: bytes):
        from ..iam.policy import Policy, PolicyError

        self.s3.object_layer.get_bucket_info(bucket)
        try:
            pol = Policy.from_json(body)
            pol.validate_bucket(bucket)
        except PolicyError as e:
            raise S3Error("MalformedPolicy", str(e)) from None
        self.s3.bucket_meta.update(
            bucket, policy_json=pol.to_json()
        )
        self._respond(204)

    # -- bucket notification (bucket-notification-handlers.go) ------------

    def _get_bucket_notification(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        raw = self.s3.bucket_meta.get(bucket).notification_xml
        if raw:
            return self._respond(200, raw.encode())
        from ..event.rules import NotificationConfig

        self._respond(200, NotificationConfig().to_xml())

    def _put_bucket_notification(self, bucket: str, body: bytes):
        from ..event.rules import NotificationConfig, NotificationError

        self.s3.object_layer.get_bucket_info(bucket)
        try:
            cfg = NotificationConfig.from_xml(body)
            # validates ARNs against registered targets AND installs
            # the rules (config.Validate + bucketRulesMap update)
            self.s3.events.set_bucket_config(bucket, cfg)
        except NotificationError as e:
            raise S3Error("InvalidArgument", str(e)) from None
        self.s3.bucket_meta.update(
            bucket, notification_xml=cfg.to_xml().decode()
        )
        self.s3.mark_event_rules_loaded(bucket)
        self._respond(200)

    # -- bucket lifecycle (bucket-lifecycle-handlers.go) ------------------

    def _get_bucket_lifecycle(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        raw = self.s3.bucket_meta.get(bucket).lifecycle_xml
        if not raw:
            raise S3Error("NoSuchLifecycleConfiguration")
        self._respond(200, raw.encode())

    def _put_bucket_lifecycle(self, bucket: str, body: bytes):
        from ..ilm import Lifecycle, LifecycleError

        self.s3.object_layer.get_bucket_info(bucket)
        try:
            lc = Lifecycle.from_xml(body)
        except LifecycleError as e:
            raise S3Error("MalformedXML", str(e)) from None
        self.s3.bucket_meta.update(
            bucket, lifecycle_xml=lc.to_xml().decode()
        )
        self._respond(200)

    # -- bucket tagging (bucket-handlers.go PutBucketTaggingHandler) ------

    def _get_bucket_tagging(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        raw = self.s3.bucket_meta.get(bucket).tagging_xml
        if not raw:
            raise S3Error("NoSuchTagSet")
        self._respond(200, raw.encode())

    def _put_bucket_tagging(self, bucket: str, body: bytes):
        from ..utils import tags as tagmod

        self.s3.object_layer.get_bucket_info(bucket)
        try:
            tags = tagmod.from_xml(body, tagmod.MAX_BUCKET_TAGS)
        except tagmod.TagXMLError as e:
            raise S3Error("MalformedXML", str(e)) from None
        except tagmod.TagError as e:
            raise S3Error("InvalidTag", str(e)) from None
        self.s3.bucket_meta.update(
            bucket, tagging_xml=tagmod.to_xml(tags).decode()
        )
        self._respond(200)

    # -- bucket encryption config (bucket-encryption-handlers.go) ---------

    def _get_bucket_encryption(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        raw = self.s3.bucket_meta.get(bucket).sse_config_xml
        if not raw:
            raise S3Error("ServerSideEncryptionConfigurationNotFoundError")
        self._respond(200, raw.encode())

    def _put_bucket_encryption(self, bucket: str, body: bytes):
        """Store the SSE default config; only SSE-S3 (AES256) is
        honored, mirroring validateBucketSSEConfig."""
        self.s3.object_layer.get_bucket_info(bucket)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML") from None
        from ..utils.xmlutil import strip_ns

        algos = [
            (el.text or "").strip()
            for el in root.iter()
            if strip_ns(el.tag) == "SSEAlgorithm"
        ]
        if algos != ["AES256"]:
            raise S3Error(
                "NotImplemented",
                "only a single AES256 default rule is supported",
            )
        self.s3.bucket_meta.update(
            bucket, sse_config_xml=body.decode("utf-8", "replace")
        )
        self._respond(200)

    # -- bucket object lock (bucket-handlers.go:1026) ---------------------

    def _get_bucket_object_lock(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        raw = self.s3.bucket_meta.get(bucket).object_lock_xml
        if not raw:
            raise S3Error("ObjectLockConfigurationNotFoundError")
        self._respond(200, raw.encode())

    def _put_bucket_object_lock(self, bucket: str, body: bytes):
        from ..objectlayer import objectlock as olock

        self.s3.object_layer.get_bucket_info(bucket)
        try:
            cfg = olock.ObjectLockConfig.from_xml(body)
        except olock.ObjectLockError as e:
            raise S3Error("MalformedXML", str(e)) from None
        # lock settings may only change on buckets born lock-enabled
        # (bucket-handlers.go:1060: "Deny object locking configuration
        # settings on existing buckets without object lock enabled")
        if not self.s3.bucket_meta.get(bucket).object_lock_xml:
            raise S3Error("ObjectLockConfigurationNotFoundError")
        self.s3.bucket_meta.update(
            bucket, object_lock_xml=cfg.to_xml().decode()
        )
        self._respond(200)

    # -- bucket replication config (bucket metadata only; async
    #    replication engine attaches in the replication module) ----------

    def _get_bucket_replication(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        raw = self.s3.bucket_meta.get(bucket).replication_xml
        if not raw:
            raise S3Error("ReplicationConfigurationNotFoundError")
        self._respond(200, raw.encode())

    def _put_bucket_replication(self, bucket: str, body: bytes):
        from ..replication.config import ReplicationConfig, ReplicationError

        self.s3.object_layer.get_bucket_info(bucket)
        if not self.s3.bucket_meta.get(bucket).versioning_enabled:
            raise S3Error("ReplicationSourceNotVersionedError")
        try:
            cfg = ReplicationConfig.from_xml(body)
        except ReplicationError as e:
            raise S3Error("MalformedXML", str(e)) from None
        self.s3.bucket_meta.update(
            bucket, replication_xml=cfg.to_xml().decode()
        )
        self._respond(200)

    def _delete_bucket_replication(self, bucket: str):
        self.s3.object_layer.get_bucket_info(bucket)
        self.s3.bucket_meta.update(bucket, replication_xml="")
        self._respond(204)

    # -- ACL stubs (cmd/acl-handlers.go: static FULL_CONTROL owner) -------

    def _get_acl(self, bucket: str, key: str):
        if key:
            self.s3.object_layer.get_object_info(bucket, key)
        else:
            self.s3.object_layer.get_bucket_info(bucket)
        self._respond(
            200,
            b'<?xml version="1.0" encoding="UTF-8"?>'
            b'<AccessControlPolicy xmlns="' + xmlr.S3_NS.encode() + b'">'
            b"<Owner><ID>minio</ID><DisplayName>minio</DisplayName></Owner>"
            b"<AccessControlList><Grant>"
            b'<Grantee xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance"'
            b' xsi:type="CanonicalUser">'
            b"<ID>minio</ID><DisplayName>minio</DisplayName></Grantee>"
            b"<Permission>FULL_CONTROL</Permission>"
            b"</Grant></AccessControlList></AccessControlPolicy>",
        )

    def _put_acl(self, bucket: str, key: str):
        """Only the 'private' canned ACL round-trips; anything else is
        NotImplemented (PutBucketACLHandler)."""
        if key:
            self.s3.object_layer.get_object_info(bucket, key)
        else:
            self.s3.object_layer.get_bucket_info(bucket)
        canned = self.headers.get("x-amz-acl", "")
        body = self._read_body()
        if canned and canned != "private":
            raise S3Error("NotImplemented", "only private ACL")
        if body and b"FULL_CONTROL" not in body and b"private" not in body:
            raise S3Error("NotImplemented", "only private ACL")
        self._respond(200)

    # -- object tagging (object-handlers.go PutObjectTaggingHandler) ------

    def _get_object_tagging(self, bucket, key, query):
        from ..utils import tags as tagmod

        vid = query.get("versionId", [""])[0]
        info = self.s3.object_layer.get_object_info(bucket, key, vid)
        tags = tagmod.decode(info.user_defined.get("x-amz-tagging", ""))
        hdrs = (
            {"x-amz-version-id": info.version_id}
            if info.version_id
            else None
        )
        self._respond(200, tagmod.to_xml(tags), hdrs)

    def _put_object_tagging(self, bucket, key, query):
        from ..utils import tags as tagmod

        vid = query.get("versionId", [""])[0]
        try:
            tags = tagmod.from_xml(
                self._read_body(), tagmod.MAX_OBJECT_TAGS
            )
        except tagmod.TagXMLError as e:
            raise S3Error("MalformedXML", str(e)) from None
        except tagmod.TagError as e:
            raise S3Error("InvalidTag", str(e)) from None
        self.s3.object_layer.update_object_meta(
            bucket, key, {"x-amz-tagging": tagmod.encode(tags)}, vid
        )
        self._respond(200)

    def _delete_object_tagging(self, bucket, key, query):
        vid = query.get("versionId", [""])[0]
        self.s3.object_layer.update_object_meta(
            bucket, key, {"x-amz-tagging": None}, vid
        )
        self._respond(204)

    # -- object retention / legal hold (object-handlers.go) ---------------

    def _require_lock_config(self, bucket: str):
        if not self.s3.bucket_meta.get(bucket).object_lock_xml:
            raise S3Error("InvalidBucketObjectLockConfiguration")

    def _get_object_retention(self, bucket, key, query):
        from ..objectlayer import objectlock as olock

        self._require_lock_config(bucket)
        vid = query.get("versionId", [""])[0]
        info = self.s3.object_layer.get_object_info(bucket, key, vid)
        ret = olock.Retention.from_meta(info.user_defined)
        if not ret.valid:
            raise S3Error("NoSuchObjectLockConfiguration")
        self._respond(200, ret.to_xml())

    def _put_object_retention(self, bucket, key, query):
        from ..objectlayer import objectlock as olock

        self._require_lock_config(bucket)
        vid = query.get("versionId", [""])[0]
        try:
            ret = olock.Retention.from_xml(self._read_body())
        except olock.ObjectLockError as e:
            raise S3Error("MalformedXML", str(e)) from None
        info = self.s3.object_layer.get_object_info(bucket, key, vid)
        cur = olock.Retention.from_meta(info.user_defined)
        active = (
            cur.valid
            and cur.retain_until is not None
            and cur.retain_until > olock.utcnow()
        )
        # strengthening is always allowed: same-or-stronger mode with a
        # same-or-later date (COMPLIANCE > GOVERNANCE).  Anything else
        # against an active retention is a weakening attempt.
        strengthens = ret.retain_until >= cur.retain_until if active else True
        if active and cur.mode == olock.COMPLIANCE:
            # COMPLIANCE can never be weakened, by anyone
            # (enforceRetentionBypassForPut compliance branch)
            if ret.mode != olock.COMPLIANCE or not strengthens:
                raise S3Error("ObjectLocked")
        elif active and cur.mode == olock.GOVERNANCE:
            # weakening GOVERNANCE needs the bypass header + permission;
            # upgrading to COMPLIANCE or extending the date does not
            if (
                not (strengthens and ret.mode in (olock.GOVERNANCE,
                                                  olock.COMPLIANCE))
                and not self._governance_bypass_allowed(bucket, key)
            ):
                raise S3Error("ObjectLocked")
        self.s3.object_layer.update_object_meta(
            bucket, key,
            {
                olock.META_MODE: ret.mode,
                olock.META_RETAIN_UNTIL: olock.format_iso8601(
                    ret.retain_until
                ),
            },
            vid,
        )
        self._respond(200)

    def _get_object_legal_hold(self, bucket, key, query):
        from ..objectlayer import objectlock as olock

        self._require_lock_config(bucket)
        vid = query.get("versionId", [""])[0]
        info = self.s3.object_layer.get_object_info(bucket, key, vid)
        status = info.user_defined.get(olock.META_LEGAL_HOLD, "OFF")
        self._respond(200, olock.legal_hold_xml(status))

    def _put_object_legal_hold(self, bucket, key, query):
        from ..objectlayer import objectlock as olock

        self._require_lock_config(bucket)
        vid = query.get("versionId", [""])[0]
        try:
            status = olock.parse_legal_hold_xml(self._read_body())
        except olock.ObjectLockError as e:
            raise S3Error("MalformedXML", str(e)) from None
        self.s3.object_layer.update_object_meta(
            bucket, key, {olock.META_LEGAL_HOLD: status}, vid
        )
        self._respond(200)

    def _governance_bypass_allowed(self, bucket: str, key: str) -> bool:
        """Caller set x-amz-bypass-governance-retention AND holds the
        bypass permission (enforceRetentionBypassForDelete)."""
        from ..objectlayer import objectlock as olock

        if not olock.is_governance_bypass(dict(self.headers.items())):
            return False
        account = self._auth.access_key if self._auth else ""
        return self._check_action(
            "s3:BypassGovernanceRetention", bucket, key, account
        )

    def _enforce_worm(self, bucket, key, version_id: str) -> None:
        """Block deletion of WORM-protected versions.  Only consulted
        when the bucket carries an object-lock configuration."""
        from ..objectlayer import objectlock as olock

        from ..objectlayer.api import (
            BucketNotFound,
            ObjectNotFound,
            VersionNotFound,
        )

        try:
            if not self.s3.bucket_meta.get(bucket).object_lock_xml:
                return
        except BucketNotFound:
            return
        try:
            info = self.s3.object_layer.get_object_info(
                bucket, key, version_id
            )
        except (ObjectNotFound, VersionNotFound):
            # absent version / delete marker: nothing to protect.  Any
            # OTHER failure (quorum loss, lock timeout) must propagate -
            # a WORM gate that fails open is not a gate.
            return
        blocked = olock.retention_blocks_delete(
            info.user_defined,
            bypass_governance=self._governance_bypass_allowed(bucket, key),
        )
        if blocked is not None:
            raise S3Error("ObjectLocked")

    def _notify(
        self, name, bucket, key, etag="", size=0, version_id=""
    ) -> None:
        """Queue a bucket event (sendEvent, cmd/notification.go) -
        O(1) when the bucket has no notification rules AND nobody is
        listening (live ListenBucketNotification streams receive
        events regardless of configured rules)."""
        s3 = self.s3
        s3.ensure_event_rules(bucket)
        if not s3.events.rules.has_rules(bucket) and not (
            s3.events.has_listeners(bucket)
        ):
            return
        from ..event import Event, Identity

        ctx = self._auth
        s3.events.send(
            Event(
                name=name,
                bucket=bucket,
                object_key=key,
                etag=etag,
                size=size,
                version_id=version_id,
                identity=Identity(
                    "" if ctx is None or ctx.anonymous else ctx.access_key,
                    self.client_address[0] if self.client_address else "",
                ),
                endpoint=s3.endpoint,
            )
        )

    def _delete_multiple(self, bucket: str, body: bytes):
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML") from None
        ns = ""
        if root.tag.startswith("{"):
            ns = root.tag[: root.tag.index("}") + 1]
        quiet = (root.findtext(f"{ns}Quiet") or "").lower() == "true"
        deleted, errs = [], []
        account = self._auth.access_key if self._auth else ""
        versioned, suspended = self._versioning(bucket)
        for obj in root.findall(f"{ns}Object"):
            key = obj.findtext(f"{ns}Key") or ""
            vid = (obj.findtext(f"{ns}VersionId") or "").strip()
            # per-key authorization (DeleteMultipleObjectsHandler checks
            # DeleteObject for every named key)
            action = "s3:DeleteObjectVersion" if vid else "s3:DeleteObject"
            if not self._check_action(action, bucket, key, account):
                errs.append((key, "AccessDenied", "Access Denied."))
                continue
            try:
                if vid or not (versioned or suspended):
                    self._enforce_worm(bucket, key, vid)
            except S3Error as e:
                errs.append((key, e.err.code, e.err.message))
                continue
            try:
                # a named version is removed outright; an unqualified
                # delete on a versioned bucket writes a marker
                dinfo = self.s3.object_layer.delete_object(
                    bucket, key, vid,
                    versioned=versioned, version_suspended=suspended,
                )
                from ..event.event import EventName

                self._notify(
                    EventName.OBJECT_REMOVED_DELETE_MARKER
                    if dinfo.delete_marker
                    else EventName.OBJECT_REMOVED_DELETE,
                    bucket, key, version_id=dinfo.version_id or vid,
                )
                if not quiet:
                    deleted.append(key)
            except Exception as e:  # noqa: BLE001
                err = s3errors.from_exception(e)
                if err.code in ("NoSuchKey", "NoSuchVersion"):
                    if not quiet:
                        deleted.append(key)  # S3 treats as success
                else:
                    errs.append((key, err.code, err.message))
        self._respond(200, xmlr.delete_result_xml(deleted, errs))

    def _post_policy(self, bucket: str):
        """Browser form upload (PostPolicyBucketHandler,
        cmd/bucket-handlers.go): multipart/form-data with a signed,
        base64-encoded policy document."""
        ctype = self.headers.get("Content-Type", "")
        boundary = ""
        for param in ctype.split(";")[1:]:
            k, _, v = param.strip().partition("=")
            if k == "boundary":
                boundary = v.strip('"')
        if not boundary:
            raise S3Error("MalformedPOSTRequest", "missing boundary")
        reader, size = self._open_body()
        if size > MAX_IN_MEMORY_BODY:
            raise S3Error("EntityTooLarge")
        body = b""
        while len(body) < size:
            c = reader.read(size - len(body))
            if not c:
                break
            body += c
        form, file_data, file_name = _parse_multipart_form(body, boundary)
        key = form.get("key", "")
        if not key:
            raise S3Error("InvalidArgument", "POST requires key field")
        key = key.replace("${filename}", file_name)
        form["key"] = key
        form["bucket"] = bucket
        form["content-length"] = str(len(file_data))
        post_account = self.s3.verifier.verify_post_policy(form)
        # the form's signer must hold PutObject (isPutActionAllowed,
        # auth-handler.go:583)
        if not self._check_action(
            "s3:PutObject", bucket, key, post_account
        ):
            raise S3Error("AccessDenied")
        meta = {}
        if form.get("content-type"):
            meta["content-type"] = form["content-type"]
        for k, v in form.items():
            if k.startswith("x-amz-meta-"):
                meta[k] = v
        hreader = HashReader(io.BytesIO(file_data), len(file_data))
        from ..event.event import EventName

        info = self._checked_put(
            bucket, key, hreader, len(file_data), meta,
            versioned=self._versioning(bucket)[0],
            event_name=EventName.OBJECT_CREATED_POST,
        )
        status = form.get("success_action_status", "204")
        etag_hdr = {"ETag": f'"{info.etag}"'}
        if status == "201":
            location = f"{self.s3.endpoint}/{bucket}/{key}"
            self._respond(
                201,
                xmlr.post_response_xml(location, bucket, key, info.etag),
                {**etag_hdr, "Location": location},
            )
        elif status == "200":
            self._respond(200, b"", etag_hdr)
        else:
            self._respond(204, b"", etag_hdr)

    # -- object ops -------------------------------------------------------

    def _object_headers(self, info: ObjectInfo) -> dict:
        h = {
            "ETag": f'"{info.etag}"',
            "Last-Modified": email.utils.formatdate(
                info.mod_time, usegmt=True
            ),
            "Accept-Ranges": "bytes",
        }
        if info.content_type:
            h["Content-Type-Override"] = info.content_type
        for k, v in info.user_defined.items():
            if k.startswith("x-amz-meta-") or k.startswith(
                "x-amz-object-lock-"
            ):
                h[k] = v
        if info.version_id:
            h["x-amz-version-id"] = info.version_id
        return h

    def _check_conditions(self, info: ObjectInfo):
        """Conditional header evaluation (object-handlers-common.go)."""
        inm = self.headers.get("If-None-Match")
        im = self.headers.get("If-Match")
        ims = self.headers.get("If-Modified-Since")
        ius = self.headers.get("If-Unmodified-Since")
        etag = f'"{info.etag}"'
        if im and im not in (etag, "*", info.etag):
            raise S3Error("PreconditionFailed")
        if inm and inm in (etag, "*", info.etag):
            raise S3Error("NotModified")
        if ims:
            t = email.utils.parsedate_to_datetime(ims)
            if t and info.mod_time <= t.timestamp():
                raise S3Error("NotModified")
        if ius:
            t = email.utils.parsedate_to_datetime(ius)
            if t and info.mod_time > t.timestamp():
                raise S3Error("PreconditionFailed")

    def _parse_range(self, total: int) -> "tuple[int, int] | None":
        """Parse Range: bytes=a-b (httprange.go)."""
        hdr = self.headers.get("Range")
        if not hdr:
            return None
        if not hdr.startswith("bytes="):
            return None  # ignored per RFC
        spec = hdr[len("bytes=") :]
        if "," in spec:
            raise S3Error("NotImplemented", "multiple ranges")
        lo_s, _, hi_s = spec.partition("-")
        try:
            if lo_s == "":
                # suffix range
                n = int(hi_s)
                if n == 0:
                    raise S3Error("InvalidRange")
                lo = max(0, total - n)
                hi = total - 1
            else:
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else total - 1
        except ValueError:
            raise S3Error("InvalidRange") from None
        if lo > hi or lo >= total:
            raise S3Error("InvalidRange")
        return lo, min(hi, total - 1)

    def _get_object(self, bucket, key, query):
        """Stream the object body straight to the socket: headers go out
        first (size known from metadata), then the erasure decode writes
        block-by-block into wfile - constant memory per request."""
        ol = self.s3.object_layer
        version_id = query.get("versionId", [""])[0]
        info, sse = self._read_info_and_sse(ol, bucket, key, version_id)
        self._check_conditions(info)
        rng = self._parse_range(info.size)
        headers = self._object_headers(info)
        headers.update(self._sse_response_headers(info.user_defined))
        headers.pop("Content-Type-Override", None)
        # tag count rides GET responses only (GetObject API contract)
        tag_enc = info.user_defined.get("x-amz-tagging", "")
        if tag_enc:
            headers["x-amz-tagging-count"] = str(len(tag_enc.split("&")))
        ct = info.content_type or "application/octet-stream"
        if rng:
            lo, hi = rng
            status, length = 206, hi - lo + 1
            headers["Content-Range"] = f"bytes {lo}-{hi}/{info.size}"
        else:
            status, length = 200, info.size
            lo = 0
        self.send_response(status)
        self.send_header("Server", "MinIO-TPU")
        self.send_header(
            "x-amz-request-id", uuid.uuid4().hex[:16].upper()
        )
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Type", ct)
        self.send_header("Content-Length", str(length))
        self.end_headers()
        if length:
            try:
                ol.get_object(
                    bucket, key, self.wfile, lo, length, version_id,
                    sse,
                )
                self._resp_bytes += length
            except Exception:  # noqa: BLE001
                # headers already sent; the only honest signal is a
                # broken connection (the reference behaves the same)
                self.close_connection = True
                raise ConnectionError(
                    "mid-stream decode failure"
                ) from None
        from ..event.event import EventName

        self._notify(
            EventName.OBJECT_ACCESSED_GET, bucket, key,
            size=length, version_id=version_id,
        )

    def _head_object(self, bucket, key, query):
        version_id = query.get("versionId", [""])[0]
        info, _sse = self._read_info_and_sse(
            self.s3.object_layer, bucket, key, version_id
        )  # key required (and checked) for HEAD too
        self._check_conditions(info)
        headers = self._object_headers(info)
        headers.update(self._sse_response_headers(info.user_defined))
        headers.pop("Content-Type-Override", None)
        self.send_response(200)
        self.send_header("Server", "MinIO-TPU")
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header(
            "Content-Type",
            info.content_type or "application/octet-stream",
        )
        self.send_header("Content-Length", str(info.size))
        self.end_headers()
        from ..event.event import EventName

        self._notify(
            EventName.OBJECT_ACCESSED_HEAD, bucket, key,
            info.etag, info.size, info.version_id,
        )

    def _put_lock_and_tag_meta(self, bucket: str, key: str) -> dict:
        """PUT-time tagging + object-lock metadata
        (checkPutObjectLockAllowed, cmd/object-handlers.go; the
        x-amz-tagging header carries URL-encoded tags)."""
        from ..objectlayer import objectlock as olock
        from ..utils import tags as tagmod

        meta: dict = {}
        tag_hdr = self.headers.get("x-amz-tagging", "")
        if tag_hdr:
            try:
                tags = tagmod.from_header(tag_hdr)
            except tagmod.TagError as e:
                raise S3Error("InvalidTag", str(e)) from None
            meta["x-amz-tagging"] = tagmod.encode(tags)
        try:
            lock_meta = olock.retention_meta_from_headers(
                dict(self.headers.items())
            )
        except olock.ObjectLockError as e:
            raise S3Error("ObjectLockInvalidHeaders", str(e)) from None
        lock_xml = ""
        try:
            lock_xml = self.s3.bucket_meta.get(bucket).object_lock_xml
        except Exception as exc:
            _log.debug("bucket object-lock config read failed", extra=kv(err=str(exc)))
        if lock_meta:
            # explicit lock headers need the bucket to be lock-enabled
            if not lock_xml:
                raise S3Error("InvalidBucketObjectLockConfiguration")
            meta.update(lock_meta)
        elif lock_xml:
            # no explicit headers: the bucket's default rule stamps
            # every new version
            try:
                cfg = olock.ObjectLockConfig.from_xml(lock_xml.encode())
                meta.update(cfg.default_retention_meta())
            except olock.ObjectLockError:
                pass
        return meta

    def _collect_user_metadata(self) -> dict:
        meta = {}
        ct = self.headers.get("Content-Type")
        if ct:
            meta["content-type"] = ct
        for k, v in self.headers.items():
            lk = k.lower()
            if lk.startswith("x-amz-meta-"):
                meta[lk] = v
        return meta

    def _checked_put(
        self, bucket, key, hreader, size, meta,
        versioned=False, event_name=None,
    ):
        """The full PUT invariant chain - size cap, quota,
        lock/tagging defaults, replication stamp + queue,
        bucket-default/requested SSE, event - shared by the S3 PUT,
        POST-policy, and web-upload paths so the invariants cannot
        drift between them (objectPutValidate* in the reference's
        object-handlers.go / web-handlers.go)."""
        if size > MAX_OBJECT_SIZE:
            raise S3Error("EntityTooLarge")
        from ..objectlayer import quota as quotamod

        quotamod.enforce_put(self.s3, bucket, size)
        meta.update(self._put_lock_and_tag_meta(bucket, key))
        replicate = self.s3.replication.should_replicate(bucket, key)
        if replicate:
            from ..replication.replicate import META_REPLICATION_STATUS

            meta[META_REPLICATION_STATUS] = "PENDING"
        sse = self._request_sse(bucket)
        # transparent compression (MINIO_TPU_COMPRESS) is decided inside
        # the object layer so POST-policy/multipart/copy share the seam
        info = self.s3.object_layer.put_object(
            bucket, key, hreader, size, meta,
            versioned=versioned, sse=sse,
        )
        if replicate:
            self.s3.replication.queue(bucket, key, info.version_id)
        from ..event.event import EventName

        self._notify(
            event_name or EventName.OBJECT_CREATED_PUT, bucket, key,
            info.etag, info.size, info.version_id,
        )
        return info

    def _put_object(self, bucket, key):
        """Stream the body straight into the erasure encoder in
        block_size chunks (cmd/erasure-encode.go:73-109) - bounded memory
        regardless of object size."""
        reader, size = self._open_body()
        if size > MAX_OBJECT_SIZE:
            raise S3Error("EntityTooLarge")
        hreader = self._hash_reader(reader, size)
        versioned, _ = self._versioning(bucket)
        meta = self._collect_user_metadata()
        info = self._checked_put(
            bucket, key, hreader, size, meta, versioned=versioned
        )
        hdrs = {"ETag": f'"{info.etag}"'}
        hdrs.update(self._sse_response_headers(info.user_defined))
        if info.version_id:
            hdrs["x-amz-version-id"] = info.version_id
        self._respond(200, b"", hdrs)

    # -- server-side encryption plumbing (cmd/crypto/header.go,
    #    cmd/encryption-v1.go) ----------------------------------------

    def _parse_ssec_headers(self, prefix: str):
        """SSESpec from the SSE-C header triplet under ``prefix``, or
        None when absent.  Validation order and messages follow
        crypto.SSEC.ParseHTTP (cmd/crypto/header.go:208)."""
        algo = self.headers.get(f"{prefix}-algorithm")
        key_b64 = self.headers.get(f"{prefix}-key")
        md5_b64 = self.headers.get(f"{prefix}-key-MD5")
        if algo is None and key_b64 is None and md5_b64 is None:
            return None
        if not getattr(self.s3, "tls", False):
            # ErrInsecureSSECustomerRequest: keys must never ride
            # plaintext HTTP
            raise S3Error(
                "InvalidRequest",
                "Requests specifying Server Side Encryption with "
                "Customer provided keys must be made over a secure "
                "connection.",
            )
        if algo != "AES256":
            raise S3Error(
                "InvalidArgument",
                "Requests specifying Server Side Encryption with "
                "Customer provided keys must provide a valid "
                "encryption algorithm.",
            )
        if not key_b64:
            raise S3Error(
                "InvalidArgument",
                "Requests specifying Server Side Encryption with "
                "Customer provided keys must provide an appropriate "
                "secret key.",
            )
        if not md5_b64:
            raise S3Error(
                "InvalidArgument",
                "Requests specifying Server Side Encryption with "
                "Customer provided keys must provide the client "
                "calculated MD5 of the secret key.",
            )
        import base64 as b64

        from ..codec import sse as ssemod

        try:
            key = b64.b64decode(key_b64, validate=True)
        except Exception:  # noqa: BLE001
            raise S3Error(
                "InvalidArgument", "The secret key was invalid."
            ) from None
        if len(key) != 32:
            raise S3Error(
                "InvalidArgument",
                "The secret key was invalid for the specified "
                "algorithm.",
            )
        if ssemod.key_md5_b64(key) != md5_b64:
            raise S3Error(
                "InvalidArgument",
                "The calculated MD5 hash of the key did not match "
                "the hash that was provided.",
            )
        return ssemod.SSESpec("C", key)

    def _request_sse(self, bucket: str):
        """Encryption intent of a write (PUT/copy-dest/initiate-
        multipart): explicit SSE-C or SSE-S3 headers, else the
        bucket's default encryption config.  SSE-KMS requests return
        NotImplemented exactly like the reference
        (object-handlers.go:102)."""
        from ..codec import sse as ssemod

        passthrough = getattr(
            self.s3.object_layer, "sse_passthrough", False
        )
        spec = self._parse_ssec_headers(
            "x-amz-server-side-encryption-customer"
        )
        algo = self.headers.get("x-amz-server-side-encryption")
        if spec is not None:
            if algo:
                raise S3Error(
                    "InvalidRequest",
                    "SSE-C and SSE-S3 headers are mutually exclusive",
                )
            return spec
        if algo is not None:
            if algo == "aws:kms":
                raise S3Error("NotImplemented", "SSE-KMS")
            if algo != "AES256":
                raise S3Error(
                    "InvalidRequest",
                    "The encryption method specified is not supported",
                )
            if not passthrough and not ssemod.sse_s3_available():
                # a gateway only forwards the header; the UPSTREAM's
                # KMS does the work, so no local KMS is needed
                raise S3Error(
                    "InvalidArgument",
                    "Server side encryption specified but KMS is not "
                    "configured",
                )
            return ssemod.SSESpec("S3")
        # bucket-default SSE (PutBucketEncryption config): applied
        # when the request itself is silent (validateAndGetSSE)
        try:
            raw = self.s3.bucket_meta.get(bucket).sse_config_xml
        except Exception:  # noqa: BLE001
            raw = ""
        if raw and self._default_sse_algo(raw) == "AES256":
            if not passthrough and not ssemod.sse_s3_available():
                # the bucket DEMANDS encryption: storing plaintext
                # because the KMS went away would silently violate it
                raise S3Error(
                    "InvalidArgument",
                    "Bucket default encryption is configured but KMS "
                    "is not configured",
                )
            return ssemod.SSESpec("S3")
        return None

    @staticmethod
    def _default_sse_algo(raw: str) -> str:
        """SSEAlgorithm of the bucket's default-encryption rule
        (parsed, not substring-matched)."""
        try:
            root = ET.fromstring(raw)
        except ET.ParseError:
            return ""
        for el in root.iter():
            if el.tag.split("}")[-1] == "SSEAlgorithm":
                return (el.text or "").strip()
        return ""

    def _copy_source_info_and_sse(self, src_bucket, src_key):
        """(src_info, source read-spec) for copy operations; gateway
        layers forward the copy-source customer key to the upstream
        instead of running local SSE guards (like _read_info_and_sse
        for GET/HEAD)."""
        ol = self.s3.object_layer
        if getattr(ol, "sse_passthrough", False):
            spec = self._parse_ssec_headers(
                "x-amz-copy-source-server-side-encryption-customer"
            )
            info = ol.get_object_info(src_bucket, src_key, sse=spec)
            return info, spec
        info = ol.get_object_info(src_bucket, src_key)
        return info, self._read_sse(info, copy_source=True)

    def _read_info_and_sse(self, ol, bucket, key, version_id):
        """(info, read-spec) for a GET/HEAD.  Gateway layers do SSE
        pass-through: the UPSTREAM owns encryption, so the request's
        customer key rides the gateway HEAD/GET verbatim and the
        local _read_sse guards do not apply (gateway-s3-sse.go)."""
        if getattr(ol, "sse_passthrough", False):
            spec = self._parse_ssec_headers(
                "x-amz-server-side-encryption-customer"
            )
            info = ol.get_object_info(
                bucket, key, version_id, sse=spec
            )
            return info, spec
        info = ol.get_object_info(bucket, key, version_id)
        return info, self._read_sse(info)

    def _read_sse(self, info, copy_source: bool = False):
        """Spec needed to READ ``info``; enforces that SSE-C objects
        are fetched with their key and non-SSE-C objects without one
        (getEncryptedObject guards, cmd/encryption-v1.go)."""
        from ..codec import sse as ssemod

        prefix = (
            "x-amz-copy-source-server-side-encryption-customer"
            if copy_source
            else "x-amz-server-side-encryption-customer"
        )
        spec = self._parse_ssec_headers(prefix)
        mode = (info.user_defined or {}).get(ssemod.META_SSE)
        if mode == "C" and spec is None:
            raise S3Error(
                "InvalidRequest",
                "The object was stored using a form of Server Side "
                "Encryption. The correct parameters must be provided "
                "to retrieve the object.",
            )
        if mode != "C" and spec is not None:
            raise S3Error(
                "InvalidRequest",
                "Encryption parameters were provided but the object "
                "is not encrypted with a customer key",
            )
        if mode == "C" and ssemod.key_md5_b64(spec.key) != (
            info.user_defined.get(ssemod.META_SSE_KEY_MD5)
        ):
            # wrong key, detected BEFORE headers go out - a mid-stream
            # decrypt failure can only abort the connection
            raise S3Error(
                "AccessDenied",
                "The provided encryption key does not match the key "
                "used to encrypt the object",
            )
        return spec if mode == "C" else None

    @staticmethod
    def _sse_response_headers(meta: dict) -> dict:
        from ..codec import sse as ssemod

        mode = (meta or {}).get(ssemod.META_SSE)
        if mode == "C":
            return {
                "x-amz-server-side-encryption-customer-algorithm":
                    "AES256",
                "x-amz-server-side-encryption-customer-key-MD5":
                    meta.get(ssemod.META_SSE_KEY_MD5, ""),
            }
        if mode == "S3":
            return {"x-amz-server-side-encryption": "AES256"}
        return {}

    def _parse_copy_source(self) -> "tuple[str, str]":
        """(bucket, key) from x-amz-copy-source - one parser for both
        the authorization and handler sides so they cannot drift."""
        src = urllib.parse.unquote(
            self.headers["x-amz-copy-source"]
        ).lstrip("/")
        if "/" not in src:
            raise S3Error("InvalidArgument", "bad copy source")
        return src.split("/", 1)

    def _copy_object(self, bucket, key):
        src_bucket, src_key = self._parse_copy_source()
        directive = self.headers.get(
            "x-amz-metadata-directive", "COPY"
        )
        if (src_bucket, src_key) == (bucket, key) and directive != "REPLACE":
            # S3: copying onto itself without changing metadata is
            # rejected (CopyObjectHandler)
            raise S3Error(
                "InvalidRequest",
                "self-copy requires x-amz-metadata-directive: REPLACE",
            )
        # destination-bucket lock defaults / explicit lock headers and
        # REPLACE-directive tags stamp the new version
        lock_tag = self._put_lock_and_tag_meta(bucket, key)
        # quota + replication apply to copies exactly like PUTs
        # (code-review r4: copy must not bypass either)
        from ..objectlayer import quota as quotamod

        src_info, sse_src = self._copy_source_info_and_sse(
            src_bucket, src_key
        )
        sse_dst = self._request_sse(bucket)
        quotamod.enforce_put(self.s3, bucket, src_info.size)
        replicate = self.s3.replication.should_replicate(bucket, key)
        if replicate:
            from ..replication.replicate import META_REPLICATION_STATUS

            lock_tag = {
                **lock_tag, META_REPLICATION_STATUS: "PENDING",
            }
        meta = (
            self._collect_user_metadata()
            if directive == "REPLACE"
            else None
        )
        if meta is not None:
            meta.update(lock_tag)
        versioned, _ = self._versioning(bucket)
        info = self.s3.object_layer.copy_object(
            src_bucket, src_key, bucket, key, meta,
            versioned=versioned, sse_src=sse_src, sse=sse_dst,
        )
        if meta is None and lock_tag:
            # COPY directive keeps source metadata; lock/replication
            # stamps still apply to the fresh destination version
            self.s3.object_layer.update_object_meta(
                bucket, key, lock_tag, info.version_id
            )
        if replicate:
            self.s3.replication.queue(bucket, key, info.version_id)
        hdrs = (
            {"x-amz-version-id": info.version_id}
            if info.version_id
            else None
        )
        from ..event.event import EventName

        self._notify(
            EventName.OBJECT_CREATED_COPY, bucket, key,
            info.etag, info.size, info.version_id,
        )
        self._respond(
            200, xmlr.copy_object_xml(info.etag, info.mod_time_ns), hdrs
        )

    def _select_object(self, bucket, key, query):
        """SelectObjectContent (object-handlers.go:91): SQL over one
        object, streamed back as EventStream frames."""
        from . import select as selmod

        body = self._read_body()
        info = self.s3.object_layer.get_object_info(bucket, key)
        selmod.handle_select(self, bucket, key, info, body)

    def _delete_object(self, bucket, key, query):
        version_id = query.get("versionId", [""])[0]
        versioned, suspended = self._versioning(bucket)
        # WORM: deleting a concrete version (or unversioned data) is
        # subject to retention/legal hold; writing a delete marker on a
        # versioned bucket is always allowed (bucket-object-lock.go:83)
        if version_id or not (versioned or suspended):
            self._enforce_worm(bucket, key, version_id)
        hdrs: dict = {}
        try:
            info = self.s3.object_layer.delete_object(
                bucket, key, version_id,
                versioned=versioned, version_suspended=suspended,
            )
            if info.delete_marker:
                hdrs["x-amz-delete-marker"] = "true"
            if info.version_id:
                hdrs["x-amz-version-id"] = info.version_id
            from ..event.event import EventName

            self._notify(
                EventName.OBJECT_REMOVED_DELETE_MARKER
                if info.delete_marker
                else EventName.OBJECT_REMOVED_DELETE,
                bucket, key, version_id=info.version_id,
            )
        except Exception as e:  # noqa: BLE001
            err = s3errors.from_exception(e)
            # deleting what is already gone is success (idempotent, and
            # consistent with the multi-delete path)
            if err.code not in ("NoSuchKey", "NoSuchVersion"):
                raise
        self._respond(204, b"", hdrs)

    # -- multipart --------------------------------------------------------

    def _initiate_multipart(self, bucket, key):
        # lock defaults/headers + tagging apply to multipart uploads
        # too (checkPutObjectLockAllowed in NewMultipartUploadHandler)
        meta = self._collect_user_metadata()
        meta.update(self._put_lock_and_tag_meta(bucket, key))
        if self.s3.replication.should_replicate(bucket, key):
            from ..replication.replicate import META_REPLICATION_STATUS

            meta[META_REPLICATION_STATUS] = "PENDING"
        sse = self._request_sse(bucket)
        uid = self.s3.object_layer.new_multipart_upload(
            bucket, key, meta, sse
        )
        hdrs = {}
        if sse is not None:
            from ..codec import sse as ssemod

            hdrs = (
                {
                    "x-amz-server-side-encryption-customer-algorithm":
                        "AES256",
                    "x-amz-server-side-encryption-customer-key-MD5":
                        ssemod.key_md5_b64(sse.key),
                }
                if sse.mode == "C"
                else {"x-amz-server-side-encryption": "AES256"}
            )
        self._respond(
            200, xmlr.initiate_multipart_xml(bucket, key, uid), hdrs
        )

    def _put_part(self, bucket, key, query):
        if "x-amz-copy-source" in self.headers:
            return self._upload_part_copy(bucket, key, query)
        uid = query["uploadId"][0]
        try:
            pnum = int(query["partNumber"][0])
        except ValueError:
            raise S3Error("InvalidArgument", "partNumber") from None
        reader, size = self._open_body()
        if size > MAX_OBJECT_SIZE:
            raise S3Error("EntityTooLarge")
        from ..objectlayer import quota as quotamod

        quotamod.enforce_put(self.s3, bucket, size)
        hreader = self._hash_reader(reader, size)
        # SSE-C uploads must present the key on every part
        # (PutObjectPartHandler re-derives the seal per part)
        part_sse = self._parse_ssec_headers(
            "x-amz-server-side-encryption-customer"
        )
        pi = self.s3.object_layer.put_object_part(
            bucket, key, uid, pnum, hreader, size, part_sse
        )
        self._respond(200, b"", {"ETag": f'"{pi.etag}"'})

    def _upload_part_copy(self, bucket, key, query):
        """UploadPartCopy (CopyObjectPartHandler,
        object-handlers.go:795): stream a source object (or byte
        range of it) in as one part - decrypt with the copy-source
        key, re-encrypt under the upload's regime."""
        from ..utils.hashreader import HashReader
        from ..utils.pipe import streaming_copy

        uid = query["uploadId"][0]
        try:
            pnum = int(query["partNumber"][0])
        except (KeyError, ValueError):
            raise S3Error("InvalidArgument", "partNumber") from None
        src_bucket, src_key = self._parse_copy_source()
        ol = self.s3.object_layer
        src_info, sse_src = self._copy_source_info_and_sse(
            src_bucket, src_key
        )
        part_sse = self._parse_ssec_headers(
            "x-amz-server-side-encryption-customer"
        )
        offset, length = 0, -1
        rng = self.headers.get("x-amz-copy-source-range")
        if rng:
            # strict "bytes=a-b" (ErrInvalidCopyPartRange): open-ended
            # and suffix forms are NOT valid here, unlike GET ranges
            m = re.fullmatch(r"bytes=(\d+)-(\d+)", rng.strip())
            if not m:
                raise S3Error(
                    "InvalidArgument",
                    "The x-amz-copy-source-range value must be of the "
                    "form bytes=first-last where first and last are "
                    "the zero-based offsets of the first and last "
                    "bytes to copy",
                )
            lo, hi = int(m.group(1)), int(m.group(2))
            if lo > hi or hi >= src_info.size:
                raise S3Error(
                    "InvalidArgument",
                    f"Range specified is not valid for source object "
                    f"of size: {src_info.size}",
                )
            offset, length = lo, hi - lo + 1
        size = length if length >= 0 else src_info.size
        if size > MAX_OBJECT_SIZE:
            raise S3Error("EntityTooLarge")
        from ..objectlayer import quota as quotamod

        quotamod.enforce_put(self.s3, bucket, size)
        pi = streaming_copy(
            lambda sink: ol.get_object(
                src_bucket, src_key, sink, offset, length, "", sse_src
            ),
            lambda source: ol.put_object_part(
                bucket, key, uid, pnum,
                HashReader(source, size), size, part_sse,
            ),
        )
        self._respond(
            200, xmlr.copy_part_xml(pi.etag, pi.mod_time_ns)
        )

    def _complete_multipart(self, bucket, key, query, body):
        uid = query["uploadId"][0]
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML") from None
        ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
        parts = []
        for pe in root.findall(f"{ns}Part"):
            parts.append(
                CompletePart(
                    int(pe.findtext(f"{ns}PartNumber")),
                    (pe.findtext(f"{ns}ETag") or "").strip('"'),
                )
            )
        versioned, _ = self._versioning(bucket)
        info = self.s3.object_layer.complete_multipart_upload(
            bucket, key, uid, parts, versioned=versioned
        )
        if self.s3.replication.should_replicate(bucket, key):
            self.s3.replication.queue(bucket, key, info.version_id)
        from ..event.event import EventName

        self._notify(
            EventName.OBJECT_CREATED_COMPLETE_MULTIPART, bucket, key,
            info.etag, info.size, info.version_id,
        )
        hdrs = (
            {"x-amz-version-id": info.version_id}
            if info.version_id
            else None
        )
        self._respond(
            200,
            xmlr.complete_multipart_xml(
                f"{self.s3.endpoint}/{bucket}/{key}",
                bucket,
                key,
                info.etag,
            ),
            hdrs,
        )

    def _abort_multipart(self, bucket, key, query):
        uid = query["uploadId"][0]
        self.s3.object_layer.abort_multipart_upload(bucket, key, uid)
        self._respond(204)

    def _list_parts(self, bucket, key, query):
        uid = query["uploadId"][0]
        parts = self.s3.object_layer.list_object_parts(bucket, key, uid)
        self._respond(
            200, xmlr.list_parts_xml(bucket, key, uid, parts)
        )

    def _list_uploads(self, bucket, query):
        prefix = query.get("prefix", [""])[0]
        ups = self.s3.object_layer.list_multipart_uploads(bucket, prefix)
        self._respond(200, xmlr.list_uploads_xml(bucket, ups))


def _parse_multipart_form(
    body: bytes, boundary: str
) -> "tuple[dict[str, str], bytes, str]":
    """Parse a multipart/form-data body into (fields, file_bytes, filename).

    Field names are lower-cased; only the "file" part keeps raw bytes.
    """
    delim = b"--" + boundary.encode()
    fields: dict[str, str] = {}
    file_data, file_name = b"", ""
    for part in body.split(delim)[1:]:
        if part in (b"--", b"--\r\n") or part.startswith(b"--"):
            break
        part = part.lstrip(b"\r\n")
        head, sep, data = part.partition(b"\r\n\r\n")
        if not sep:
            raise S3Error("MalformedPOSTRequest", "bad form part")
        data = data[:-2] if data.endswith(b"\r\n") else data
        name, fname, ctype = "", "", ""
        for line in head.split(b"\r\n"):
            hname, _, hval = line.decode("latin-1").partition(":")
            hname = hname.strip().lower()
            hval = hval.strip()
            if hname == "content-disposition":
                for piece in hval.split(";")[1:]:
                    pk, _, pv = piece.strip().partition("=")
                    pv = pv.strip('"')
                    if pk == "name":
                        name = pv
                    elif pk == "filename":
                        fname = pv
            elif hname == "content-type":
                ctype = hval
        if name.lower() == "file":
            file_data, file_name = data, fname
            if ctype and "content-type" not in fields:
                fields["content-type"] = ctype
        elif name:
            fields[name.lower()] = data.decode("utf-8", "replace")
    return fields, file_data, file_name
