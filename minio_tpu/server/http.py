"""The S3 HTTP server: router + handlers (L6/L7 of the layer map).

One threaded stdlib HTTP server hosting the S3 API surface
(cmd/api-router.go routes + cmd/object-handlers.go / bucket-handlers.go
glue).  Requests are authenticated with SigV4 (auth.py), dispatched on
(method, path-shape, query), and translated to ObjectLayer calls; errors
render as S3 XML (s3errors.py / response.py).

The reference funnels every handler through middleware
(maxClients(collectAPIStats(httpTrace(...))), api-router.go:94); here the
equivalent cross-cutting layer lives in _Handler.route(): auth, tracing
hooks, error rendering, request IDs.
"""

from __future__ import annotations

import base64
import datetime
import email.utils
import hashlib
import io
import os
import socket
import threading
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..objectlayer.api import CompletePart, ObjectInfo
from ..utils.hashreader import HashReader
from . import response as xmlr, s3errors
from .auth import AuthError, Credentials, SigV4Verifier
from .s3errors import S3Error

MAX_IN_MEMORY_BODY = 1 << 30  # single-PUT cap; larger objects use multipart


class S3Server:
    """Owns the listener + object layer; one per process (xhttp.NewServer
    analogue, cmd/http/server.go:185)."""

    def __init__(
        self,
        object_layer,
        address: str = "127.0.0.1:9000",
        access_key: str = "minioadmin",
        secret_key: str = "minioadmin",
        region: str = "us-east-1",
        iam=None,
    ):
        self.object_layer = object_layer
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.region = region
        self.iam = iam
        if iam is not None:
            lookup = iam.lookup_secret
        else:
            creds = Credentials(access_key, secret_key)
            lookup = (
                lambda ak: creds.secret_key
                if ak == creds.access_key
                else None
            )
        self.verifier = SigV4Verifier(lookup, region)
        self._httpd: "ThreadingHTTPServer | None" = None
        self._thread: "threading.Thread | None" = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "S3Server":
        server = self

        class Handler(_Handler):
            s3 = server

        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), Handler
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="s3-server", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    s3: S3Server = None  # injected subclass attribute

    # silence default stderr logging
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    # -- plumbing ---------------------------------------------------------

    def _parse(self):
        parsed = urllib.parse.urlsplit(self.path)
        path = urllib.parse.unquote(parsed.path)
        query = urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True
        )
        return path, query

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        if length > MAX_IN_MEMORY_BODY:
            # reject without reading: the unread bytes would desync this
            # keep-alive connection, so force it closed
            self.close_connection = True
            raise S3Error("EntityTooLarge")
        if length:
            body = self.rfile.read(length)
        else:
            body = b""
        self._body_consumed = True
        return body

    def _respond(
        self,
        status: int,
        body: bytes = b"",
        headers: "dict | None" = None,
        content_type: str = "application/xml",
    ):
        self.send_response(status)
        self.send_header("Server", "MinIO-TPU")
        self.send_header(
            "x-amz-request-id", uuid.uuid4().hex[:16].upper()
        )
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        if body or status not in (204, 304):
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
        else:
            self.send_header("Content-Length", "0")
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def _error(self, err: s3errors.APIError, resource: str):
        if err.status == 304:  # Not Modified carries no body
            self._respond(304)
            return
        body = xmlr.error_xml(
            err.code, err.message, resource, uuid.uuid4().hex[:16]
        )
        self._respond(err.status, body)

    # -- entry ------------------------------------------------------------

    def route(self):
        path, query = self._parse()
        self._body_consumed = False
        try:
            body = self._read_body()
            # authenticate (setAuthHandler / checkRequestAuthType)
            self.s3.verifier.verify(
                self.command,
                path,
                query,
                dict(self.headers.items()),
                body,
            )
            self._dispatch(path, query, body)
        except Exception as e:  # noqa: BLE001
            if not self._body_consumed:
                self.close_connection = True
            self._error(s3errors.from_exception(e), path)

    do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = route

    # -- dispatch (api-router.go route table) -----------------------------

    def _dispatch(self, path: str, query, body: bytes):
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        m = self.command
        ol = self.s3.object_layer

        if not bucket:
            if m == "GET":
                return self._list_buckets()
            raise S3Error("MethodNotAllowed")

        if key:
            if m == "GET":
                if "uploadId" in query:
                    return self._list_parts(bucket, key, query)
                return self._get_object(bucket, key, query)
            if m == "HEAD":
                return self._head_object(bucket, key, query)
            if m == "PUT":
                if "partNumber" in query and "uploadId" in query:
                    return self._put_part(bucket, key, query, body)
                if "x-amz-copy-source" in self.headers:
                    return self._copy_object(bucket, key)
                return self._put_object(bucket, key, body)
            if m == "POST":
                if "uploads" in query:
                    return self._initiate_multipart(bucket, key)
                if "uploadId" in query:
                    return self._complete_multipart(
                        bucket, key, query, body
                    )
            if m == "DELETE":
                if "uploadId" in query:
                    return self._abort_multipart(bucket, key, query)
                return self._delete_object(bucket, key, query)
            raise S3Error("MethodNotAllowed")

        # bucket-level
        if m == "GET":
            if "location" in query:
                return self._respond(200, xmlr.location_xml(""))
            if "uploads" in query:
                return self._list_uploads(bucket, query)
            if "versioning" in query:
                return self._respond(
                    200,
                    b'<?xml version="1.0" encoding="UTF-8"?>\n'
                    b'<VersioningConfiguration xmlns="'
                    + xmlr.S3_NS.encode()
                    + b'"/>',
                )
            return self._list_objects(bucket, query)
        if m == "HEAD":
            ol.get_bucket_info(bucket)
            return self._respond(200)
        if m == "PUT":
            ol.make_bucket(bucket)
            return self._respond(200, headers={"Location": f"/{bucket}"})
        if m == "DELETE":
            ol.delete_bucket(bucket)
            return self._respond(204)
        if m == "POST":
            if "delete" in query:
                return self._delete_multiple(bucket, body)
        raise S3Error("MethodNotAllowed")

    # -- service ----------------------------------------------------------

    def _list_buckets(self):
        buckets = self.s3.object_layer.list_buckets()
        self._respond(200, xmlr.list_buckets_xml(buckets))

    # -- bucket ops -------------------------------------------------------

    def _list_objects(self, bucket: str, query):
        q1 = {k: v[0] for k, v in query.items()}
        try:
            max_keys = int(q1.get("max-keys", 1000))
        except ValueError:
            raise S3Error("InvalidArgument", "max-keys") from None
        if max_keys < 0:
            raise S3Error("InvalidArgument", "max-keys negative")
        prefix = q1.get("prefix", "")
        delimiter = q1.get("delimiter", "")
        encode = q1.get("encoding-type", "") == "url"
        if q1.get("list-type") == "2":
            token = q1.get("continuation-token", "")
            start_after = q1.get("start-after", "")
            try:
                marker = (
                    base64.urlsafe_b64decode(token.encode()).decode()
                    if token
                    else start_after
                )
            except Exception:  # noqa: BLE001
                raise S3Error(
                    "InvalidArgument", "continuation-token"
                ) from None
            res = self.s3.object_layer.list_objects(
                bucket, prefix, marker, delimiter, max_keys
            )
            body = xmlr.list_objects_v2_xml(
                bucket, prefix, delimiter, max_keys, start_after,
                token, res, encode,
            )
        else:
            marker = q1.get("marker", "")
            res = self.s3.object_layer.list_objects(
                bucket, prefix, marker, delimiter, max_keys
            )
            body = xmlr.list_objects_v1_xml(
                bucket, prefix, marker, delimiter, max_keys, res, encode
            )
        self._respond(200, body)

    def _delete_multiple(self, bucket: str, body: bytes):
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML") from None
        ns = ""
        if root.tag.startswith("{"):
            ns = root.tag[: root.tag.index("}") + 1]
        quiet = (root.findtext(f"{ns}Quiet") or "").lower() == "true"
        deleted, errs = [], []
        for obj in root.findall(f"{ns}Object"):
            key = obj.findtext(f"{ns}Key") or ""
            try:
                self.s3.object_layer.delete_object(bucket, key)
                if not quiet:
                    deleted.append(key)
            except Exception as e:  # noqa: BLE001
                err = s3errors.from_exception(e)
                if err.code == "NoSuchKey":
                    if not quiet:
                        deleted.append(key)  # S3 treats as success
                else:
                    errs.append((key, err.code, err.message))
        self._respond(200, xmlr.delete_result_xml(deleted, errs))

    # -- object ops -------------------------------------------------------

    def _object_headers(self, info: ObjectInfo) -> dict:
        h = {
            "ETag": f'"{info.etag}"',
            "Last-Modified": email.utils.formatdate(
                info.mod_time, usegmt=True
            ),
            "Accept-Ranges": "bytes",
        }
        if info.content_type:
            h["Content-Type-Override"] = info.content_type
        for k, v in info.user_defined.items():
            if k.startswith("x-amz-meta-"):
                h[k] = v
        if info.version_id:
            h["x-amz-version-id"] = info.version_id
        return h

    def _check_conditions(self, info: ObjectInfo):
        """Conditional header evaluation (object-handlers-common.go)."""
        inm = self.headers.get("If-None-Match")
        im = self.headers.get("If-Match")
        ims = self.headers.get("If-Modified-Since")
        ius = self.headers.get("If-Unmodified-Since")
        etag = f'"{info.etag}"'
        if im and im not in (etag, "*", info.etag):
            raise S3Error("PreconditionFailed")
        if inm and inm in (etag, "*", info.etag):
            raise S3Error("NotModified")
        if ims:
            t = email.utils.parsedate_to_datetime(ims)
            if t and info.mod_time <= t.timestamp():
                raise S3Error("NotModified")
        if ius:
            t = email.utils.parsedate_to_datetime(ius)
            if t and info.mod_time > t.timestamp():
                raise S3Error("PreconditionFailed")

    def _parse_range(self, total: int) -> "tuple[int, int] | None":
        """Parse Range: bytes=a-b (httprange.go)."""
        hdr = self.headers.get("Range")
        if not hdr:
            return None
        if not hdr.startswith("bytes="):
            return None  # ignored per RFC
        spec = hdr[len("bytes=") :]
        if "," in spec:
            raise S3Error("NotImplemented", "multiple ranges")
        lo_s, _, hi_s = spec.partition("-")
        try:
            if lo_s == "":
                # suffix range
                n = int(hi_s)
                if n == 0:
                    raise S3Error("InvalidRange")
                lo = max(0, total - n)
                hi = total - 1
            else:
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else total - 1
        except ValueError:
            raise S3Error("InvalidRange") from None
        if lo > hi or lo >= total:
            raise S3Error("InvalidRange")
        return lo, min(hi, total - 1)

    def _get_object(self, bucket, key, query):
        """Stream the object body straight to the socket: headers go out
        first (size known from metadata), then the erasure decode writes
        block-by-block into wfile - constant memory per request."""
        ol = self.s3.object_layer
        version_id = query.get("versionId", [""])[0]
        info = ol.get_object_info(bucket, key, version_id)
        self._check_conditions(info)
        rng = self._parse_range(info.size)
        headers = self._object_headers(info)
        headers.pop("Content-Type-Override", None)
        ct = info.content_type or "application/octet-stream"
        if rng:
            lo, hi = rng
            status, length = 206, hi - lo + 1
            headers["Content-Range"] = f"bytes {lo}-{hi}/{info.size}"
        else:
            status, length = 200, info.size
            lo = 0
        self.send_response(status)
        self.send_header("Server", "MinIO-TPU")
        self.send_header(
            "x-amz-request-id", uuid.uuid4().hex[:16].upper()
        )
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Type", ct)
        self.send_header("Content-Length", str(length))
        self.end_headers()
        if length == 0:
            return
        try:
            ol.get_object(
                bucket, key, self.wfile, lo, length, version_id
            )
        except Exception:  # noqa: BLE001
            # headers already sent; the only honest signal is a broken
            # connection (the reference behaves the same mid-stream)
            self.close_connection = True
            raise ConnectionError("mid-stream decode failure") from None

    def _head_object(self, bucket, key, query):
        version_id = query.get("versionId", [""])[0]
        info = self.s3.object_layer.get_object_info(
            bucket, key, version_id
        )
        self._check_conditions(info)
        headers = self._object_headers(info)
        headers.pop("Content-Type-Override", None)
        self.send_response(200)
        self.send_header("Server", "MinIO-TPU")
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header(
            "Content-Type",
            info.content_type or "application/octet-stream",
        )
        self.send_header("Content-Length", str(info.size))
        self.end_headers()

    def _collect_user_metadata(self) -> dict:
        meta = {}
        ct = self.headers.get("Content-Type")
        if ct:
            meta["content-type"] = ct
        for k, v in self.headers.items():
            lk = k.lower()
            if lk.startswith("x-amz-meta-"):
                meta[lk] = v
        return meta

    def _put_object(self, bucket, key, body: bytes):
        md5_hdr = self.headers.get("Content-MD5", "")
        md5_hex = ""
        if md5_hdr:
            try:
                md5_hex = base64.b64decode(md5_hdr).hex()
            except Exception:  # noqa: BLE001
                raise S3Error("InvalidDigest") from None
        reader = HashReader(
            io.BytesIO(body), len(body), md5_hex=md5_hex
        )
        info = self.s3.object_layer.put_object(
            bucket, key, reader, len(body), self._collect_user_metadata()
        )
        self._respond(200, b"", {"ETag": f'"{info.etag}"'})

    def _copy_object(self, bucket, key):
        src = urllib.parse.unquote(
            self.headers["x-amz-copy-source"]
        ).lstrip("/")
        if "/" not in src:
            raise S3Error("InvalidArgument", "bad copy source")
        src_bucket, src_key = src.split("/", 1)
        directive = self.headers.get(
            "x-amz-metadata-directive", "COPY"
        )
        meta = (
            self._collect_user_metadata()
            if directive == "REPLACE"
            else None
        )
        info = self.s3.object_layer.copy_object(
            src_bucket, src_key, bucket, key, meta
        )
        self._respond(
            200, xmlr.copy_object_xml(info.etag, info.mod_time_ns)
        )

    def _delete_object(self, bucket, key, query):
        version_id = query.get("versionId", [""])[0]
        try:
            self.s3.object_layer.delete_object(bucket, key, version_id)
        except Exception as e:  # noqa: BLE001
            err = s3errors.from_exception(e)
            if err.code != "NoSuchKey":
                raise
        self._respond(204)

    # -- multipart --------------------------------------------------------

    def _initiate_multipart(self, bucket, key):
        uid = self.s3.object_layer.new_multipart_upload(
            bucket, key, self._collect_user_metadata()
        )
        self._respond(
            200, xmlr.initiate_multipart_xml(bucket, key, uid)
        )

    def _put_part(self, bucket, key, query, body):
        uid = query["uploadId"][0]
        try:
            pnum = int(query["partNumber"][0])
        except ValueError:
            raise S3Error("InvalidArgument", "partNumber") from None
        pi = self.s3.object_layer.put_object_part(
            bucket, key, uid, pnum, io.BytesIO(body), len(body)
        )
        self._respond(200, b"", {"ETag": f'"{pi.etag}"'})

    def _complete_multipart(self, bucket, key, query, body):
        uid = query["uploadId"][0]
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML") from None
        ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
        parts = []
        for pe in root.findall(f"{ns}Part"):
            parts.append(
                CompletePart(
                    int(pe.findtext(f"{ns}PartNumber")),
                    (pe.findtext(f"{ns}ETag") or "").strip('"'),
                )
            )
        info = self.s3.object_layer.complete_multipart_upload(
            bucket, key, uid, parts
        )
        self._respond(
            200,
            xmlr.complete_multipart_xml(
                f"{self.s3.endpoint}/{bucket}/{key}",
                bucket,
                key,
                info.etag,
            ),
        )

    def _abort_multipart(self, bucket, key, query):
        uid = query["uploadId"][0]
        self.s3.object_layer.abort_multipart_upload(bucket, key, uid)
        self._respond(204)

    def _list_parts(self, bucket, key, query):
        uid = query["uploadId"][0]
        parts = self.s3.object_layer.list_object_parts(bucket, key, uid)
        self._respond(
            200, xmlr.list_parts_xml(bucket, key, uid, parts)
        )

    def _list_uploads(self, bucket, query):
        prefix = query.get("prefix", [""])[0]
        ups = self.s3.object_layer.list_multipart_uploads(bucket, prefix)
        self._respond(200, xmlr.list_uploads_xml(bucket, ups))
