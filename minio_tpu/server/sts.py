"""STS API (cmd/sts-handlers.go): AssumeRole on the root path.

POST / with a form body ``Action=AssumeRole&Version=2011-06-15`` signed
with SigV4 by an existing static credential; responds with temp
credentials (access key, secret, session token, expiration).  The other
AssumeRole* variants (WebIdentity/ClientGrants/LDAP) need external
OIDC/LDAP providers; they are rejected with a proper STS error.
"""

from __future__ import annotations

import datetime
import urllib.parse
import xml.sax.saxutils as sx

from ..iam.sys import IAMError, UserNotFound
from .s3errors import S3Error

STS_VERSION = "2011-06-15"
_NS = "https://sts.amazonaws.com/doc/2011-06-15/"


def parse_form(body: bytes) -> "dict[str, str]":
    return {
        k: v[0]
        for k, v in urllib.parse.parse_qs(
            body.decode("utf-8", "replace"), keep_blank_values=True
        ).items()
    }


def handle_sts(handler, form: "dict[str, str]") -> None:
    """Dispatch one STS action for an authenticated caller."""
    action = form.get("Action", "")
    if action in (
        "AssumeRoleWithWebIdentity",
        "AssumeRoleWithClientGrants",
    ):
        return _handle_sts_oidc(handler, form, action)
    if action == "AssumeRoleWithLDAPIdentity":
        raise S3Error(
            "NotImplemented",
            f"{action} requires an external LDAP provider",
        )
    if action != "AssumeRole":
        raise S3Error("InvalidParameterValue", f"unknown Action {action!r}")
    version = form.get("Version", "")
    if version != STS_VERSION:
        raise S3Error(
            "InvalidParameterValue", f"Version must be {STS_VERSION}"
        )
    ctx = handler._auth
    if ctx is None or ctx.anonymous:
        raise S3Error("AccessDenied", "AssumeRole requires signed creds")
    iam = handler.s3.iam
    # the reference refuses AssumeRole for temp creds; root is allowed
    duration = None
    if form.get("DurationSeconds"):
        try:
            duration = int(form["DurationSeconds"])
        except ValueError:
            raise S3Error(
                "InvalidParameterValue", "DurationSeconds"
            ) from None
    try:
        cred = iam.assume_role(
            ctx.access_key,
            duration_s=duration,
            session_policy=form.get("Policy") or None,
        )
    except UserNotFound:
        raise S3Error("STSInvalidClientTokenId") from None
    except IAMError as e:
        raise S3Error("InvalidParameterValue", str(e)) from None
    exp = datetime.datetime.fromtimestamp(
        cred["expiration"], datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")
    body = (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        f'<AssumeRoleResponse xmlns="{_NS}">'
        "<AssumeRoleResult>"
        "<Credentials>"
        f"<AccessKeyId>{sx.escape(cred['access_key'])}</AccessKeyId>"
        f"<SecretAccessKey>{sx.escape(cred['secret'])}</SecretAccessKey>"
        f"<SessionToken>{sx.escape(cred['session_token'])}</SessionToken>"
        f"<Expiration>{exp}</Expiration>"
        "</Credentials>"
        "</AssumeRoleResult>"
        "<ResponseMetadata/>"
        "</AssumeRoleResponse>"
    ).encode()
    handler._respond(200, body)


def _handle_sts_oidc(handler, form: "dict[str, str]", action: str):
    """AssumeRoleWithWebIdentity / AssumeRoleWithClientGrants
    (sts-handlers.go:293-443): validate the provider-issued JWT, read
    the policy claim, mint a parentless temp credential carrying that
    policy.  Unsigned requests are allowed - the token IS the proof."""
    from ..iam import openid
    from ..iam.sys import PolicyNotFound

    if form.get("Version", "") != STS_VERSION:
        raise S3Error(
            "InvalidParameterValue", f"Version must be {STS_VERSION}"
        )
    validator = openid.get_validator()
    if validator is None:
        raise S3Error(
            "NotImplemented",
            f"{action} requires an OpenID provider "
            f"(set {openid.ENV_CONFIG_URL})",
        )
    token_field = (
        "WebIdentityToken"
        if action == "AssumeRoleWithWebIdentity"
        else "Token"
    )
    token = form.get(token_field, "")
    if not token:
        raise S3Error("InvalidParameterValue", f"missing {token_field}")
    try:
        claims = validator.validate(token)
    except openid.OpenIDError as e:
        raise S3Error("AccessDenied", f"invalid token: {e}") from None
    try:
        policy = validator.policy_claim(claims)
    except openid.OpenIDError as e:
        raise S3Error("AccessDenied", str(e)) from None
    # the credential must NEVER outlive the identity token: an
    # explicit DurationSeconds is capped at the token's remaining
    # validity, and a token with less than the minimum left is
    # rejected outright (flooring it up would mint creds that
    # outlive the identity provider's session)
    import time as _time

    from ..iam.sys import STS_MAX_DURATION_S, STS_MIN_DURATION_S

    remaining = None
    if isinstance(claims.get("exp"), (int, float)):
        remaining = int(claims["exp"] - _time.time())
        if remaining < STS_MIN_DURATION_S:
            raise S3Error(
                "AccessDenied",
                "token expires too soon for a temporary credential",
            )
    duration = None
    if form.get("DurationSeconds"):
        try:
            duration = int(form["DurationSeconds"])
        except ValueError:
            raise S3Error(
                "InvalidParameterValue", "DurationSeconds"
            ) from None
        if remaining is not None:
            duration = min(duration, remaining)
    elif remaining is not None:
        duration = min(remaining, STS_MAX_DURATION_S)
    iam = handler.s3.iam
    try:
        cred = iam.assume_role_with_token(
            policy, duration_s=duration,
            subject=str(claims.get("sub", "")),
        )
    except PolicyNotFound as e:
        raise S3Error(
            "AccessDenied", f"policy claim names an unknown policy: {e}"
        ) from None
    except IAMError as e:
        raise S3Error("InvalidParameterValue", str(e)) from None
    exp = datetime.datetime.fromtimestamp(
        cred["expiration"], datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")
    result = f"{action}Result"
    subject_el = (
        "<SubjectFromWebIdentityToken>"
        f"{sx.escape(str(claims.get('sub', '')))}"
        "</SubjectFromWebIdentityToken>"
        if action == "AssumeRoleWithWebIdentity"
        else ""
    )
    body = (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        f'<{action}Response xmlns="{_NS}">'
        f"<{result}>"
        f"{subject_el}"
        "<Credentials>"
        f"<AccessKeyId>{sx.escape(cred['access_key'])}</AccessKeyId>"
        f"<SecretAccessKey>{sx.escape(cred['secret'])}</SecretAccessKey>"
        f"<SessionToken>{sx.escape(cred['session_token'])}</SessionToken>"
        f"<Expiration>{exp}</Expiration>"
        "</Credentials>"
        f"</{result}>"
        "<ResponseMetadata/>"
        f"</{action}Response>"
    ).encode()
    handler._respond(200, body)
