"""Web UI backend: the browser's JSON-RPC control plane plus
upload/download endpoints (cmd/web-handlers.go:81, web-router.go).

Wire shape matches the reference's jsonrpc usage::

    POST /minio-tpu/webrpc
    {"id": 1, "jsonrpc": "2.0", "method": "web.ListBuckets",
     "params": {}}

``web.Login`` exchanges credentials for a JWT (signed with the
server's root secret, like the reference's authenticateWeb); every
other method requires it as a Bearer token.  File transfer rides
dedicated endpoints so bodies stream instead of riding JSON:

    PUT /minio-tpu/web/upload/<bucket>/<object>     (Bearer token)
    GET /minio-tpu/web/download/<bucket>/<object>?token=<url token>

The browser frontend itself (static assets) is not bundled - any
S3-browser UI can drive this plane.
"""

from __future__ import annotations

import json
import urllib.parse

from ..utils import jwt
from . import s3errors
from .s3errors import S3Error

RPC_PATH = "/minio-tpu/webrpc"
WEB_PREFIX = "/minio-tpu/web"
TOKEN_EXPIRY_S = 24 * 3600
URL_TOKEN_EXPIRY_S = 3600
UI_VERSION = "minio-tpu-web/1"


class WebError(Exception):
    pass


def _auth_token(h) -> str:
    """Validated access key from the request's Bearer token."""
    authz = h.headers.get("Authorization", "")
    if not authz.startswith("Bearer "):
        raise WebError("authentication required")
    try:
        claims = jwt.verify(
            authz[len("Bearer "):], h.s3.iam.root_secret_key
        )
    except jwt.JWTError as e:
        raise WebError(f"invalid token: {e}") from None
    return claims.get("sub", "")


def _allow(h, access_key: str, action: str, bucket: str,
           key: str = "") -> None:
    """One IAM/policy decision for a web call - the same authorize()
    the S3 plane runs (a read-only user must be read-only here too)."""
    h._query = {}
    if not h._check_action(action, bucket, key, access_key):
        raise WebError("access denied")


# -- RPC methods ------------------------------------------------------------


def _login(h, params) -> dict:
    import hmac as hmac_mod

    username = params.get("username", "")
    password = params.get("password", "")
    secret = h.s3.iam.lookup_secret(username)
    if secret is None or not hmac_mod.compare_digest(
        secret, password
    ):
        raise WebError("invalid credentials")
    if h.s3.iam.is_temp_credential(username):
        # a 24h web JWT must not outlive a short-lived STS credential
        raise WebError(
            "temporary credentials cannot log into the web console"
        )
    token = jwt.sign(
        {"sub": username}, h.s3.iam.root_secret_key, expiry_s=TOKEN_EXPIRY_S
    )
    return {"token": token, "uiVersion": UI_VERSION}


def _server_info(h, params, access_key) -> dict:
    import time

    return {
        "MinioVersion": UI_VERSION,
        "MinioMemory": "",
        "MinioPlatform": "",
        "MinioRuntime": "python",
        "MinioGlobalInfo": {
            "isDistErasure": h.s3.peer_notifier is not None,
            "serverTime_ns": time.time_ns(),
        },
        "MinioUserInfo": {"isIAMUser": False},
    }


def _storage_info(h, params, access_key) -> dict:
    return h.s3.object_layer.storage_info()


def _list_buckets(h, params, access_key) -> dict:
    out = []
    for b in h.s3.object_layer.list_buckets():
        if b.name.startswith("."):
            continue
        # per-bucket visibility, like the reference's web ListBuckets
        # (readable buckets only)
        try:
            _allow(h, access_key, "s3:ListBucket", b.name)
        except WebError:
            continue
        out.append(
            {"name": b.name, "creationDate_ns": b.created_ns}
        )
    return {"buckets": out}


def _make_bucket(h, params, access_key) -> dict:
    bucket = params.get("bucketName", "")
    _allow(h, access_key, "s3:CreateBucket", bucket)
    # the shared path keeps web creates federation-unique
    h._bucket_create(bucket)
    return {}


def _delete_bucket(h, params, access_key) -> dict:
    bucket = params.get("bucketName", "")
    _allow(h, access_key, "s3:DeleteBucket", bucket)
    # the shared path unregisters DNS + drops config/event rules
    h._bucket_delete(bucket)
    return {}


def _list_objects(h, params, access_key) -> dict:
    _allow(h, access_key, "s3:ListBucket", params.get("bucketName", ""))
    res = h.s3.object_layer.list_objects(
        params.get("bucketName", ""),
        params.get("prefix", ""),
        params.get("marker", ""),
        "/",
        int(params.get("maxKeys", 1000)),
    )
    return {
        "objects": [
            {
                "name": o.name,
                "size": o.size,
                "lastModified_ns": o.mod_time_ns,
                "contentType": o.content_type,
                "etag": o.etag,
            }
            for o in res.objects
        ]
        + [{"name": p, "size": 0, "isDir": True} for p in res.prefixes],
        "isTruncated": res.is_truncated,
        "nextMarker": res.next_marker,
    }


def _remove_objects(h, params, access_key) -> dict:
    bucket = params.get("bucketName", "")
    removed, errors = [], []
    versioned, suspended = h._versioning(bucket)
    from ..event.event import EventName

    _set_event_principal(h, access_key)
    for name in params.get("objects", []):
        try:
            _allow(h, access_key, "s3:DeleteObject", bucket, name)
            dinfo = h.s3.object_layer.delete_object(
                bucket, name,
                versioned=versioned, version_suspended=suspended,
            )
            removed.append(name)
            # versioned buckets write a delete marker, a distinct
            # event with the marker's version id (http _delete_object)
            h._notify(
                EventName.OBJECT_REMOVED_DELETE_MARKER
                if dinfo.delete_marker
                else EventName.OBJECT_REMOVED_DELETE,
                bucket, name, version_id=dinfo.version_id,
            )
        except Exception as e:  # noqa: BLE001
            errors.append({"object": name, "error": str(e)})
    return {"removed": removed, "errors": errors}


def _presigned_get(h, params, access_key) -> dict:
    from .auth import presign_url

    bucket = params.get("bucketName", "")
    obj = params.get("objectName", "")
    expiry = min(int(params.get("expiry", 3600)), 7 * 24 * 3600)
    _allow(h, access_key, "s3:GetObject", bucket, obj)
    secret = h.s3.iam.lookup_secret(access_key)
    if secret is None:
        raise WebError("credentials no longer valid")
    url = presign_url(
        "GET",
        f"{h.s3.endpoint}/{bucket}/{urllib.parse.quote(obj)}",
        access_key,
        secret,
        expires=expiry,
        region=h.s3.region,
    )
    return {"url": url}


def _create_url_token(h, params, access_key) -> dict:
    return {
        "token": jwt.sign(
            {"sub": access_key, "web-url-token": True},
            h.s3.iam.root_secret_key,
            expiry_s=URL_TOKEN_EXPIRY_S,
        )
    }


def _get_bucket_policy(h, params, access_key) -> dict:
    bucket = params.get("bucketName", "")
    _allow(h, access_key, "s3:GetBucketPolicy", bucket)
    h.s3.object_layer.get_bucket_info(bucket)
    return {
        "policy": h.s3.bucket_meta.get(bucket).policy_json or ""
    }


def _set_bucket_policy(h, params, access_key) -> dict:
    from ..iam.policy import Policy, PolicyError

    bucket = params.get("bucketName", "")
    _allow(h, access_key, "s3:PutBucketPolicy", bucket)
    h.s3.object_layer.get_bucket_info(bucket)
    raw = params.get("policy", "")
    if raw:
        try:
            Policy.from_json(raw)
        except (PolicyError, ValueError) as e:
            raise WebError(f"bad policy: {e}") from None
    h.s3.bucket_meta.update(bucket, policy_json=raw)
    return {}


def _generate_auth(h, params, access_key) -> dict:
    """Fresh random credential pair for the console's 'generate'
    button (web-handlers.go:823 GenerateAuth); owner only, nothing is
    persisted until SetAuth/add-user applies it."""
    if not h.s3.iam.is_owner(access_key):
        raise WebError("only the owner can generate credentials")
    from ..iam.sys import generate_credentials

    ak, sk = generate_credentials()
    return {"accessKey": ak, "secretKey": sk}


def _set_auth(h, params, access_key) -> dict:
    """Change the calling IAM user's OWN secret key after proving the
    current one (web-handlers.go:850 SetAuth); the owner's root
    credential cannot be changed through the browser."""
    import hmac as hmac_mod

    if h.s3.iam.is_owner(access_key):
        raise WebError(
            "owner credentials cannot be changed via the console"
        )
    current = params.get("currentSecretKey", "")
    new = params.get("newSecretKey", "")
    secret = h.s3.iam.lookup_secret(access_key)
    if secret is None or not hmac_mod.compare_digest(
        secret, current
    ):
        raise WebError("current secret key does not match")
    if len(new) < 8:
        raise WebError("new secret key must be at least 8 characters")
    h.s3.iam.set_user_secret(access_key, new)
    return {}


def _list_all_bucket_policies(h, params, access_key) -> dict:
    """Per-prefix canned access summary of the bucket policy
    (web-handlers.go:1721 ListAllBucketPolicies): for each resource
    prefix the policy names, report readonly/writeonly/readwrite as
    the anonymous GET/PUT decisions the engine would actually make."""
    from ..iam.policy import Args, Policy

    bucket = params.get("bucketName", "")
    _allow(h, access_key, "s3:GetBucketPolicy", bucket)
    h.s3.object_layer.get_bucket_info(bucket)
    raw = h.s3.bucket_meta.get(bucket).policy_json or ""
    if not raw:
        return {"policies": []}
    try:
        pol = Policy.from_json(raw)
    except Exception as e:  # noqa: BLE001
        raise WebError(f"bad stored policy: {e}") from None
    prefixes: "set[str]" = set()
    for st in getattr(pol, "statements", []):
        for res in getattr(st, "resources", []):
            tail = res.split(":::", 1)[-1]
            if tail.startswith(bucket):
                rest = tail[len(bucket):].lstrip("/")
                prefixes.add(rest.rstrip("*"))
    out = []
    for prefix in sorted(prefixes):
        probe = prefix + "obj"
        can_read = pol.is_allowed(
            Args(
                account="", action="s3:GetObject",
                bucket=bucket, object=probe,
            )
        )
        can_write = pol.is_allowed(
            Args(
                account="", action="s3:PutObject",
                bucket=bucket, object=probe,
            )
        )
        level = {
            (True, True): "readwrite",
            (True, False): "readonly",
            (False, True): "writeonly",
            (False, False): "none",
        }[(can_read, can_write)]
        out.append(
            {"bucket": bucket, "prefix": prefix, "policy": level}
        )
    return {"policies": out}


_METHODS = {
    "web.ServerInfo": _server_info,
    "web.StorageInfo": _storage_info,
    "web.ListBuckets": _list_buckets,
    "web.MakeBucket": _make_bucket,
    "web.DeleteBucket": _delete_bucket,
    "web.ListObjects": _list_objects,
    "web.RemoveObject": _remove_objects,
    "web.GetBucketPolicy": _get_bucket_policy,
    "web.SetBucketPolicy": _set_bucket_policy,
    "web.ListAllBucketPolicies": _list_all_bucket_policies,
    "web.PresignedGet": _presigned_get,
    "web.CreateURLToken": _create_url_token,
    "web.GenerateAuth": _generate_auth,
    "web.SetAuth": _set_auth,
}


def _rpc(h) -> None:
    try:
        doc = json.loads(h._read_body() or b"{}")
    except ValueError:
        return _rpc_error(h, None, "parse error")
    rid = doc.get("id")
    method = doc.get("method", "")
    params = doc.get("params") or {}
    try:
        if method == "web.Login":
            return _rpc_result(h, rid, _login(h, params))
        access_key = _auth_token(h)
        if h.s3.object_layer is None:
            raise WebError("server initializing")
        fn = _METHODS.get(method)
        if fn is not None:
            return _rpc_result(h, rid, fn(h, params, access_key))
        return _rpc_error(h, rid, f"unknown method {method!r}")
    except WebError as e:
        return _rpc_error(h, rid, str(e))
    except Exception as e:  # noqa: BLE001
        err = s3errors.from_exception(e)
        return _rpc_error(h, rid, f"{err.code}: {err.message}")


def _rpc_result(h, rid, result) -> None:
    h._respond(
        200,
        json.dumps(
            {"jsonrpc": "2.0", "id": rid, "result": result}
        ).encode(),
        content_type="application/json",
    )


def _set_event_principal(h, access_key: str) -> None:
    """Bearer-token web requests never run sigv4 verification, so
    h._auth stays None and events would carry an empty principal;
    stamp the authenticated web identity before notifying."""
    from .auth import AuthContext

    h._auth = AuthContext(access_key=access_key, kind="web-jwt")


def _rpc_error(h, rid, message: str) -> None:
    h._respond(
        200,  # jsonrpc transports errors in-band
        json.dumps(
            {
                "jsonrpc": "2.0",
                "id": rid,
                "error": {"message": message},
            }
        ).encode(),
        content_type="application/json",
    )


# -- upload / download ------------------------------------------------------


def _upload(h, bucket: str, obj: str) -> None:
    access_key = _auth_token(h)  # bearer-authenticated like WebUpload
    try:
        _allow(h, access_key, "s3:PutObject", bucket, obj)
    except WebError:
        raise S3Error("AccessDenied") from None
    reader, size = h._open_body()
    if size < 0:
        raise S3Error("MissingContentLength")
    from ..utils.hashreader import HashReader

    # the S3 PUT invariant chain (size cap, quota, lock defaults,
    # bucket-default SSE, replication, event) rides the shared
    # helper so web uploads can never drift from it (ADVICE r4)
    _set_event_principal(h, access_key)
    versioned, _ = h._versioning(bucket)
    info = h._checked_put(
        bucket,
        obj,
        HashReader(reader, size),
        size,
        {
            "content-type": h.headers.get("Content-Type")
            or "application/octet-stream"
        },
        versioned=versioned,
    )
    h._respond(200, b"", {"ETag": f'"{info.etag}"'})


def _verify_url_token(h, query) -> dict:
    """Shared URL-token check for download/zip endpoints: a login
    token is NOT a download token (web-handlers.go URL token)."""
    token = query.get("token", [""])[0]
    try:
        claims = jwt.verify(token, h.s3.iam.root_secret_key)
    except jwt.JWTError as e:
        raise S3Error("AccessDenied", f"bad token: {e}") from None
    if not claims.get("web-url-token"):
        raise S3Error("AccessDenied", "not a download token")
    return claims


def _download(h, bucket: str, obj: str, query) -> None:
    claims = _verify_url_token(h, query)
    try:
        _allow(h, claims.get("sub", ""), "s3:GetObject", bucket, obj)
    except WebError:
        raise S3Error("AccessDenied") from None
    info = h.s3.object_layer.get_object_info(bucket, obj)
    from ..codec import sse as ssemod

    if (info.user_defined or {}).get(ssemod.META_SSE) == "C":
        # a web download cannot supply the customer key; failing
        # before end_headers() beats a truncated 200 (ADVICE r4)
        raise S3Error(
            "InvalidRequest",
            "The object was stored using a form of Server Side "
            "Encryption. The correct parameters must be provided "
            "to retrieve the object.",
        )
    h.send_response(200)
    h.send_header("Server", "MinIO-TPU")
    h.send_header("Content-Type", "application/octet-stream")
    # control chars and quotes stripped: a crafted object name must
    # not split the response into injected headers
    fname = "".join(
        c
        for c in obj.rsplit("/", 1)[-1]
        if c.isprintable() and c not in '"\\'
    ) or "download"
    h.send_header(
        "Content-Disposition", f'attachment; filename="{fname}"'
    )
    h.send_header("Content-Length", str(info.size))
    h.end_headers()
    h._headers_sent = True
    h._last_status = 200
    if info.size:
        h.s3.object_layer.get_object(bucket, obj, h.wfile)
        h._resp_bytes += info.size


def _download_zip(h, query) -> None:
    """DownloadZip (web-handlers.go:1290): POST a JSON document
    ``{"bucketName": b, "prefix": p, "objects": [...]}`` with a URL
    token; objects ending in '/' expand recursively.  The archive is
    streamed - zipfile writes straight into the chunked response, so
    memory stays bounded per object block."""
    import zipfile

    claims = _verify_url_token(h, query)
    try:
        args = json.loads(h._read_body() or b"{}")
    except ValueError:
        raise S3Error("InvalidRequest", "bad JSON body") from None
    bucket = args.get("bucketName", "")
    prefix = args.get("prefix", "")
    objects = args.get("objects") or []
    if not bucket or not objects:
        raise S3Error("InvalidRequest", "bucketName and objects required")
    account = claims.get("sub", "")
    ol = h.s3.object_layer
    from ..codec import sse as ssemod

    # expand prefixes + permission-check every entry BEFORE headers
    names: "list[str]" = []
    for obj in objects:
        full = prefix + obj
        if full.endswith("/") or full == "":
            marker = ""
            while True:
                res = ol.list_objects(
                    bucket, full, marker, "", 1000
                )
                names.extend(o.name for o in res.objects)
                if not res.is_truncated:
                    break
                marker = res.next_marker
        else:
            names.append(full)
    for name in names:
        try:
            _allow(h, account, "s3:GetObject", bucket, name)
        except WebError:
            raise S3Error("AccessDenied") from None
        info = ol.get_object_info(bucket, name)
        if (info.user_defined or {}).get(ssemod.META_SSE) == "C":
            raise S3Error(
                "InvalidRequest",
                "zip download cannot read SSE-C objects",
            )
    h.send_response(200)
    h.send_header("Server", "MinIO-TPU")
    h.send_header("Content-Type", "application/zip")
    h.send_header(
        "Content-Disposition", 'attachment; filename="download.zip"'
    )
    h.send_header("Transfer-Encoding", "chunked")
    h.end_headers()
    h._headers_sent = True
    h._last_status = 200

    class _Chunked:
        """Chunked-transfer writer (length unknown up front)."""

        def write(self, b: bytes) -> int:
            if b:
                h.wfile.write(f"{len(b):x}\r\n".encode())
                h.wfile.write(b)
                h.wfile.write(b"\r\n")
                h._resp_bytes += len(b)
            return len(b)

        def flush(self):
            h.wfile.flush()

    out = _Chunked()
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as zf:
        for name in names:
            # archive paths are relative to the requested prefix
            arcname = name[len(prefix):] if name.startswith(
                prefix
            ) else name
            zi = zipfile.ZipInfo(arcname or name)
            # ZipFile's compression arg does NOT apply to handed-in
            # ZipInfo objects (they default to STORED)
            zi.compress_type = zipfile.ZIP_DEFLATED
            with zf.open(zi, "w", force_zip64=True) as entry:
                ol.get_object(bucket, name, entry)
    h.wfile.write(b"0\r\n\r\n")
    h.wfile.flush()


CONSOLE_PATH = "/minio-tpu/console"


def handle(h, path: str, query) -> None:
    """Entry from the router for RPC_PATH / WEB_PREFIX paths."""
    if path == CONSOLE_PATH:
        # the embedded browser frontend (static, unauthenticated -
        # every action it performs authenticates via web.Login)
        if h.command != "GET":
            raise S3Error("MethodNotAllowed")
        from .console_ui import CONSOLE_HTML

        return h._respond(
            200, CONSOLE_HTML, content_type="text/html; charset=utf-8"
        )
    if path == RPC_PATH:
        if h.command != "POST":
            raise S3Error("MethodNotAllowed")
        return _rpc(h)
    tail = path[len(WEB_PREFIX) + 1 :]
    parts = tail.split("/", 2)
    if len(parts) == 3 and parts[0] == "upload" and h.command == "PUT":
        return _upload(
            h, parts[1], urllib.parse.unquote(parts[2])
        )
    if len(parts) == 3 and parts[0] == "download" and h.command == "GET":
        return _download(
            h, parts[1], urllib.parse.unquote(parts[2]), query
        )
    if parts[0] == "zip" and h.command == "POST":
        return _download_zip(h, query)
    raise S3Error("MethodNotAllowed")
