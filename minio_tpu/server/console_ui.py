"""Embedded browser console (the reference ships a React bundle via
cmd/web-router.go + assets; here one self-contained page, no build
step, driving the same web JSON-RPC plane).

Served at GET /minio-tpu/console.  Pure static text - no templating,
no user input interpolation server-side.
"""

CONSOLE_HTML = b"""<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>minio-tpu console</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
  :root { --fg: #1a1f29; --mut: #69707d; --line: #e3e6ea;
          --acc: #0a6fb8; --bad: #b02a37; --bg: #f7f8fa; }
  * { box-sizing: border-box; }
  body { margin: 0; font: 14px/1.45 system-ui, sans-serif;
         color: var(--fg); background: var(--bg); }
  header { background: #fff; border-bottom: 1px solid var(--line);
           padding: 10px 20px; display: flex; align-items: center;
           justify-content: space-between; }
  header h1 { font-size: 16px; margin: 0; }
  main { max-width: 960px; margin: 24px auto; padding: 0 16px; }
  .card { background: #fff; border: 1px solid var(--line);
          border-radius: 6px; padding: 16px; margin-bottom: 16px; }
  table { width: 100%; border-collapse: collapse; }
  th, td { text-align: left; padding: 6px 8px;
           border-bottom: 1px solid var(--line); }
  th { color: var(--mut); font-weight: 600; font-size: 12px;
       text-transform: uppercase; }
  a { color: var(--acc); text-decoration: none; cursor: pointer; }
  button { border: 1px solid var(--line); background: #fff;
           border-radius: 4px; padding: 5px 10px; cursor: pointer; }
  button.primary { background: var(--acc); color: #fff;
                   border-color: var(--acc); }
  button.danger { color: var(--bad); }
  input { border: 1px solid var(--line); border-radius: 4px;
          padding: 6px 8px; }
  #err { color: var(--bad); min-height: 1.2em; margin: 8px 0; }
  .row { display: flex; gap: 8px; align-items: center;
         flex-wrap: wrap; }
  .crumb { margin: 0 0 10px; color: var(--mut); }
  .hidden { display: none; }
</style>
</head>
<body>
<header>
  <h1>minio-tpu console</h1>
  <div id="who" class="row"></div>
</header>
<main>
  <div id="err"></div>
  <div id="login" class="card">
    <h3>Sign in</h3>
    <div class="row">
      <input id="user" placeholder="access key" autocomplete="username">
      <input id="pass" placeholder="secret key" type="password"
             autocomplete="current-password">
      <button class="primary" onclick="login()">Sign in</button>
    </div>
  </div>
  <div id="app" class="hidden">
    <div class="card">
      <div class="row">
        <h3 style="margin:0;flex:1">Buckets</h3>
        <input id="newbucket" placeholder="new bucket name">
        <button class="primary" onclick="makeBucket()">Create</button>
      </div>
      <table><tbody id="buckets"></tbody></table>
    </div>
    <div id="objects-card" class="card hidden">
      <p class="crumb" id="crumb"></p>
      <div class="row" style="margin-bottom:10px">
        <input id="file" type="file">
        <button class="primary" onclick="upload()">Upload</button>
      </div>
      <table>
        <thead><tr><th>Key</th><th>Size</th><th></th></tr></thead>
        <tbody id="objects"></tbody>
      </table>
    </div>
  </div>
</main>
<script>
"use strict";
let token = sessionStorage.getItem("mt-token") || "";
let bucket = "", prefix = "";
const $ = id => document.getElementById(id);
// rows are built with DOM APIs + addEventListener, never by
// interpolating names into HTML/JS strings: object keys are
// user-controlled and must stay inert text
function el(tag, text) {
  const e = document.createElement(tag);
  if (text !== undefined) e.textContent = text;
  return e;
}
function actionLink(label, fn, cls) {
  const b = el(cls === "link" ? "a" : "button", label);
  if (cls && cls !== "link") b.className = cls;
  b.addEventListener("click", fn);
  return b;
}

async function rpc(method, params) {
  const headers = {"Content-Type": "application/json"};
  if (token) headers["Authorization"] = "Bearer " + token;
  const r = await fetch("/minio-tpu/webrpc", {
    method: "POST", headers,
    body: JSON.stringify({id: 1, jsonrpc: "2.0", method,
                          params: params || {}}),
  });
  const doc = await r.json();
  if (doc.error) throw new Error(doc.error.message);
  return doc.result;
}
function fail(e) { $("err").textContent = e.message || String(e); }
function ok() { $("err").textContent = ""; }

async function login() {
  try {
    const res = await rpc("web.Login", {
      username: $("user").value, password: $("pass").value});
    token = res.token;
    sessionStorage.setItem("mt-token", token);
    ok(); show();
  } catch (e) { fail(e); }
}
function logout() {
  token = ""; sessionStorage.removeItem("mt-token");
  location.reload();
}
async function show() {
  $("login").classList.add("hidden");
  $("app").classList.remove("hidden");
  $("who").innerHTML = '<button onclick="logout()">Sign out</button>';
  await listBuckets();
}
async function listBuckets() {
  try {
    const res = await rpc("web.ListBuckets");
    const tbody = $("buckets");
    tbody.replaceChildren();
    if (!res.buckets.length) {
      const tr = el("tr");
      tr.append(el("td", "no buckets"));
      tbody.append(tr);
    }
    for (const b of res.buckets) {
      const tr = el("tr");
      const td1 = el("td");
      td1.append(actionLink(b.name, () => openBucket(b.name), "link"));
      const td2 = el("td");
      td2.style.textAlign = "right";
      td2.append(actionLink("delete", () => dropBucket(b.name),
                            "danger"));
      tr.append(td1, td2);
      tbody.append(tr);
    }
    ok();
  } catch (e) { fail(e); }
}
async function makeBucket() {
  try {
    await rpc("web.MakeBucket", {bucketName: $("newbucket").value});
    $("newbucket").value = ""; await listBuckets();
  } catch (e) { fail(e); }
}
async function dropBucket(name) {
  if (!confirm("Delete bucket " + name + "?")) return;
  try {
    await rpc("web.DeleteBucket", {bucketName: name});
    if (bucket === name) $("objects-card").classList.add("hidden");
    await listBuckets();
  } catch (e) { fail(e); }
}
async function openBucket(name, pfx) {
  bucket = name; prefix = pfx || "";
  try {
    const res = await rpc("web.ListObjects",
                          {bucketName: bucket, prefix});
    $("objects-card").classList.remove("hidden");
    $("crumb").textContent = bucket + "/" + prefix;
    const tbody = $("objects");
    tbody.replaceChildren();
    if (!res.objects.length) {
      const tr = el("tr");
      tr.append(el("td", "empty"));
      tbody.append(tr);
    }
    for (const o of res.objects) {
      const tr = el("tr");
      if (o.isDir) {
        const td = el("td");
        td.append(actionLink(o.name,
          () => openBucket(bucket, o.name), "link"));
        tr.append(td, el("td"), el("td"));
      } else {
        const td3 = el("td");
        td3.style.textAlign = "right";
        td3.append(actionLink("download", () => download(o.name),
                              "link"));
        td3.append(document.createTextNode(" "));
        td3.append(actionLink("delete", () => removeObj(o.name),
                              "danger"));
        tr.append(el("td", o.name), el("td", String(o.size)), td3);
      }
      tbody.append(tr);
    }
    ok();
  } catch (e) { fail(e); }
}
async function removeObj(key) {
  try {
    await rpc("web.RemoveObject",
              {bucketName: bucket, objects: [key]});
    await openBucket(bucket, prefix);
  } catch (e) { fail(e); }
}
async function download(key) {
  try {
    const res = await rpc("web.CreateURLToken");
    location.href = "/minio-tpu/web/download/" + bucket + "/" +
      encodeURIComponent(key).replaceAll("%2F", "/") +
      "?token=" + encodeURIComponent(res.token);
  } catch (e) { fail(e); }
}
async function upload() {
  const f = $("file").files[0];
  if (!f) { fail(new Error("choose a file first")); return; }
  try {
    const encPrefix = prefix.split("/").map(
      encodeURIComponent).join("/");
    const r = await fetch("/minio-tpu/web/upload/" +
        encodeURIComponent(bucket) + "/" +
        encPrefix + encodeURIComponent(f.name), {
      method: "PUT",
      headers: {"Authorization": "Bearer " + token,
                "Content-Type": f.type || "application/octet-stream"},
      body: f,
    });
    if (!r.ok) throw new Error("upload failed: HTTP " + r.status);
    $("file").value = "";
    await openBucket(bucket, prefix);
  } catch (e) { fail(e); }
}
if (token) show();
</script>
</body>
</html>
"""
