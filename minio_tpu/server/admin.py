"""Admin API (cmd/admin-router.go:40-230 + admin-handlers.go subset).

Mounted at ``/minio-tpu/admin/v1`` behind SigV4 auth; only the owner
(root credential) may call it, mirroring the reference's adminAPI
privilege default.  Surfaces: server/storage info, heal triggering,
and IAM management (users, service accounts, canned policies) -
the madmin-facing subset the console and mc rely on.
"""

from __future__ import annotations

import json
import threading
import time

from ..iam.policy import Policy, PolicyError
from ..iam.sys import IAMError, PolicyNotFound, UserNotFound
from .s3errors import S3Error

from ..utils.log import kv, logger

_log = logger("admin")

# guards lazy creation of the per-server heal-sequence registry
_heal_state_lock = threading.Lock()

PREFIX = "/minio-tpu/admin/v1"
VERSION = "0.3.0"
_START = time.time()


class AdminAPI:
    """Routes one admin request; constructed per server."""

    def __init__(self, server):
        self.s3 = server

    # -- dispatch ---------------------------------------------------------

    def handle(
        self, method: str, tail: str, q: "dict[str, str]", body: bytes
    ) -> "tuple[int, bytes]":
        ol = self.s3.object_layer
        if ol is None:
            raise S3Error("ServerNotInitialized")
        route = (method, tail)
        if route == ("GET", "info"):
            return 200, self._info(ol)
        if route == ("GET", "storageinfo"):
            return 200, _json(ol.storage_info())
        if route == ("POST", "heal"):
            return 200, self._heal(ol, q)
        # aggregate MRF/background-heal state, every node
        # (getAggregatedBackgroundHealState, admin-heal-ops.go)
        if route == ("GET", "background-heal/status"):
            doc = {"nodes": [self._bg_heal_local()]}
            peers = getattr(self.s3, "peer_notifier", None)
            if peers is not None:
                doc["nodes"].extend(
                    peers._gather(
                        lambda c: c.call(
                            "bghealstatus", retry=False
                        ),
                        lambda c: {
                            "endpoint": f"{c.host}:{c.port}",
                            "state": "offline",
                        },
                    )
                )
            return 200, _json(doc)
        # service control (ServiceHandler, admin-handlers.go:192):
        # stop/restart THIS node, fanned out to peers first
        if route == ("POST", "service"):
            action = q.get("action", "")
            if action not in ("stop", "restart"):
                raise S3Error(
                    "InvalidArgument",
                    "action must be stop or restart",
                )
            peers = getattr(self.s3, "peer_notifier", None)
            signalled = []
            if peers is not None:
                for c in peers.clients:
                    try:
                        c.call(
                            "signalservice", {"action": action},
                            retry=False,
                        )
                        signalled.append(f"{c.host}:{c.port}")
                    except Exception as exc:
                        _log.debug("peer signal failed", extra=kv(err=str(exc)))
            self._signal_self(action)
            return 200, _json(
                {"action": action, "peers_signalled": signalled}
            )
        # resumable heal sequences with client tokens
        # (admin-heal-ops.go LaunchNewHealSequence/PopHealStatusJSON)
        if route == ("POST", "heal-sequence"):
            return 200, self._heal_sequence(ol, q)
        if route == ("POST", "heal-sequence/stop"):
            state = self._heal_state()
            from ..heal.sequence import HealSequenceError

            try:
                return 200, _json(state.stop(self._heal_path(q)))
            except HealSequenceError as e:
                raise S3Error(e.code, str(e)) from None
        if route == ("GET", "top-locks"):
            return 200, self._top_locks()
        if route == ("GET", "cache-stats"):
            stats_fn = getattr(ol, "cache_stats", None)
            if stats_fn is None:
                return 200, _json({"enabled": False})
            return 200, _json({"enabled": True, **stats_fn()})
        # tiered read cache (cache/tiered.py): device+host tiers of
        # digest-verified encoded groups in front of the quorum reader
        if route == ("GET", "read-cache-stats"):
            from .. import cache as rcache

            return 200, _json(rcache.read_cache_stats())
        if route == ("POST", "read-cache-clear"):
            from .. import cache as rcache

            return 200, _json({"cleared": rcache.clear_read_cache()})
        # codec kernel telemetry dump (codec/telemetry.py): per-op
        # calls/bytes/device-seconds, batcher occupancy, stream totals
        if route == ("GET", "kernel-stats"):
            from ..codec.telemetry import KERNEL_STATS

            return 200, _json(KERNEL_STATS.snapshot())
        # profiling (admin-router.go:82): start on every node, download
        # collects per-node artifacts in one JSON document
        if route == ("POST", "profiling/start"):
            kind = q.get("type", "cpu")
            try:
                self.s3.profiler.start(kind)
            except (ValueError, RuntimeError) as e:
                raise S3Error("InvalidArgument", str(e)) from None
            peers = getattr(self.s3, "peer_notifier", None)
            started = [self.s3.tracer.node]
            if peers is not None:
                for c in peers.clients:
                    try:
                        c.call("startprofiling", {"type": kind})
                        started.append(f"{c.host}:{c.port}")
                    except Exception as exc:
                        _log.debug("peer profiling start failed", extra=kv(err=str(exc)))
            return 200, _json({"started": started, "type": kind})
        if route == ("GET", "profiling/download"):
            import base64

            kind = q.get("type", "cpu")
            profiles: dict = {}
            local_err = ""
            try:
                profiles[self.s3.tracer.node] = base64.b64encode(
                    self.s3.profiler.stop(kind)
                ).decode()
            except RuntimeError as e:
                # still stop the PEERS: bailing here would leave
                # cProfile running on every other node forever
                local_err = str(e)
            peers = getattr(self.s3, "peer_notifier", None)
            if peers is not None:
                for c in peers.clients:
                    try:
                        res = c.call("downloadprofiling", {"type": kind})
                        profiles[f"{c.host}:{c.port}"] = (
                            base64.b64encode(
                                res.get("profile", b"")
                            ).decode()
                        )
                    except Exception:  # noqa: BLE001
                        profiles[f"{c.host}:{c.port}"] = ""
            if local_err and not any(profiles.values()):
                raise S3Error("InvalidArgument", local_err)
            return 200, _json(
                {
                    "type": kind,
                    "profiles": profiles,
                    **({"local_error": local_err} if local_err else {}),
                }
            )
        # KMS key status (admin-handlers.go KMSKeyStatusHandler): a
        # full generate->unseal roundtrip proves the configured KMS
        # can both mint and open data keys for this key id
        if route == ("GET", "kms/key/status"):
            from ..codec import kms as kmsmod

            kms = kmsmod.get_kms()
            if kms is None:
                raise S3Error(
                    "InvalidArgument", "KMS is not configured"
                )
            key_id = q.get("key-id") or kms.default_key_id()
            status = {"key-id": key_id, **kms.info()}
            ctx = {"path": "admin/kms-status-check"}
            try:
                dk, sealed = kms.generate_key(key_id, ctx)
                status["encryption"] = "success"
            except kmsmod.KMSError as e:
                status["encryption"] = f"failed: {e}"
                return 200, _json(status)
            try:
                if kms.unseal_key(key_id, sealed, ctx) == dk:
                    status["decryption"] = "success"
                else:
                    status["decryption"] = "failed: key mismatch"
            except kmsmod.KMSError as e:
                status["decryption"] = f"failed: {e}"
            return 200, _json(status)
        # cluster health diagnostics (admin-handlers.go:1007
        # OBDInfoHandler): system + per-drive microbenchmarks, every
        # node, one JSON document
        if route == ("GET", "healthinfo"):
            doc = {"nodes": [self._health_info_local(ol)]}
            peers = getattr(self.s3, "peer_notifier", None)
            if peers is not None:
                # concurrent gather, no retry: wall time is ONE
                # node's probe, and a dead peer costs one timeout
                doc["nodes"].extend(
                    peers._gather(
                        lambda c: c.call("healthinfo", retry=False),
                        lambda c: {
                            "endpoint": f"{c.host}:{c.port}",
                            "state": "offline",
                        },
                    )
                )
            return 200, _json(doc)
        if route == ("GET", "datausage"):
            crawler = getattr(self.s3, "crawler", None)
            if crawler is None:
                from ..crawler import DataUsage

                return 200, _json(DataUsage().to_dict())
            return 200, _json(crawler.usage().to_dict())
        if route == ("POST", "crawl"):
            crawler = getattr(self.s3, "crawler", None)
            if crawler is None:
                raise S3Error("ServerNotInitialized")
            # an explicit admin crawl bypasses the freshness gate
            return 200, _json(crawler.crawl_once(force=True).to_dict())
        # chaos fault control (cluster harness): schedule FaultDisk
        # rules on THIS node's local drives over the wire, so a test
        # driver can degrade a REMOTE process it does not share memory
        # with.  Only mounted when the server was started with
        # MINIO_TPU_FAULT_INJECTION=1 (fault_disks is absent otherwise).
        if tail in ("fault/inject", "fault/clear", "fault/status"):
            return self._fault(method, tail, body)
        # server-loop observability + chaos wedge (testgrid wedged_loop
        # cell): status is read-only; the wedge rides the same
        # MINIO_TPU_FAULT_INJECTION gate as disk faults
        if tail in ("loops/status", "loops/wedge"):
            return self._loops(method, tail, body)
        # bucket quota (admin SetBucketQuota / GetBucketQuotaConfig)
        if route == ("GET", "get-bucket-quota"):
            ol.get_bucket_info(_req(q, "bucket"))
            raw = self.s3.bucket_meta.get(_req(q, "bucket")).quota_json
            return 200, (raw.encode() if raw else b"{}")
        if route == ("PUT", "set-bucket-quota"):
            from ..objectlayer.quota import QuotaConfig, QuotaError

            bucket = _req(q, "bucket")
            ol.get_bucket_info(bucket)
            if body.strip() in (b"", b"{}"):
                self.s3.bucket_meta.update(bucket, quota_json="")
                return 200, b"{}"
            try:
                cfg = QuotaConfig.from_json(body)
            except QuotaError as e:
                raise S3Error("InvalidArgument", str(e)) from None
            self.s3.bucket_meta.update(
                bucket, quota_json=cfg.to_json()
            )
            return 200, b"{}"
        # replication remote targets (admin SetRemoteTarget)
        if route == ("GET", "list-remote-targets"):
            bucket = _req(q, "bucket")
            ol.get_bucket_info(bucket)
            raw = self.s3.bucket_meta.get(bucket).replication_targets_json
            return 200, (raw.encode() if raw else b"[]")
        if route == ("PUT", "set-remote-target"):
            bucket = _req(q, "bucket")
            ol.get_bucket_info(bucket)
            doc = _body_json(body)
            for field in ("endpoint", "access_key", "secret_key",
                          "target_bucket"):
                if not doc.get(field):
                    raise S3Error(
                        "InvalidArgument", f"missing {field}"
                    )
            raw = self.s3.bucket_meta.get(
                bucket
            ).replication_targets_json
            try:
                docs = json.loads(raw) if raw else []
            except ValueError:
                docs = []
            docs = [
                d
                for d in docs
                if d.get("target_bucket") != doc["target_bucket"]
            ] + [doc]
            self.s3.bucket_meta.update(
                bucket, replication_targets_json=json.dumps(docs)
            )
            return 200, _json(
                {
                    "arn": (
                        "arn:minio:replication:::"
                        + doc["target_bucket"]
                    )
                }
            )
        # runtime KV config (admin-router.go:89 set-config-kv family)
        if route == ("GET", "get-config"):
            return 200, _json(self.s3.config.dump())
        if route == ("GET", "config-help"):
            from ..config import ConfigError

            try:
                return 200, _json(
                    self.s3.config.help(_req(q, "subsys"))
                )
            except ConfigError as e:
                raise S3Error("InvalidArgument", str(e)) from None
        if route == ("PUT", "set-config-kv"):
            from ..config import ConfigError

            try:
                self.s3.config.set_kvs(
                    _req(q, "subsys"),
                    _body_json(body),
                    q.get("target", "_"),
                )
            except ConfigError as e:
                raise S3Error("InvalidArgument", str(e)) from None
            return 200, b"{}"
        if route == ("DELETE", "del-config-kv"):
            from ..config import ConfigError

            try:
                self.s3.config.del_kvs(
                    _req(q, "subsys"), q.get("target", "_")
                )
            except ConfigError as e:
                raise S3Error("InvalidArgument", str(e)) from None
            return 200, b"{}"
        # IAM management
        iam = self.s3.iam
        if route == ("GET", "list-users"):
            return 200, _json(iam.list_users())
        if route == ("PUT", "add-user"):
            doc = _body_json(body)
            iam.add_user(
                _req(q, "accessKey"),
                doc.get("secretKey", ""),
                doc.get("policy", ""),
            )
            return 200, b"{}"
        if route == ("DELETE", "remove-user"):
            iam.remove_user(_req(q, "accessKey"))
            return 200, b"{}"
        if route == ("PUT", "set-user-policy"):
            iam.set_user_policy(_req(q, "accessKey"), q.get("name", ""))
            return 200, b"{}"
        if route == ("PUT", "set-user-status"):
            iam.set_user_status(
                _req(q, "accessKey"), q.get("status") == "enabled"
            )
            return 200, b"{}"
        if route == ("POST", "service-account"):
            ak, sk = iam.add_service_account(_req(q, "parent"))
            return 200, _json({"accessKey": ak, "secretKey": sk})
        # groups (admin-router.go update-group-members / group status)
        if route == ("GET", "groups"):
            return 200, _json(iam.list_groups())
        if route == ("GET", "group"):
            return 200, _json(iam.group_info(_req(q, "group")))
        if route == ("PUT", "update-group-members"):
            doc = _body_json(body)
            members = doc.get("members", [])
            if doc.get("isRemove"):
                iam.remove_group_members(_req(q, "group"), members)
            else:
                iam.add_group_members(_req(q, "group"), members)
            return 200, b"{}"
        if route == ("PUT", "set-group-policy"):
            iam.set_group_policy(_req(q, "group"), q.get("name", ""))
            return 200, b"{}"
        if route == ("PUT", "set-group-status"):
            iam.set_group_status(
                _req(q, "group"), q.get("status") == "enabled"
            )
            return 200, b"{}"
        if route == ("GET", "list-canned-policies"):
            return 200, _json(
                {
                    name: iam.get_policy(name).to_dict()
                    for name in iam.list_policies()
                }
            )
        if route == ("PUT", "add-canned-policy"):
            try:
                pol = Policy.from_json(body)
            except PolicyError as e:
                raise S3Error("MalformedPolicy", str(e)) from None
            iam.set_policy(_req(q, "name"), pol)
            return 200, b"{}"
        if route == ("DELETE", "remove-canned-policy"):
            iam.remove_policy(_req(q, "name"))
            return 200, b"{}"
        raise S3Error("MethodNotAllowed", f"admin {method} /{tail}")

    # -- handlers ---------------------------------------------------------

    def _loops(
        self, method: str, tail: str, body: bytes
    ) -> "tuple[int, bytes]":
        """Server-loop control plane.

        GET  loops/status  per-loop state/connections/inflight/sheds
                           (available in every mode; threaded reports
                           zero loops).
        POST loops/wedge   {loop, seconds} - busy-spin one loop's
                           thread so the chaos grid can prove a wedged
                           loop degrades only its own shard.  Gated on
                           MINIO_TPU_FAULT_INJECTION=1 like disk faults.
        """
        plane = getattr(self.s3, "_plane", None)
        if (method, tail) == ("GET", "loops/status"):
            doc = {
                "mode": getattr(self.s3, "server_mode", "threaded"),
            }
            if plane is not None:
                doc.update(plane.describe())
            else:
                doc.update(count=0, reuseport=False, per_loop=[])
            return 200, _json(doc)
        if (method, tail) != ("POST", "loops/wedge"):
            raise S3Error("MethodNotAllowed", f"admin {method} /{tail}")
        if not getattr(self.s3, "fault_disks", None):
            raise S3Error(
                "InvalidArgument",
                "fault injection disabled: start the server with "
                "MINIO_TPU_FAULT_INJECTION=1",
            )
        if plane is None:
            raise S3Error(
                "InvalidArgument",
                "no async plane to wedge (MINIO_TPU_SERVER=threaded)",
            )
        doc = _body_json(body) if body.strip() else {}
        try:
            index = int(doc.get("loop", -1))
            seconds = float(doc.get("seconds", 0.0))
        except (TypeError, ValueError):
            raise S3Error(
                "InvalidArgument", "loop/seconds must be numeric"
            ) from None
        if seconds <= 0 or seconds > 300:
            raise S3Error(
                "InvalidArgument", "seconds must be in (0, 300]"
            )
        if not plane.wedge_loop(index, seconds):
            raise S3Error(
                "InvalidArgument",
                f"no such loop {index} (have {len(plane.loops)})",
            )
        _log.info(
            "server loop wedged",
            extra=kv(loop=index, seconds=seconds),
        )
        return 200, _json({"wedged": index, "seconds": seconds})

    def _fault(
        self, method: str, tail: str, body: bytes
    ) -> "tuple[int, bytes]":
        """Remote fault control for the cluster harness.

        POST fault/inject  {disk, api, delay_s, hang_s, error, corrupt,
                            prob, calls} - add one schedule rule; "disk"
                            matches a local drive root by suffix ("*"
                            or absent = every local drive).
        POST fault/clear   {disk} - lift rules + release parked hangs.
        GET  fault/status  per-drive rule count + injected-action tally.
        """
        fault_disks = getattr(self.s3, "fault_disks", None)
        if not fault_disks:
            raise S3Error(
                "InvalidArgument",
                "fault injection disabled: start the server with "
                "MINIO_TPU_FAULT_INJECTION=1",
            )
        if (method, tail) == ("GET", "fault/status"):
            return 200, _json(
                {
                    root: {
                        "rules": fd.rule_count(),
                        "injected": fd.injected(),
                    }
                    for root, fd in sorted(fault_disks.items())
                }
            )
        doc = _body_json(body) if body.strip() else {}
        sel = str(doc.get("disk", "*"))
        matched = {
            root: fd
            for root, fd in fault_disks.items()
            if sel in ("", "*") or root.endswith(sel)
        }
        if not matched:
            raise S3Error(
                "InvalidArgument", f"no local drive matches {sel!r}"
            )
        if (method, tail) == ("POST", "fault/clear"):
            for fd in matched.values():
                fd.clear()
            return 200, _json({"cleared": sorted(matched)})
        if (method, tail) != ("POST", "fault/inject"):
            raise S3Error("MethodNotAllowed", f"admin {method} /{tail}")
        api = doc.get("api")
        if not api:
            raise S3Error("InvalidArgument", "missing api")
        calls = doc.get("calls")
        if calls is not None and not isinstance(calls, list):
            raise S3Error("InvalidArgument", "calls must be a list")
        for fd in matched.values():
            fd.inject(
                str(api),
                delay_s=float(doc.get("delay_s", 0.0)),
                hang_s=float(doc.get("hang_s", 0.0)),
                error=bool(doc.get("error", False)),
                corrupt=bool(doc.get("corrupt", False)),
                prob=float(doc.get("prob", 1.0)),
                calls=calls,
            )
        _log.info(
            "fault schedule injected",
            extra=kv(api=str(api), disks=len(matched)),
        )
        # the parked hang is the product here: an injected fault
        # schedule deliberately outlives this request and is released
        # by a later POST fault/clear, never by this frame
        return 200, _json({"injected": sorted(matched)})  # noqa: MTPU601,MTPU603

    def _health_info_local(self, ol) -> dict:
        """This node's OBD document: platform + memory + per-local-
        drive latency/throughput microprobe (the reference's
        getLocalDrivesOBD 4 MiB probe, obdinfo.go)."""
        import os as _os
        import platform

        doc = {
            "endpoint": getattr(self.s3, "endpoint", ""),
            "state": "online",
            "version": VERSION,
            "uptime_seconds": round(time.time() - _START, 1),
            "platform": platform.platform(),
            "python": platform.python_version(),
            "cpus": _os.cpu_count(),
            # request-plane mode + admission/backpressure counters
            # (server/admission.py PlaneStats)
            "server_plane": dict(
                getattr(self.s3, "plane_stats").snapshot(),
                mode=getattr(self.s3, "server_mode", "threaded"),
            )
            if getattr(self.s3, "plane_stats", None) is not None
            else {},
        }
        # multi-loop front plane: shard count, listener strategy, and
        # per-loop state (empty block in threaded mode)
        plane = getattr(self.s3, "_plane", None)
        doc["server_loops"] = (
            plane.describe()
            if plane is not None
            else {"count": 0, "reuseport": False, "per_loop": []}
        )
        # shared admission budget: live per-tenant inflight plus the
        # high-water mark each tenant's token counter ever reached -
        # the out-of-process witness that the GLOBAL cap held exactly
        # across loops (bench --concurrency asserts hwm <= cap here)
        admission = getattr(self.s3, "admission", None)
        if admission is not None:
            doc["admission"] = {
                "tenant_inflight": admission.tenant_inflight(),
                "tenant_hwm": admission.budget.tenant_hwm(),
                "select_inflight": admission.budget.select.value(),
                "select_hwm": admission.budget.select.hwm,
            }
        # tiered read cache: zero-filled when off, so the OBD shape is
        # stable across modes (cache/__init__.py read_cache_stats)
        from .. import cache as rcache

        doc["read_cache"] = rcache.read_cache_stats()
        # S3 Select pushdown: engine mix, fallback reasons, scan I/O
        from ..s3select import device as seldev

        doc["select"] = dict(
            seldev.STATS.snapshot(), mode=seldev.select_mode()
        )
        # device transfer/compute overlap: configured mode plus the
        # windows the codec actually opened and the per-plane bus
        # traffic backing them (codec/telemetry.py)
        from ..codec.telemetry import KERNEL_STATS
        from ..ops import codec_step

        ksnap = KERNEL_STATS.snapshot()
        doc["codec_overlap"] = {
            "mode": codec_step.codec_overlap_mode(),
            "overlap_windows": ksnap["overlap_windows"],
            "h2d": ksnap["h2d"],
            "d2h": ksnap["d2h"],
        }
        try:
            page = _os.sysconf("SC_PAGE_SIZE")
            doc["mem_total_bytes"] = page * _os.sysconf("SC_PHYS_PAGES")
            doc["mem_available_bytes"] = page * _os.sysconf(
                "SC_AVPHYS_PAGES"
            )
        except (ValueError, OSError, AttributeError):
            pass
        from concurrent.futures import ThreadPoolExecutor

        from .metrics import _iter_disks

        probe = b"\0" * (1 << 20)

        def probe_drive(d) -> dict:
            import uuid as _uuid

            # unique path per request (concurrent OBD calls must not
            # race each other's probe files) + guaranteed cleanup
            path = f"tmp/obd-probe-{_uuid.uuid4().hex}"
            entry = {"endpoint": ""}
            try:
                info = d.disk_info()
                entry.update(
                    endpoint=info.endpoint,
                    total=info.total,
                    free=info.free,
                )
                t0 = time.monotonic()
                d.write_all(".sys", path, probe)
                t1 = time.monotonic()
                try:
                    d.read_all(".sys", path)
                    t2 = time.monotonic()
                finally:
                    try:
                        d.delete_file(".sys", path)
                    except Exception as exc:
                        _log.debug("obd probe file cleanup failed", extra=kv(err=str(exc)))
                entry["write_mibps"] = round(1 / max(t1 - t0, 1e-9), 1)
                entry["read_mibps"] = round(1 / max(t2 - t1, 1e-9), 1)
                entry["latency_ms"] = round((t1 - t0) * 1e3, 2)
                entry["state"] = "ok"
            except Exception as e:  # noqa: BLE001
                entry["state"] = f"error: {type(e).__name__}"
            # lifetime per-API ledger when a MeteredDisk is in the
            # wrapper chain (storage/metered.py)
            stats_fn = getattr(d, "api_stats", None)
            if callable(stats_fn):
                try:
                    entry["api_stats"] = stats_fn()
                except Exception as exc:
                    _log.debug("disk api_stats read failed", extra=kv(err=str(exc)))
            # circuit-breaker view (storage/health.py): state machine
            # position, trip/recovery counts, streaming read quantiles
            h = getattr(d, "health", None)
            if h is not None:
                try:
                    entry["health"] = h.snapshot()
                except Exception as exc:
                    _log.debug(
                        "disk health read failed", extra=kv(err=str(exc))
                    )
            return entry

        local = [
            d
            for d in _iter_disks(ol)
            if d is not None
            and getattr(d, "is_local", lambda: False)()
        ]
        # concurrent probes: a many-drive node must answer inside the
        # peer RPC timeout, and wall time is one drive's probe
        if local:
            with ThreadPoolExecutor(
                max_workers=min(8, len(local))
            ) as pool:
                doc["drives"] = list(pool.map(probe_drive, local))
        else:
            doc["drives"] = []
        return doc

    def _info(self, ol) -> bytes:
        si = ol.storage_info()
        disks = []
        from .metrics import _iter_disks

        for d in _iter_disks(ol):
            if d is None:
                disks.append({"state": "offline"})
                continue
            try:
                info = d.disk_info()
                disks.append(
                    {
                        "endpoint": info.endpoint,
                        "state": "ok" if d.is_online() else "offline",
                        "total": info.total,
                        "used": info.used,
                        "free": info.free,
                    }
                )
            except Exception:  # noqa: BLE001
                disks.append({"state": "offline"})
        doc = {
            "version": VERSION,
            "uptime_seconds": round(time.time() - _START, 1),
            "mode": "erasure",
            "storage": si,
            "disks": disks,
        }
        # distributed mode: one entry per peer via the control plane
        # (madmin ServerInfo aggregates every node)
        notifier = getattr(self.s3, "peer_notifier", None)
        if notifier is not None:
            doc["mode"] = "distributed"
            doc["nodes"] = notifier.server_infos()
        return _json(doc)

    def _top_locks(self) -> bytes:
        """Held locks across the cluster (madmin TopLocks): this
        node's local locker plus every peer's via the control plane."""
        locks: list = []
        local = getattr(self.s3, "local_locker", None)
        if local is not None:
            locks.extend(local.dump())
        notifier = getattr(self.s3, "peer_notifier", None)
        if notifier is not None:
            for node_locks in notifier.all_locks():
                locks.extend(node_locks)
        return _json({"locks": locks})

    def _bg_heal_local(self) -> dict:
        routine = getattr(self.s3, "heal_routine", None)
        queue = getattr(self.s3, "heal_queue", None)
        return {
            "endpoint": getattr(self.s3, "endpoint", ""),
            "state": "online",
            "enabled": routine is not None,
            "queued": len(queue) if queue is not None else 0,
            "healed": getattr(routine, "healed", 0),
            "failed": getattr(routine, "failed", 0),
        }

    @staticmethod
    def _signal_self(action: str) -> None:
        """Deliver the service signal to this process AFTER the HTTP
        response flushes (a small delay thread, like the reference's
        deferred serviceSignalCh send)."""
        import os as _os
        import signal as _signal
        import sys as _sys
        import threading as _threading
        import time as _time

        def fire():
            _time.sleep(0.5)
            if action == "stop":
                _os.kill(_os.getpid(), _signal.SIGTERM)
            else:  # restart: re-exec the same argv in place
                try:
                    _os.execv(_sys.executable, [_sys.executable] + _sys.argv)
                except OSError:
                    _os.kill(_os.getpid(), _signal.SIGTERM)

        _threading.Thread(target=fire, daemon=True).start()

    def _heal_state(self):
        from ..heal.sequence import AllHealState

        # double-checked under a module lock: two concurrent launches
        # must share ONE registry or tokens and overlap guards split
        with _heal_state_lock:
            state = getattr(self.s3, "heal_state", None)
            if state is None:
                state = self.s3.heal_state = AllHealState()
        return state

    @staticmethod
    def _heal_path(q: "dict[str, str]") -> str:
        bucket = q.get("bucket", "")
        if not bucket:
            raise S3Error("InvalidArgument", "heal requires bucket")
        prefix = q.get("prefix", "")
        return f"{bucket}/{prefix}".rstrip("/")

    def _heal_sequence(self, ol, q: "dict[str, str]") -> bytes:
        """Launch (no clientToken) or poll (clientToken) a heal
        sequence; maps HealSequenceError onto admin API errors."""
        from ..heal.sequence import (
            AllHealState,  # noqa: F401 (doc aid)
            HealSequence,
            HealSequenceError,
        )

        state = self._heal_state()
        path = self._heal_path(q)
        token = q.get("clientToken", "")
        try:
            if token:
                return _json(state.pop_status(path, token))
            seq = HealSequence(
                ol,
                q.get("bucket", ""),
                q.get("prefix", ""),
                dry_run=q.get("dryRun") == "true",
                client_address=q.get("clientAddress", ""),
            )
            return _json(
                state.launch(seq, q.get("forceStart") == "true")
            )
        except HealSequenceError as e:
            raise S3Error(e.code, str(e)) from None

    def _heal(self, ol, q: "dict[str, str]") -> bytes:
        bucket = q.get("bucket", "")
        obj = q.get("object", "")
        dry = q.get("dryRun") == "true"
        if not bucket:
            raise S3Error("InvalidArgument", "heal requires bucket")
        if obj:
            res = ol.heal_object(
                bucket, obj, q.get("versionId", ""), dry_run=dry
            )
        else:
            res = ol.heal_bucket(bucket, dry_run=dry)
        return _json(res)


def _json(doc) -> bytes:
    return json.dumps(doc).encode()


def _body_json(body: bytes) -> dict:
    try:
        doc = json.loads(body or b"{}")
    except ValueError:
        raise S3Error("InvalidArgument", "malformed JSON body") from None
    if not isinstance(doc, dict):
        raise S3Error("InvalidArgument", "JSON object expected")
    return doc


def _req(q: "dict[str, str]", key: str) -> str:
    v = q.get(key, "")
    if not v:
        raise S3Error("InvalidArgument", f"missing {key}")
    return v


def map_admin_error(e: Exception) -> "S3Error | None":
    from ..iam.sys import GroupNotFound

    if isinstance(e, UserNotFound):
        return S3Error("InvalidArgument", f"no such user: {e}")
    if isinstance(e, PolicyNotFound):
        return S3Error("InvalidArgument", f"no such policy: {e}")
    if isinstance(e, GroupNotFound):
        return S3Error("InvalidArgument", f"no such group: {e}")
    if isinstance(e, IAMError):
        return S3Error("InvalidArgument", str(e))
    return None
