"""AWS Signature V4 verification (cmd/signature-v4.go).

Supports header-based SigV4 (Authorization: AWS4-HMAC-SHA256 ...) and
presigned URLs (X-Amz-Algorithm=AWS4-HMAC-SHA256 query auth,
cmd/signature-v4.go doesPresignedSignatureMatch), with UNSIGNED-PAYLOAD
and signed-payload content hashes.  SigV2 and streaming chunked signatures
are recognized and rejected with a clear error until implemented.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse

SIGN_V4_ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
PRESIGN_MAX_EXPIRES = 7 * 24 * 3600


class AuthError(Exception):
    """Maps to a specific S3 error code."""

    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" if encode_slash else "-._~/"
    return urllib.parse.quote(s, safe=safe)


def _canonical_query(query: "dict[str, list[str]]", skip=("X-Amz-Signature",)) -> str:
    pairs = []
    for k in sorted(query):
        if k in skip:
            continue
        for v in sorted(query[k]):
            pairs.append(f"{_uri_encode(k)}={_uri_encode(v)}")
    return "&".join(pairs)


def _signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = hmac.new(
        ("AWS4" + secret).encode(), date.encode(), hashlib.sha256
    ).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


def _hmac_hex(key: bytes, msg: str) -> str:
    return hmac.new(key, msg.encode(), hashlib.sha256).hexdigest()


def canonical_request(
    method: str,
    path: str,
    query: "dict[str, list[str]]",
    headers: "dict[str, str]",
    signed_headers: list[str],
    payload_hash: str,
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers
    )
    return "\n".join(
        [
            method.upper(),
            _uri_encode(path, encode_slash=False) or "/",
            _canonical_query(query),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(amz_date: str, scope: str, creq: str) -> str:
    return "\n".join(
        [
            SIGN_V4_ALGORITHM,
            amz_date,
            scope,
            hashlib.sha256(creq.encode()).hexdigest(),
        ]
    )


def sign_v4(
    method: str,
    path: str,
    query: "dict[str, list[str]]",
    headers: "dict[str, str]",
    signed_headers: list[str],
    payload_hash: str,
    access_key: str,
    secret_key: str,
    amz_date: str,
    region: str = "us-east-1",
    service: str = "s3",
) -> str:
    """Compute the V4 signature (shared by verifier, clients, presigner)."""
    date = amz_date[:8]
    scope = f"{date}/{region}/{service}/aws4_request"
    creq = canonical_request(
        method, path, query, headers, signed_headers, payload_hash
    )
    sts = string_to_sign(amz_date, scope, creq)
    key = _signing_key(secret_key, date, region, service)
    return _hmac_hex(key, sts)


class Credentials:
    def __init__(self, access_key: str, secret_key: str):
        self.access_key = access_key
        self.secret_key = secret_key


class SigV4Verifier:
    """Verifies incoming requests against a credential lookup."""

    def __init__(self, lookup, region: str = "us-east-1", clock=None):
        """lookup(access_key) -> secret_key or None."""
        self._lookup = lookup
        self.region = region
        self._clock = clock or (
            lambda: datetime.datetime.now(datetime.timezone.utc)
        )

    # -- entry point -----------------------------------------------------

    def verify(
        self,
        method: str,
        path: str,
        query: "dict[str, list[str]]",
        headers: "dict[str, str]",
        payload: bytes = b"",
    ) -> str:
        """Returns the authenticated access key; raises AuthError."""
        headers = {k.lower(): v for k, v in headers.items()}
        auth = headers.get("authorization", "")
        if auth.startswith(SIGN_V4_ALGORITHM):
            return self._verify_header(method, path, query, headers, payload)
        if "X-Amz-Algorithm" in query:
            return self._verify_presigned(method, path, query, headers)
        if auth.startswith("AWS "):
            raise AuthError(
                "SignatureVersionNotSupported", "SigV2 not supported"
            )
        raise AuthError("AccessDenied", "no credentials provided")

    # -- header auth -----------------------------------------------------

    def _verify_header(self, method, path, query, headers, payload) -> str:
        auth = headers["authorization"]
        try:
            rest = auth[len(SIGN_V4_ALGORITHM):].strip()
            fields = dict(
                kv.strip().split("=", 1) for kv in rest.split(",")
            )
            credential = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            got_sig = fields["Signature"]
            access_key, date, region, service, term = (
                credential.split("/", 4)
            )
        except (KeyError, ValueError):
            raise AuthError(
                "AuthorizationHeaderMalformed", auth
            ) from None
        if term != "aws4_request" or service != "s3":
            raise AuthError("AuthorizationHeaderMalformed", credential)
        if region != self.region:
            raise AuthError(
                "AuthorizationHeaderMalformed",
                f"bad region {region}, expecting {self.region}",
            )
        secret = self._lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", access_key)
        amz_date = headers.get("x-amz-date", "")
        if not amz_date:
            # SigV4 permits signing with the RFC1123 Date header; the
            # string-to-sign timestamp is still ISO-basic
            rfc_date = headers.get("date", "")
            if not rfc_date:
                raise AuthError("AccessDenied", "missing date")
            import email.utils

            try:
                t = email.utils.parsedate_to_datetime(rfc_date)
            except (TypeError, ValueError):
                raise AuthError("MalformedDate", rfc_date) from None
            if t is None:
                raise AuthError("MalformedDate", rfc_date)
            amz_date = t.astimezone(datetime.timezone.utc).strftime(
                "%Y%m%dT%H%M%SZ"
            )
        self._check_skew(amz_date)
        payload_hash = headers.get("x-amz-content-sha256", "")
        if payload_hash.startswith("STREAMING-"):
            raise AuthError(
                "NotImplemented", "streaming signatures not supported yet"
            )
        if not payload_hash:
            payload_hash = hashlib.sha256(payload).hexdigest()
        elif payload_hash != UNSIGNED_PAYLOAD:
            actual = hashlib.sha256(payload).hexdigest()
            if actual != payload_hash:
                raise AuthError(
                    "XAmzContentSHA256Mismatch", "payload hash mismatch"
                )
        want = sign_v4(
            method, path, query, headers, signed_headers, payload_hash,
            access_key, secret, amz_date, region,
        )
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch", "")
        return access_key

    # -- presigned auth --------------------------------------------------

    def _verify_presigned(self, method, path, query, headers) -> str:
        q1 = {k: v[0] for k, v in query.items()}
        if q1.get("X-Amz-Algorithm") != SIGN_V4_ALGORITHM:
            raise AuthError("InvalidRequest", "bad algorithm")
        try:
            credential = q1["X-Amz-Credential"]
            amz_date = q1["X-Amz-Date"]
            expires = int(q1["X-Amz-Expires"])
            signed_headers = q1["X-Amz-SignedHeaders"].split(";")
            got_sig = q1["X-Amz-Signature"]
            access_key, date, region, service, term = (
                credential.split("/", 4)
            )
        except (KeyError, ValueError):
            raise AuthError(
                "AuthorizationQueryParametersError", ""
            ) from None
        if not (0 < expires <= PRESIGN_MAX_EXPIRES):
            raise AuthError(
                "AuthorizationQueryParametersError", "bad expires"
            )
        secret = self._lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", access_key)
        # expiry check
        try:
            t0 = datetime.datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            raise AuthError("MalformedDate", amz_date) from None
        now = self._clock()
        if now < t0 - datetime.timedelta(minutes=15):
            raise AuthError("RequestNotReadyYet", "")
        if now > t0 + datetime.timedelta(seconds=expires):
            raise AuthError("ExpiredToken", "presigned URL expired")
        payload_hash = q1.get("X-Amz-Content-Sha256", UNSIGNED_PAYLOAD)
        want = sign_v4(
            method, path, query, headers, signed_headers, payload_hash,
            access_key, secret, amz_date, region,
        )
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch", "")
        return access_key

    def _check_skew(self, amz_date: str) -> None:
        try:
            t = datetime.datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            raise AuthError("MalformedDate", amz_date) from None
        skew = abs((self._clock() - t).total_seconds())
        if skew > 15 * 60:
            raise AuthError(
                "RequestTimeTooSkewed", f"skew {int(skew)}s"
            )


def presign_url(
    method: str,
    url: str,
    access_key: str,
    secret_key: str,
    expires: int = 3600,
    region: str = "us-east-1",
    amz_date: "str | None" = None,
) -> str:
    """Generate a presigned URL (client-side helper, web handlers)."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    if amz_date is None:
        amz_date = datetime.datetime.now(
            datetime.timezone.utc
        ).strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
    query.update(
        {
            "X-Amz-Algorithm": [SIGN_V4_ALGORITHM],
            "X-Amz-Credential": [
                f"{access_key}/{date}/{region}/s3/aws4_request"
            ],
            "X-Amz-Date": [amz_date],
            "X-Amz-Expires": [str(expires)],
            "X-Amz-SignedHeaders": ["host"],
        }
    )
    sig = sign_v4(
        method, parsed.path or "/", query, {"host": host}, ["host"],
        UNSIGNED_PAYLOAD, access_key, secret_key, amz_date, region,
    )
    query["X-Amz-Signature"] = [sig]
    qs = urllib.parse.urlencode(query, doseq=True, quote_via=urllib.parse.quote)
    return urllib.parse.urlunsplit(
        (parsed.scheme, parsed.netloc, parsed.path, qs, "")
    )
