"""AWS signature verification (cmd/signature-v4.go, signature-v2.go,
streaming-signature-v4.go, postpolicyform.go).

Supports:
* header SigV4 + presigned SigV4, with UNSIGNED-PAYLOAD / signed payloads
* streaming SigV4 ("aws-chunked" with per-chunk signatures) and the
  unsigned-trailer streaming variant, via SigV4ChunkedReader
* header SigV2 + presigned SigV2 (legacy HMAC-SHA1)
* POST form policy signatures (browser uploads)

Verification is two-phase so the server never buffers bodies for auth:
``verify_stream`` checks the signature against the *declared* payload
hash and returns an AuthContext describing how the body must be read
(chunk-signature framing and/or content-sha256 to verify at EOF).
"""

from __future__ import annotations

import base64
import dataclasses
import datetime
import hashlib
import hmac
import json
import urllib.parse

SIGN_V4_ALGORITHM = "AWS4-HMAC-SHA256"
SIGN_V2_ALGORITHM = "AWS"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_PAYLOAD_TRAILER = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER"
STREAMING_UNSIGNED_TRAILER = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
PRESIGN_MAX_EXPIRES = 7 * 24 * 3600


class AuthError(Exception):
    """Maps to a specific S3 error code."""

    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" if encode_slash else "-._~/"
    return urllib.parse.quote(s, safe=safe)


def _canonical_query(query: "dict[str, list[str]]", skip=("X-Amz-Signature",)) -> str:
    pairs = []
    for k in sorted(query):
        if k in skip:
            continue
        for v in sorted(query[k]):
            pairs.append(f"{_uri_encode(k)}={_uri_encode(v)}")
    return "&".join(pairs)


def _signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = hmac.new(
        ("AWS4" + secret).encode(), date.encode(), hashlib.sha256
    ).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


def _hmac_hex(key: bytes, msg: str) -> str:
    return hmac.new(key, msg.encode(), hashlib.sha256).hexdigest()


def canonical_request(
    method: str,
    path: str,
    query: "dict[str, list[str]]",
    headers: "dict[str, str]",
    signed_headers: list[str],
    payload_hash: str,
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers
    )
    return "\n".join(
        [
            method.upper(),
            _uri_encode(path, encode_slash=False) or "/",
            _canonical_query(query),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(amz_date: str, scope: str, creq: str) -> str:
    return "\n".join(
        [
            SIGN_V4_ALGORITHM,
            amz_date,
            scope,
            hashlib.sha256(creq.encode()).hexdigest(),
        ]
    )


def sign_v4(
    method: str,
    path: str,
    query: "dict[str, list[str]]",
    headers: "dict[str, str]",
    signed_headers: list[str],
    payload_hash: str,
    access_key: str,
    secret_key: str,
    amz_date: str,
    region: str = "us-east-1",
    service: str = "s3",
) -> str:
    """Compute the V4 signature (shared by verifier, clients, presigner)."""
    date = amz_date[:8]
    scope = f"{date}/{region}/{service}/aws4_request"
    creq = canonical_request(
        method, path, query, headers, signed_headers, payload_hash
    )
    sts = string_to_sign(amz_date, scope, creq)
    key = _signing_key(secret_key, date, region, service)
    return _hmac_hex(key, sts)


class Credentials:
    def __init__(self, access_key: str, secret_key: str):
        self.access_key = access_key
        self.secret_key = secret_key


@dataclasses.dataclass
class AuthContext:
    """How a request authenticated + how its body must be consumed.

    The auth-type classification the reference makes in
    getRequestAuthType (cmd/auth-handler.go:101), carried forward so
    handlers can wire the right body reader without re-parsing headers.
    """

    access_key: str = ""
    kind: str = "anonymous"  # v4 | v4-presigned | v2 | v2-presigned | anonymous
    content_sha256: "str | None" = None  # hex digest to verify at EOF
    streaming: bool = False  # body uses aws-chunked framing
    signed_chunks: bool = False  # each chunk carries a V4 signature
    trailer: bool = False  # trailing checksum headers after last chunk
    trailer_header: str = ""  # declared x-amz-trailer checksum name
    seed_signature: str = ""
    signing_key: bytes = b""
    amz_date: str = ""
    scope: str = ""

    @property
    def anonymous(self) -> bool:
        return self.kind == "anonymous"


class SigV4Verifier:
    """Verifies incoming requests against a credential lookup."""

    def __init__(self, lookup, region: str = "us-east-1", clock=None):
        """lookup(access_key) -> secret_key or None."""
        self._lookup = lookup
        self.region = region
        self._clock = clock or (
            lambda: datetime.datetime.now(datetime.timezone.utc)
        )

    # -- entry points ----------------------------------------------------

    def verify_stream(
        self,
        method: str,
        path: str,
        query: "dict[str, list[str]]",
        headers: "dict[str, str]",
    ) -> AuthContext:
        """Body-free verification: check the signature against the
        *declared* payload hash and describe how to read the body.

        Anonymous requests return an anonymous context (policy decides
        downstream); bad signatures raise AuthError.
        """
        headers = {k.lower(): v for k, v in headers.items()}
        auth = headers.get("authorization", "")
        if auth.startswith(SIGN_V4_ALGORITHM):
            return self._verify_header(method, path, query, headers)
        if "X-Amz-Algorithm" in query:
            return self._verify_presigned(method, path, query, headers)
        if auth.startswith(SIGN_V2_ALGORITHM + " "):
            return self._verify_v2_header(method, path, query, headers)
        if "Signature" in query and "AWSAccessKeyId" in query:
            return self._verify_v2_presigned(method, path, query, headers)
        return AuthContext()

    def verify_post_policy(self, form: "dict[str, str]") -> str:
        """POST form-upload verification against this verifier's
        credential store; returns the access key."""
        return verify_post_policy(
            form, self._lookup, self.region, self._clock
        )

    def verify(
        self,
        method: str,
        path: str,
        query: "dict[str, list[str]]",
        headers: "dict[str, str]",
        payload: bytes = b"",
    ) -> str:
        """Buffered-body compatibility wrapper: verify signature AND
        payload hash in one call.  Returns the access key."""
        headers = {k.lower(): v for k, v in headers.items()}
        if (
            headers.get("authorization", "").startswith(SIGN_V4_ALGORITHM)
            and "x-amz-content-sha256" not in headers
        ):
            # old-style clients sign the actual body hash without sending
            # the header; reconstruct it (possible here: we have the body)
            headers = dict(headers)
            headers["x-amz-content-sha256"] = hashlib.sha256(
                payload
            ).hexdigest()
        ctx = self.verify_stream(method, path, query, headers)
        if ctx.anonymous:
            raise AuthError("AccessDenied", "no credentials provided")
        if ctx.streaming:
            raise AuthError(
                "InvalidRequest", "streaming body in buffered verify"
            )
        if ctx.content_sha256 is not None:
            actual = hashlib.sha256(payload).hexdigest()
            if actual != ctx.content_sha256:
                raise AuthError(
                    "XAmzContentSHA256Mismatch", "payload hash mismatch"
                )
        return ctx.access_key

    # -- header auth -----------------------------------------------------

    def _verify_header(self, method, path, query, headers) -> AuthContext:
        auth = headers["authorization"]
        try:
            rest = auth[len(SIGN_V4_ALGORITHM):].strip()
            fields = dict(
                kv.strip().split("=", 1) for kv in rest.split(",")
            )
            credential = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            got_sig = fields["Signature"]
            access_key, date, region, service, term = (
                credential.split("/", 4)
            )
        except (KeyError, ValueError):
            raise AuthError(
                "AuthorizationHeaderMalformed", auth
            ) from None
        if term != "aws4_request" or service != "s3":
            raise AuthError("AuthorizationHeaderMalformed", credential)
        if region != self.region:
            raise AuthError(
                "AuthorizationHeaderMalformed",
                f"bad region {region}, expecting {self.region}",
            )
        secret = self._lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", access_key)
        amz_date = headers.get("x-amz-date", "")
        if not amz_date:
            # SigV4 permits signing with the RFC1123 Date header; the
            # string-to-sign timestamp is still ISO-basic
            rfc_date = headers.get("date", "")
            if not rfc_date:
                raise AuthError("AccessDenied", "missing date")
            import email.utils

            try:
                t = email.utils.parsedate_to_datetime(rfc_date)
            except (TypeError, ValueError):
                raise AuthError("MalformedDate", rfc_date) from None
            if t is None:
                raise AuthError("MalformedDate", rfc_date)
            amz_date = t.astimezone(datetime.timezone.utc).strftime(
                "%Y%m%dT%H%M%SZ"
            )
        self._check_skew(amz_date)
        payload_hash = headers.get("x-amz-content-sha256", "")
        if not payload_hash:
            raise AuthError(
                "InvalidRequest", "missing x-amz-content-sha256"
            )
        ctx = AuthContext(access_key=access_key, kind="v4")
        if payload_hash in (STREAMING_PAYLOAD, STREAMING_PAYLOAD_TRAILER):
            ctx.streaming = True
            ctx.signed_chunks = True
            ctx.trailer = payload_hash == STREAMING_PAYLOAD_TRAILER
        elif payload_hash == STREAMING_UNSIGNED_TRAILER:
            ctx.streaming = True
            ctx.trailer = True
        elif payload_hash != UNSIGNED_PAYLOAD:
            ctx.content_sha256 = payload_hash.lower()
        if ctx.trailer:
            ctx.trailer_header = headers.get("x-amz-trailer", "").strip().lower()
        want = sign_v4(
            method, path, query, headers, signed_headers, payload_hash,
            access_key, secret, amz_date, region,
        )
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch", "")
        ctx.seed_signature = got_sig
        ctx.signing_key = _signing_key(secret, amz_date[:8], region, "s3")
        ctx.amz_date = amz_date
        ctx.scope = f"{amz_date[:8]}/{region}/s3/aws4_request"
        return ctx

    # -- presigned auth --------------------------------------------------

    def _verify_presigned(self, method, path, query, headers) -> AuthContext:
        q1 = {k: v[0] for k, v in query.items()}
        if q1.get("X-Amz-Algorithm") != SIGN_V4_ALGORITHM:
            raise AuthError("InvalidRequest", "bad algorithm")
        try:
            credential = q1["X-Amz-Credential"]
            amz_date = q1["X-Amz-Date"]
            expires = int(q1["X-Amz-Expires"])
            signed_headers = q1["X-Amz-SignedHeaders"].split(";")
            got_sig = q1["X-Amz-Signature"]
            access_key, date, region, service, term = (
                credential.split("/", 4)
            )
        except (KeyError, ValueError):
            raise AuthError(
                "AuthorizationQueryParametersError", ""
            ) from None
        if not (0 < expires <= PRESIGN_MAX_EXPIRES):
            raise AuthError(
                "AuthorizationQueryParametersError", "bad expires"
            )
        secret = self._lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", access_key)
        # expiry check
        try:
            t0 = datetime.datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            raise AuthError("MalformedDate", amz_date) from None
        now = self._clock()
        if now < t0 - datetime.timedelta(minutes=15):
            raise AuthError("RequestNotReadyYet", "")
        if now > t0 + datetime.timedelta(seconds=expires):
            raise AuthError("ExpiredToken", "presigned URL expired")
        payload_hash = q1.get("X-Amz-Content-Sha256", UNSIGNED_PAYLOAD)
        want = sign_v4(
            method, path, query, headers, signed_headers, payload_hash,
            access_key, secret, amz_date, region,
        )
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch", "")
        ctx = AuthContext(access_key=access_key, kind="v4-presigned")
        if payload_hash not in (UNSIGNED_PAYLOAD, ""):
            ctx.content_sha256 = payload_hash.lower()
        return ctx

    # -- SigV2 (cmd/signature-v2.go) -------------------------------------

    def _v2_secret(self, access_key: str) -> str:
        secret = self._lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", access_key)
        return secret

    def _verify_v2_header(self, method, path, query, headers) -> AuthContext:
        auth = headers["authorization"]
        try:
            access_key, got_sig = auth[len(SIGN_V2_ALGORITHM) + 1 :].split(
                ":", 1
            )
        except ValueError:
            raise AuthError("AuthorizationHeaderMalformed", auth) from None
        secret = self._v2_secret(access_key)
        # Date slot is empty when x-amz-date is present (it is then part of
        # the canonical amz headers), mirroring signature-v2.go
        date_str = (
            "" if "x-amz-date" in headers else headers.get("date", "")
        )
        sts = _string_to_sign_v2(method, path, query, headers, date_str)
        want = base64.b64encode(
            hmac.new(secret.encode(), sts.encode(), hashlib.sha1).digest()
        ).decode()
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch", "")
        return AuthContext(access_key=access_key, kind="v2")

    def _verify_v2_presigned(self, method, path, query, headers) -> AuthContext:
        q1 = {k: v[0] for k, v in query.items()}
        access_key = q1["AWSAccessKeyId"]
        got_sig = q1["Signature"]
        expires = q1.get("Expires", "")
        secret = self._v2_secret(access_key)
        try:
            exp_t = int(expires)
        except ValueError:
            raise AuthError(
                "AuthorizationQueryParametersError", "bad Expires"
            ) from None
        if self._clock().timestamp() > exp_t:
            raise AuthError("ExpiredToken", "presigned URL expired")
        sts = _string_to_sign_v2(method, path, query, headers, expires)
        want = base64.b64encode(
            hmac.new(secret.encode(), sts.encode(), hashlib.sha1).digest()
        ).decode()
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch", "")
        return AuthContext(access_key=access_key, kind="v2-presigned")

    def _check_skew(self, amz_date: str) -> None:
        try:
            t = datetime.datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            raise AuthError("MalformedDate", amz_date) from None
        skew = abs((self._clock() - t).total_seconds())
        if skew > 15 * 60:
            raise AuthError(
                "RequestTimeTooSkewed", f"skew {int(skew)}s"
            )


def presign_url(
    method: str,
    url: str,
    access_key: str,
    secret_key: str,
    expires: int = 3600,
    region: str = "us-east-1",
    amz_date: "str | None" = None,
) -> str:
    """Generate a presigned URL (client-side helper, web handlers)."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    if amz_date is None:
        amz_date = datetime.datetime.now(
            datetime.timezone.utc
        ).strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
    query.update(
        {
            "X-Amz-Algorithm": [SIGN_V4_ALGORITHM],
            "X-Amz-Credential": [
                f"{access_key}/{date}/{region}/s3/aws4_request"
            ],
            "X-Amz-Date": [amz_date],
            "X-Amz-Expires": [str(expires)],
            "X-Amz-SignedHeaders": ["host"],
        }
    )
    sig = sign_v4(
        method, parsed.path or "/", query, {"host": host}, ["host"],
        UNSIGNED_PAYLOAD, access_key, secret_key, amz_date, region,
    )
    query["X-Amz-Signature"] = [sig]
    qs = urllib.parse.urlencode(query, doseq=True, quote_via=urllib.parse.quote)
    return urllib.parse.urlunsplit(
        (parsed.scheme, parsed.netloc, parsed.path, qs, "")
    )


# ---------------------------------------------------------------------------
# SigV2 canonicalization (cmd/signature-v2.go resourceList + stringToSign)
# ---------------------------------------------------------------------------

V2_SUBRESOURCES = frozenset(
    {
        "acl", "delete", "lifecycle", "location", "logging",
        "notification", "partNumber", "policy", "requestPayment",
        "response-cache-control", "response-content-disposition",
        "response-content-encoding", "response-content-language",
        "response-content-type", "response-expires", "torrent",
        "uploadId", "uploads", "versionId", "versioning", "versions",
        "website",
    }
)


def _string_to_sign_v2(method, path, query, headers, date_str: str) -> str:
    amz: "dict[str, list[str]]" = {}
    for k, v in headers.items():
        lk = k.lower()
        if lk.startswith("x-amz-"):
            amz.setdefault(lk, []).append(" ".join(v.split()))
    canon_amz = "".join(
        f"{k}:{','.join(amz[k])}\n" for k in sorted(amz)
    )
    sub = []
    for k in sorted(query):
        if k not in V2_SUBRESOURCES:
            continue
        vals = query[k]
        if vals and vals[0]:
            sub.append(f"{k}={vals[0]}")
        else:
            sub.append(k)
    resource = path + (f"?{'&'.join(sub)}" if sub else "")
    return (
        f"{method.upper()}\n"
        f"{headers.get('content-md5', '')}\n"
        f"{headers.get('content-type', '')}\n"
        f"{date_str}\n"
        f"{canon_amz}{resource}"
    )


def sign_v2(
    method, path, query, headers, secret_key: str, date_str: str
) -> str:
    """Compute the V2 signature (test-client helper)."""
    sts = _string_to_sign_v2(method, path, query, headers, date_str)
    return base64.b64encode(
        hmac.new(secret_key.encode(), sts.encode(), hashlib.sha1).digest()
    ).decode()


# ---------------------------------------------------------------------------
# Streaming SigV4 chunked reader (cmd/streaming-signature-v4.go)
# ---------------------------------------------------------------------------


def _crc32c_table() -> list[int]:
    poly = 0x82F63B78  # Castagnoli, reflected
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC32C_TABLE: "list[int] | None" = None


class _Crc32c:
    """Software CRC32C (no stdlib impl).  Table-driven Python - slow on
    big bodies, but only runs when a client declares this trailer."""

    def __init__(self):
        global _CRC32C_TABLE
        if _CRC32C_TABLE is None:
            _CRC32C_TABLE = _crc32c_table()
        self._crc = 0xFFFFFFFF

    def update(self, data: bytes) -> None:
        crc, table = self._crc, _CRC32C_TABLE
        for b in data:
            crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
        self._crc = crc

    def digest(self) -> bytes:
        return (self._crc ^ 0xFFFFFFFF).to_bytes(4, "big")


class _Crc32:
    def __init__(self):
        import zlib

        self._z = zlib
        self._crc = 0

    def update(self, data: bytes) -> None:
        self._crc = self._z.crc32(data, self._crc)

    def digest(self) -> bytes:
        return self._crc.to_bytes(4, "big")


class _HashlibChecksum:
    def __init__(self, name: str):
        self._h = hashlib.new(name)

    def update(self, data: bytes) -> None:
        self._h.update(data)

    def digest(self) -> bytes:
        return self._h.digest()


def _new_trailer_checksum(header: str):
    """Incremental checksum for a declared x-amz-checksum-* trailer, or
    None when the algorithm is unknown (forward compatibility)."""
    algo = header.rpartition("-")[2]
    if algo == "crc32":
        return _Crc32()
    if algo == "crc32c":
        return _Crc32c()
    if algo in ("sha1", "sha256"):
        return _HashlibChecksum(algo)
    return None


class SigV4ChunkedReader:
    """Decode an aws-chunked body, verifying each chunk's V4 signature.

    Framing: ``<hex-size>[;chunk-signature=<sig>]\\r\\n<data>\\r\\n`` ...
    terminated by a zero-size chunk, optionally followed by trailing
    headers (x-amz-checksum-*) and a trailer signature.  The per-chunk
    string-to-sign chains the previous signature exactly as
    newSignV4ChunkedReader does.
    """

    MAX_LINE = 4096  # maxLineLength, streaming-signature-v4.go
    MAX_CHUNK = 16 << 20  # sanity cap on a single declared chunk

    def __init__(self, raw, ctx: AuthContext, decoded_length: int = -1):
        self._raw = raw
        self._ctx = ctx
        self._prev = ctx.seed_signature
        self._buf = bytearray()
        self._chunk = b""
        self._off = 0
        self._done = False
        self.decoded_length = decoded_length
        self.trailers: "dict[str, str]" = {}
        self._cksum = (
            _new_trailer_checksum(ctx.trailer_header)
            if ctx.trailer and ctx.trailer_header
            else None
        )

    # internal buffered reads over the raw (already length-limited) stream

    def _fill(self, n: int) -> None:
        while len(self._buf) < n:
            chunk = self._raw.read(65536)
            if not chunk:
                raise AuthError("IncompleteBody", "truncated chunked body")
            self._buf.extend(chunk)

    def _read_exact(self, n: int) -> bytes:
        self._fill(n)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def _read_line(self) -> bytes:
        while True:
            idx = self._buf.find(b"\r\n")
            if idx >= 0:
                line = bytes(self._buf[:idx])
                del self._buf[: idx + 2]
                return line
            if len(self._buf) > self.MAX_LINE:
                # a chunk header/trailer line this long is an attack,
                # not a client (bounded-memory guarantee)
                raise AuthError("IncompleteBody", "chunk header too long")
            chunk = self._raw.read(65536)
            if not chunk:
                # final trailer lines may end without CRLF
                line = bytes(self._buf)
                del self._buf[:]
                return line
            self._buf.extend(chunk)

    def _verify_chunk(self, data: bytes) -> None:
        sts = "\n".join(
            [
                "AWS4-HMAC-SHA256-PAYLOAD",
                self._ctx.amz_date,
                self._ctx.scope,
                self._prev,
                EMPTY_SHA256,
                hashlib.sha256(data).hexdigest(),
            ]
        )
        want = _hmac_hex(self._ctx.signing_key, sts)
        if not hmac.compare_digest(want, self._sig):
            raise AuthError("SignatureDoesNotMatch", "chunk signature")
        self._prev = want

    def _next_chunk(self) -> None:
        line = self._read_line().decode("latin-1")
        size_s, _, ext = line.partition(";")
        try:
            size = int(size_s.strip(), 16)
        except ValueError:
            raise AuthError(
                "IncompleteBody", f"bad chunk header {line!r}"
            ) from None
        if size > self.MAX_CHUNK:
            raise AuthError("IncompleteBody", "chunk too large")
        self._sig = ""
        if ext.startswith("chunk-signature="):
            self._sig = ext[len("chunk-signature=") :].strip()
        if self._ctx.signed_chunks and not self._sig:
            raise AuthError("SignatureDoesNotMatch", "missing chunk sig")
        if size == 0:
            if self._ctx.signed_chunks:
                self._verify_chunk(b"")
            self._read_trailers()
            self._done = True
            return
        data = self._read_exact(size)
        crlf = self._read_exact(2)
        if crlf != b"\r\n":
            raise AuthError("IncompleteBody", "missing chunk CRLF")
        if self._ctx.signed_chunks:
            self._verify_chunk(data)
        if self._cksum is not None:
            self._cksum.update(data)
        self._chunk = data
        self._off = 0

    def _read_trailers(self) -> None:
        if not self._ctx.trailer:
            # consume the final CRLF if present
            if self._buf[:2] == b"\r\n":
                del self._buf[:2]
            return
        trailer_canon = []
        saw_trailer_sig = False
        while True:
            line = self._read_line()
            if not line:
                break
            name, _, value = line.decode("latin-1").partition(":")
            name = name.strip().lower()
            value = value.strip()
            if name == "x-amz-trailer-signature":
                saw_trailer_sig = True
                if self._ctx.signed_chunks:
                    sts = "\n".join(
                        [
                            "AWS4-HMAC-SHA256-TRAILER",
                            self._ctx.amz_date,
                            self._ctx.scope,
                            self._prev,
                            hashlib.sha256(
                                ("".join(trailer_canon)).encode()
                            ).hexdigest(),
                        ]
                    )
                    want = _hmac_hex(self._ctx.signing_key, sts)
                    if not hmac.compare_digest(want, value):
                        raise AuthError(
                            "SignatureDoesNotMatch", "trailer signature"
                        )
                break
            if name:
                self.trailers[name] = value
                trailer_canon.append(f"{name}:{value}\n")
        if self._ctx.signed_chunks and not saw_trailer_sig:
            raise AuthError(
                "SignatureDoesNotMatch", "missing trailer signature"
            )

    def read(self, n: int = -1) -> bytes:
        out = bytearray()
        while n < 0 or len(out) < n:
            if self._off < len(self._chunk):
                take = len(self._chunk) - self._off
                if n >= 0:
                    take = min(take, n - len(out))
                out += self._chunk[self._off : self._off + take]
                self._off += take
                continue
            if self._done:
                break
            self._next_chunk()
        return bytes(out)

    def finalize(self) -> None:
        """Drive the terminal 0-chunk + trailer frames to completion.

        Callers stop read()ing once the declared decoded length arrives,
        which would leave the final chunk signature, trailer signature
        and trailing checksums unparsed (advisor finding r2) - this
        consumes and verifies them.  Extra data past the declared length
        is an error, matching the strict framing of the reference.
        """
        while not self._done:
            if self._off < len(self._chunk):
                raise AuthError(
                    "IncompleteBody", "data past declared decoded length"
                )
            self._chunk, self._off = b"", 0
            self._next_chunk()
            if self._chunk:
                raise AuthError(
                    "IncompleteBody", "data past declared decoded length"
                )
        if self._cksum is not None:
            want = self.trailers.get(self._ctx.trailer_header, "")
            got = base64.b64encode(self._cksum.digest()).decode()
            if not want or not hmac.compare_digest(got, want):
                raise AuthError(
                    "XAmzContentChecksumMismatch",
                    f"{self._ctx.trailer_header}: want {want!r} got {got!r}",
                )


# ---------------------------------------------------------------------------
# POST form policy (cmd/postpolicyform.go + doesPolicySignatureMatch)
# ---------------------------------------------------------------------------


def verify_post_policy(
    form: "dict[str, str]",
    lookup,
    region: str,
    clock=None,
) -> str:
    """Verify a POST-upload form's policy signature + conditions.

    ``form`` maps lower-cased field names to values.  Returns the
    authenticated access key; raises AuthError on any failure.
    """
    clock = clock or (
        lambda: datetime.datetime.now(datetime.timezone.utc)
    )
    policy_b64 = form.get("policy", "")
    if not policy_b64:
        raise AuthError("AccessDenied", "missing policy")
    if "x-amz-signature" in form:  # V4
        try:
            credential = form["x-amz-credential"]
            amz_date = form["x-amz-date"]
            access_key, date, reg, service, term = credential.split("/", 4)
        except (KeyError, ValueError):
            raise AuthError(
                "AccessDenied", "malformed POST credential"
            ) from None
        secret = lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", access_key)
        key = _signing_key(secret, date, reg, service)
        want = _hmac_hex(key, policy_b64)
        if not hmac.compare_digest(want, form["x-amz-signature"]):
            raise AuthError("SignatureDoesNotMatch", "")
    elif "signature" in form:  # V2
        access_key = form.get("awsaccesskeyid", "")
        secret = lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", access_key)
        want = base64.b64encode(
            hmac.new(
                secret.encode(), policy_b64.encode(), hashlib.sha1
            ).digest()
        ).decode()
        if not hmac.compare_digest(want, form["signature"]):
            raise AuthError("SignatureDoesNotMatch", "")
    else:
        raise AuthError("AccessDenied", "no POST signature")
    check_post_policy(policy_b64, form, clock)
    return access_key


# fields that need no policy condition: auth material, the file itself,
# and server-injected values (checkPostPolicy's ignore list)
_POST_EXEMPT_FIELDS = frozenset(
    {
        "file", "policy", "x-amz-signature", "signature",
        "awsaccesskeyid", "bucket", "content-length",
        "x-amz-algorithm", "x-amz-credential", "x-amz-date",
        # derived from the file part's own Content-Type header, not a
        # client-authored form field
        "content-type",
    }
)


def check_post_policy(policy_b64: str, form: "dict[str, str]", clock) -> None:
    """Validate the decoded policy document against the form fields,
    both ways: every condition must hold AND every form field must be
    covered by a condition (checkPostPolicy, cmd/postpolicyform.go)."""
    try:
        doc = json.loads(base64.b64decode(policy_b64))
    except Exception:  # noqa: BLE001
        raise AuthError("MalformedPOSTRequest", "bad policy JSON") from None
    exp = doc.get("expiration", "")
    try:
        exp_t = datetime.datetime.strptime(
            exp, "%Y-%m-%dT%H:%M:%S.%fZ"
        ).replace(tzinfo=datetime.timezone.utc)
    except ValueError:
        try:
            exp_t = datetime.datetime.strptime(
                exp, "%Y-%m-%dT%H:%M:%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            raise AuthError(
                "MalformedPOSTRequest", "bad policy expiration"
            ) from None
    if clock() > exp_t:
        raise AuthError("AccessDenied", "policy expired")
    size = int(form.get("content-length", "0") or 0)
    covered: set[str] = set()
    for cond in doc.get("conditions", []):
        if isinstance(cond, dict):
            items = [["eq", f"${k}", v] for k, v in cond.items()]
        elif isinstance(cond, list) and len(cond) == 3:
            items = [cond]
        else:
            raise AuthError("MalformedPOSTRequest", "bad condition")
        for op, target, value in items:
            op = str(op).lower()
            if op == "content-length-range":
                lo, hi = int(target), int(value)
                if not (lo <= size <= hi):
                    raise AuthError(
                        "EntityTooLarge"
                        if size > hi
                        else "EntityTooSmall",
                        "content-length-range",
                    )
                continue
            field = str(target).lstrip("$").lower()
            covered.add(field)
            got = form.get(field, "")
            if op == "eq":
                if got != value:
                    raise AuthError(
                        "AccessDenied", f"policy eq failed on {field}"
                    )
            elif op == "starts-with":
                if not got.startswith(value):
                    raise AuthError(
                        "AccessDenied",
                        f"policy starts-with failed on {field}",
                    )
            # unknown operators are ignored (forward compatibility)
    for field in form:
        if field in _POST_EXEMPT_FIELDS or field.startswith("x-ignore-"):
            continue
        if field not in covered:
            raise AuthError(
                "AccessDenied",
                f"form field {field} not covered by policy conditions",
            )
