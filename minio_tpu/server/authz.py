"""Request -> S3 action classification + authorization dispatch
(cmd/auth-handler.go:272 checkRequestAuthType + the per-handler action
constants in cmd/object-handlers.go / bucket-handlers.go).

``action_for_request`` maps (method, bucket, key, query) onto the IAM
action the reference's handler would check; ``authorize`` runs the
identity-policy or bucket-policy decision.
"""

from __future__ import annotations

from ..iam.policy import Args
from .s3errors import S3Error

_BUCKET_GET_SUBRESOURCES = {
    # FIRST: must mirror the router's dispatch precedence - a request
    # carrying several sub-resources is authorized for the one that
    # will actually serve it, and the router checks ?events first
    "events": "s3:ListenBucketNotification",
    "location": "s3:GetBucketLocation",
    "policy": "s3:GetBucketPolicy",
    "versioning": "s3:GetBucketVersioning",
    "tagging": "s3:GetBucketTagging",
    "lifecycle": "s3:GetLifecycleConfiguration",
    "notification": "s3:GetBucketNotification",
    "uploads": "s3:ListBucketMultipartUploads",
    "versions": "s3:ListBucketVersions",
    "object-lock": "s3:GetBucketObjectLockConfiguration",
    "encryption": "s3:GetEncryptionConfiguration",
    "replication": "s3:GetReplicationConfiguration",
    # ACL stubs are gated on the policy action (acl-handlers.go:142)
    "acl": "s3:GetBucketPolicy",
}

_BUCKET_PUT_SUBRESOURCES = {
    "policy": "s3:PutBucketPolicy",
    "versioning": "s3:PutBucketVersioning",
    "tagging": "s3:PutBucketTagging",
    "lifecycle": "s3:PutLifecycleConfiguration",
    "notification": "s3:PutBucketNotification",
    "object-lock": "s3:PutBucketObjectLockConfiguration",
    "encryption": "s3:PutEncryptionConfiguration",
    "replication": "s3:PutReplicationConfiguration",
    "acl": "s3:PutBucketPolicy",
}

_BUCKET_DELETE_SUBRESOURCES = {
    "policy": "s3:DeleteBucketPolicy",
    "tagging": "s3:PutBucketTagging",
    "lifecycle": "s3:PutLifecycleConfiguration",
    "encryption": "s3:PutEncryptionConfiguration",
    "replication": "s3:PutReplicationConfiguration",
}

_OBJECT_GET_SUBRESOURCES = {
    "tagging": "s3:GetObjectTagging",
    "retention": "s3:GetObjectRetention",
    "legal-hold": "s3:GetObjectLegalHold",
}

_OBJECT_PUT_SUBRESOURCES = {
    "tagging": "s3:PutObjectTagging",
    "retention": "s3:PutObjectRetention",
    "legal-hold": "s3:PutObjectLegalHold",
}


def action_for_request(
    method: str,
    bucket: str,
    key: str,
    query: "dict[str, list[str]]",
    headers: "dict[str, str] | None" = None,
) -> str:
    headers = headers or {}
    if not bucket:
        return "s3:ListAllMyBuckets"
    if key:
        if method == "GET":
            for sub, action in _OBJECT_GET_SUBRESOURCES.items():
                if sub in query:
                    return action
            if "uploadId" in query:
                return "s3:ListMultipartUploadParts"
            if "versionId" in query:
                return "s3:GetObjectVersion"
            return "s3:GetObject"
        if method == "HEAD":
            if "versionId" in query:
                return "s3:GetObjectVersion"
            return "s3:GetObject"
        if method == "PUT":
            for sub, action in _OBJECT_PUT_SUBRESOURCES.items():
                if sub in query:
                    return action
            return "s3:PutObject"
        if method == "POST":
            if "select" in query:
                return "s3:SelectObjectContent"
            return "s3:PutObject"  # initiate/complete multipart
        if method == "DELETE":
            if "uploadId" in query:
                return "s3:AbortMultipartUpload"
            if "tagging" in query:
                return "s3:DeleteObjectTagging"
            if "versionId" in query:
                return "s3:DeleteObjectVersion"
            return "s3:DeleteObject"
        raise S3Error("MethodNotAllowed")
    # bucket-level
    if method == "GET":
        for sub, action in _BUCKET_GET_SUBRESOURCES.items():
            if sub in query:
                return action
        return "s3:ListBucket"
    if method == "HEAD":
        return "s3:ListBucket"
    if method == "PUT":
        for sub, action in _BUCKET_PUT_SUBRESOURCES.items():
            if sub in query:
                return action
        return "s3:CreateBucket"
    if method == "DELETE":
        for sub, action in _BUCKET_DELETE_SUBRESOURCES.items():
            if sub in query:
                return action
        return "s3:DeleteBucket"
    if method == "POST":
        # ?delete (multi-delete) authorizes per key inside the handler;
        # POST policy form uploads authorize as PutObject after the form
        # signature verifies
        return "s3:PutObject" if "delete" not in query else "s3:DeleteObject"
    raise S3Error("MethodNotAllowed")


def condition_values(
    query: "dict[str, list[str]]",
    headers: "dict[str, str]",
    client_ip: str = "",
) -> "dict[str, list[str]]":
    """Context keys for policy Condition evaluation
    (cmd/auth-handler.go getConditionValues)."""
    cond: "dict[str, list[str]]" = {}
    for qk, ck in (
        ("prefix", "prefix"),
        ("delimiter", "delimiter"),
        ("max-keys", "max-keys"),
        ("versionid", "versionid"),
    ):
        for k, v in query.items():
            if k.lower() == qk and v:
                cond[ck] = [v[0]]
    lower = {k.lower(): v for k, v in headers.items()}
    if "referer" in lower:
        cond["referer"] = [lower["referer"]]
    if client_ip:
        cond["sourceip"] = [client_ip]
    for k, v in lower.items():
        if k.startswith("x-amz-"):
            cond[k] = [v]
    return cond


def is_reserved_bucket(bucket: str) -> bool:
    """The meta volume (any dot-prefixed name) and the router prefix
    are never reachable as S3 buckets (isMinioMetaBucketName /
    reserved-bucket guard; "minio-tpu" shadows the admin/metrics
    mounts)."""
    return bucket.startswith(".") or bucket == "minio-tpu"


def authorize(
    iam,
    bucket_policy,
    account: str,
    action: str,
    bucket: str,
    key: str,
    conditions: "dict[str, list[str]]",
) -> bool:
    """The reference's two-source decision: identity policy for
    authenticated accounts, resource (bucket) policy for anonymous."""
    args = Args(
        account=account,
        action=action,
        bucket=bucket,
        object=key,
        conditions=conditions,
    )
    if account:
        # authenticated accounts are decided by identity policy alone,
        # matching the mid-2020 reference (auth-handler.go:272: IAMSys
        # for credentials, PolicySys only for anonymous)
        return iam.is_allowed(args)
    if bucket_policy is None:
        return False
    return bucket_policy.is_allowed(args)
