"""Server-plane admission control + telemetry (ROADMAP item 4).

The async request plane sheds load *before* a request reaches the
handler pool and the codec queues (the reference's maxClients +
per-tenant throttles, cmd/handler-api.go): an overloaded stage answers
503 SlowDown instead of queueing unboundedly.  Three shed reasons:

``queue``
    The bounded handler backlog is full (or the global admission slot
    timed out in the threaded plane).
``tenant``
    The claimed access key already holds its per-tenant inflight cap
    (``MINIO_TPU_TENANT_MAX_INFLIGHT``; 0 = unlimited).  The key is
    parsed from the Authorization header *unverified* — it gates
    fairness, never privilege: SigV4 verification still happens on the
    handler path exactly as before.  Keys unknown to the IAM subsystem
    share one bucket so garbage cannot mint unbounded counters.
``quota``
    A PUT whose declared Content-Length would overflow the bucket's
    hard quota, judged against the crawler's usage snapshot only — no
    snapshot means no early shed, preserving the synchronous
    ``XMinioAdminBucketQuotaExceeded`` path inside the handler.

``PlaneStats`` is the shared observability surface for both server
modes: inflight gauge, per-stage queue depths, shed counters.  It is
sampled by the Prometheus exposition (server/metrics.py) and by admin
healthinfo.

Multi-loop plane (ROADMAP item 3): with ``MINIO_TPU_SERVER_LOOPS=N``
the async plane runs N shared-nothing event loops, so admission state
splits in two:

``SharedBudget`` / ``TokenCounter``
    The *global* shed decisions (per-tenant inflight caps, the select
    class cap) must hold across loops, but a cross-loop mutex on every
    admit would serialise the exact path the loops exist to parallelise.
    ``TokenCounter`` is lock-free: it builds an atomic bounded counter
    out of CPython's ``list.append``/``list.pop`` (single C-level
    bytecode ops, atomic under the GIL — the same property
    ``queue.SimpleQueue`` leans on).  ``try_acquire`` optimistically
    appends a reservation token, re-reads the length, and undoes the
    append when over the cap.  The invariant is one-sided by design:
    admitted holders can never exceed the cap (any thread that passed
    the check observed its own token plus every admitted-and-unreleased
    holder's token), while a racing burst may *over-shed* a request
    that would have fit — 503 SlowDown is retryable by contract, so
    shedding conservatively is the safe direction.

``LoopStats``
    Per-loop telemetry cell.  Shed counters are single-writer (only the
    owning loop thread sheds loop-side), the inflight gauge uses the
    same atomic-list trick because a loop's worker threads enter/leave
    it.  No locks anywhere on the per-request path; the ``PlaneStats``
    mutex only guards the threaded-oracle aggregate path and scrape-time
    registration.

The MTPU3xx lockorder auditor registers this module as a target: the
shared-budget fast path must mint zero audited locks (see
tests/test_async_server.py::test_shared_budget_lock_free).
"""

from __future__ import annotations

import os
import re
import threading

from ..utils.log import kv, logger

_log = logger("admission")

SHED_REASONS = ("queue", "quota", "tenant", "select")

# Authorization: AWS4-HMAC-SHA256 Credential=AK/date/region/..., ...
_CRED_RE = re.compile(r"Credential=([^/,\s]+)/")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name) or default)
    except ValueError:
        return default


class TokenCounter:
    """Lock-free bounded counter (atomic under the GIL, no mutex).

    ``_res`` holds reservation tokens: ``try_acquire`` appends one,
    re-reads ``len`` and pops its token back off when the cap is
    exceeded (the popped element may be another thread's token — the
    tokens are indistinguishable, only the multiset count matters, and
    every actor's pops are matched one-to-one to its own appends).
    ``_adm`` holds one token per *admitted* holder, so ``value()`` and
    the ``hwm`` high-water mark count real admissions, untainted by
    transient reservations from racing losers.

    Cap proof: suppose ``limit + 1`` holders were admitted
    concurrently.  The last one to pass the check did so while its own
    reservation token and those of the other ``limit``
    admitted-and-unreleased holders were all in ``_res`` (appends
    happen before checks, pops only on failure/release), so it read
    ``len(_res) >= limit + 1`` and cannot have passed.  The converse
    direction is deliberately weak: extra transient tokens can fail a
    request that would have fit.  Over-shedding is safe (503 SlowDown
    is retryable); over-admitting is not.
    """

    __slots__ = ("_res", "_adm", "hwm")

    def __init__(self):
        self._res: "list[None]" = []
        self._adm: "list[None]" = []
        # benign-race max (may under-record a transient peak, never
        # invents one): hwm <= cap is the bench's exactness witness
        self.hwm = 0

    def try_acquire(self, limit: int) -> bool:
        """Take a slot against ``limit`` (0 or negative = unlimited)."""
        res = self._res
        res.append(None)
        if 0 < limit < len(res):
            try:
                res.pop()
            except IndexError:  # pragma: no cover - matched pops only
                pass
            return False
        self._adm.append(None)
        n = len(self._adm)
        if n > self.hwm:
            self.hwm = n
        return True

    def release(self) -> None:
        try:
            self._adm.pop()
            self._res.pop()
        except IndexError:  # pragma: no cover - unmatched release
            pass

    def value(self) -> int:
        return len(self._adm)


class SharedBudget:
    """Global admission budget shared by every server loop.

    One ``TokenCounter`` per tenant plus one for the select/scan class;
    the tenant map grows only by ``dict.setdefault`` (atomic), and
    ``tenant_of`` collapses unknown access keys into "anon" so the map
    is bounded by the real IAM keyset.  Contains no locks — the
    lockorder auditor asserts as much.
    """

    __slots__ = ("_tenants", "select")

    def __init__(self):
        self._tenants: "dict[str, TokenCounter]" = {}
        self.select = TokenCounter()

    def tenant(self, name: str) -> TokenCounter:
        c = self._tenants.get(name)
        if c is None:
            c = self._tenants.setdefault(name, TokenCounter())
        return c

    def tenant_values(self) -> "dict[str, int]":
        out = {}
        for name, c in list(self._tenants.items()):
            n = c.value()
            if n > 0:
                out[name] = n
        return out

    def tenant_hwm(self) -> "dict[str, int]":
        return {
            name: c.hwm for name, c in list(self._tenants.items())
        }


class LoopStats:
    """One event loop's plane counters — no locks by construction.

    The shed dict is single-writer (only the owning loop thread sheds
    loop-side); the inflight gauge uses the atomic-list trick because
    the loop's *worker* threads call enter/leave from route().
    """

    __slots__ = ("index", "_inflight", "shed", "_depth_fns", "state")

    def __init__(self, index: int):
        self.index = index
        self._inflight: "list[None]" = []
        self.shed = {r: 0 for r in SHED_REASONS}
        self._depth_fns: "dict[str, object]" = {}
        self.state = "booting"

    def enter(self) -> None:
        self._inflight.append(None)

    def leave(self) -> None:
        try:
            self._inflight.pop()
        except IndexError:  # pragma: no cover - unmatched leave
            pass

    def inflight(self) -> int:
        return len(self._inflight)

    def shed_inc(self, reason: str) -> None:
        self.shed[reason] = self.shed.get(reason, 0) + 1

    def register_stage(self, stage: str, depth_fn) -> None:
        self._depth_fns[stage] = depth_fn

    def snapshot(self) -> dict:
        depths = {}
        for stage, fn in dict(self._depth_fns).items():
            try:
                depths[stage] = int(fn())
            except Exception:  # noqa: BLE001 - a gauge must never 500 a scrape
                depths[stage] = 0
        return {
            "loop": self.index,
            "state": self.state,
            "inflight": self.inflight(),
            "shed": dict(self.shed),
            "stage_depth": depths,
        }


class PlaneStats:
    """Thread-safe server-plane counters shared by both server modes.

    The lock guards only the threaded-oracle aggregate counters and
    scrape-time registration; multi-loop traffic lands in per-loop
    ``LoopStats`` cells that are lock-free (see module docstring).
    """

    def __init__(self):
        self._mu = threading.Lock()
        self.inflight = 0
        self.shed = {r: 0 for r in SHED_REASONS}
        # stage -> zero-arg depth sampler; stages register lazily so
        # the threaded plane simply exposes fewer gauges
        self._depth_fns: "dict[str, object]" = {}
        self._loops: "list[LoopStats]" = []

    def add_loop(self) -> LoopStats:
        """Mint the next per-loop stats cell (startup only)."""
        with self._mu:
            cell = LoopStats(len(self._loops))
            self._loops.append(cell)
            return cell

    def loop_cells(self) -> "list[LoopStats]":
        return list(self._loops)

    def enter(self, loop: "int | None" = None) -> None:
        if loop is not None and 0 <= loop < len(self._loops):
            self._loops[loop].enter()
            return
        with self._mu:
            self.inflight += 1

    def leave(self, loop: "int | None" = None) -> None:
        if loop is not None and 0 <= loop < len(self._loops):
            self._loops[loop].leave()
            return
        with self._mu:
            self.inflight = max(0, self.inflight - 1)

    def shed_inc(self, reason: str, loop: "int | None" = None) -> None:
        if loop is not None and 0 <= loop < len(self._loops):
            self._loops[loop].shed_inc(reason)
            return
        with self._mu:
            self.shed[reason] = self.shed.get(reason, 0) + 1

    def register_stage(self, stage: str, depth_fn) -> None:
        with self._mu:
            self._depth_fns[stage] = depth_fn

    def snapshot(self) -> dict:
        """Point-in-time view for metrics/healthinfo rendering.

        ``inflight``/``shed``/``stage_depth`` stay the plane-wide
        aggregates (per-loop cells summed in) so single-loop and
        threaded scrapes are shaped exactly as before; ``loops`` adds
        the per-loop breakdown for the zero-filled ``loop``-labelled
        families.
        """
        with self._mu:
            shed = dict(self.shed)
            inflight = self.inflight
            fns = dict(self._depth_fns)
            cells = list(self._loops)
        depths = {}
        for stage, fn in fns.items():
            try:
                depths[stage] = int(fn())
            except Exception:  # noqa: BLE001 - a gauge must never 500 a scrape
                depths[stage] = 0
        loops = [cell.snapshot() for cell in cells]
        for snap in loops:
            inflight += snap["inflight"]
            for reason, n in snap["shed"].items():
                shed[reason] = shed.get(reason, 0) + n
        return {
            "inflight": inflight,
            "shed": shed,
            "stage_depth": depths,
            "loops": loops,
        }


class AdmissionController:
    """Tenant- and quota-keyed early shed, shared by both planes.

    Stateless apart from the lock-free ``SharedBudget``: every server
    loop (and every threaded-oracle handler thread) admits against the
    same global counters without taking a lock, so the caps stay exact
    across loops while the common admit case costs one uncontended
    per-loop check plus two atomic list ops here.
    """

    def __init__(self, server, stats: PlaneStats):
        self._s3 = server
        self.stats = stats
        self.budget = SharedBudget()

    # -- knobs ------------------------------------------------------------

    def _tenant_max(self) -> int:
        return _env_int("MINIO_TPU_TENANT_MAX_INFLIGHT", 0)

    def _select_max(self) -> int:
        return _env_int("MINIO_TPU_SELECT_MAX_INFLIGHT", 0)

    # -- tenant stage -----------------------------------------------------

    def tenant_of(self, headers) -> str:
        """Fairness key: the *claimed* access key, collapsed to "anon"
        when absent or unknown to IAM (unverified by design — see the
        module docstring)."""
        auth_hdr = headers.get("Authorization") or ""
        m = _CRED_RE.search(auth_hdr)
        if not m:
            return "anon"
        ak = m.group(1)
        try:
            self._s3.iam.lookup_secret(ak)
        except Exception:  # noqa: BLE001 - unknown key, shared bucket
            return "anon"
        return ak

    def try_enter_tenant(self, tenant: str) -> bool:
        """Take a tenant slot; False -> shed 503 reason=tenant."""
        return self.budget.tenant(tenant).try_acquire(self._tenant_max())

    def leave_tenant(self, tenant: str) -> None:
        self.budget.tenant(tenant).release()

    def tenant_inflight(self) -> "dict[str, int]":
        return self.budget.tenant_values()

    # -- select stage -----------------------------------------------------
    #
    # Scans are a second admitted traffic class: one SELECT can pin a
    # device submesh and stream megabytes of filtered rows, so an
    # unbounded scan flood would starve the GET/PUT plane long before
    # the global inflight cap notices.  The cap is its own knob
    # (MINIO_TPU_SELECT_MAX_INFLIGHT; 0 = unlimited) and its sheds get
    # their own reason so the operator can tell scan pressure from
    # queue pressure.

    def try_enter_select(self) -> bool:
        """Take a scan slot; False -> shed 503 reason=select."""
        return self.budget.select.try_acquire(self._select_max())

    def leave_select(self) -> None:
        self.budget.select.release()

    def select_inflight(self) -> int:
        return self.budget.select.value()

    # -- quota stage ------------------------------------------------------

    def quota_rejects_put(self, command: str, path: str, headers) -> bool:
        """True when a PUT's declared size cannot fit the bucket's hard
        quota per the crawler snapshot (enforceBucketQuota's
        dataUsageCache consult) — shed before any body byte is read.

        Deliberately snapshot-only: without a crawler the handler's
        synchronous quota check still runs and keeps its exact error
        code, so this stage can only ever shed earlier, never differ.
        """
        if command != "PUT":
            return False
        bucket = path.lstrip("/").split("/", 1)[0]
        if not bucket:
            return False
        try:
            size = int(headers.get("Content-Length") or 0)
        except ValueError:
            return False
        if size <= 0:
            return False
        crawler = getattr(self._s3, "crawler", None)
        if crawler is None:
            return False
        from ..objectlayer import quota as quotamod

        try:
            cfg = quotamod.config_for(self._s3.bucket_meta, bucket)
            if cfg is None or cfg.quota_type != "hard":
                return False
            bu = crawler.usage().buckets.get(bucket)
            if bu is None:
                return False
            return bu.size + size > cfg.quota
        except Exception as exc:  # noqa: BLE001 - never shed on a broken gauge
            _log.debug(
                "quota precheck failed open", extra=kv(err=str(exc))
            )
            return False
