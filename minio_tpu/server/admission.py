"""Server-plane admission control + telemetry (ROADMAP item 4).

The async request plane sheds load *before* a request reaches the
handler pool and the codec queues (the reference's maxClients +
per-tenant throttles, cmd/handler-api.go): an overloaded stage answers
503 SlowDown instead of queueing unboundedly.  Three shed reasons:

``queue``
    The bounded handler backlog is full (or the global admission slot
    timed out in the threaded plane).
``tenant``
    The claimed access key already holds its per-tenant inflight cap
    (``MINIO_TPU_TENANT_MAX_INFLIGHT``; 0 = unlimited).  The key is
    parsed from the Authorization header *unverified* — it gates
    fairness, never privilege: SigV4 verification still happens on the
    handler path exactly as before.  Keys unknown to the IAM subsystem
    share one bucket so garbage cannot mint unbounded counters.
``quota``
    A PUT whose declared Content-Length would overflow the bucket's
    hard quota, judged against the crawler's usage snapshot only — no
    snapshot means no early shed, preserving the synchronous
    ``XMinioAdminBucketQuotaExceeded`` path inside the handler.

``PlaneStats`` is the shared observability surface for both server
modes: inflight gauge, per-stage queue depths, shed counters.  It is
sampled by the Prometheus exposition (server/metrics.py) and by admin
healthinfo.
"""

from __future__ import annotations

import os
import re
import threading

from ..utils.log import kv, logger

_log = logger("admission")

SHED_REASONS = ("queue", "quota", "tenant", "select")

# Authorization: AWS4-HMAC-SHA256 Credential=AK/date/region/..., ...
_CRED_RE = re.compile(r"Credential=([^/,\s]+)/")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name) or default)
    except ValueError:
        return default


class PlaneStats:
    """Thread-safe server-plane counters shared by both server modes."""

    def __init__(self):
        self._mu = threading.Lock()
        self.inflight = 0
        self.shed = {r: 0 for r in SHED_REASONS}
        # stage -> zero-arg depth sampler; stages register lazily so
        # the threaded plane simply exposes fewer gauges
        self._depth_fns: "dict[str, object]" = {}

    def enter(self) -> None:
        with self._mu:
            self.inflight += 1

    def leave(self) -> None:
        with self._mu:
            self.inflight = max(0, self.inflight - 1)

    def shed_inc(self, reason: str) -> None:
        with self._mu:
            self.shed[reason] = self.shed.get(reason, 0) + 1

    def register_stage(self, stage: str, depth_fn) -> None:
        with self._mu:
            self._depth_fns[stage] = depth_fn

    def snapshot(self) -> dict:
        """Point-in-time view for metrics/healthinfo rendering."""
        with self._mu:
            shed = dict(self.shed)
            inflight = self.inflight
            fns = dict(self._depth_fns)
        depths = {}
        for stage, fn in fns.items():
            try:
                depths[stage] = int(fn())
            except Exception:  # noqa: BLE001 - a gauge must never 500 a scrape
                depths[stage] = 0
        return {
            "inflight": inflight,
            "shed": shed,
            "stage_depth": depths,
        }


class AdmissionController:
    """Tenant- and quota-keyed early shed, shared by both planes."""

    def __init__(self, server, stats: PlaneStats):
        self._s3 = server
        self.stats = stats
        self._mu = threading.Lock()
        self._tenant_inflight: "dict[str, int]" = {}
        self._select_inflight = 0

    # -- knobs ------------------------------------------------------------

    def _tenant_max(self) -> int:
        return _env_int("MINIO_TPU_TENANT_MAX_INFLIGHT", 0)

    def _select_max(self) -> int:
        return _env_int("MINIO_TPU_SELECT_MAX_INFLIGHT", 0)

    # -- tenant stage -----------------------------------------------------

    def tenant_of(self, headers) -> str:
        """Fairness key: the *claimed* access key, collapsed to "anon"
        when absent or unknown to IAM (unverified by design — see the
        module docstring)."""
        auth_hdr = headers.get("Authorization") or ""
        m = _CRED_RE.search(auth_hdr)
        if not m:
            return "anon"
        ak = m.group(1)
        try:
            self._s3.iam.lookup_secret(ak)
        except Exception:  # noqa: BLE001 - unknown key, shared bucket
            return "anon"
        return ak

    def try_enter_tenant(self, tenant: str) -> bool:
        """Take a tenant slot; False -> shed 503 reason=tenant."""
        limit = self._tenant_max()
        with self._mu:
            if limit > 0 and self._tenant_inflight.get(tenant, 0) >= limit:
                return False
            self._tenant_inflight[tenant] = (
                self._tenant_inflight.get(tenant, 0) + 1
            )
            return True

    def leave_tenant(self, tenant: str) -> None:
        with self._mu:
            n = self._tenant_inflight.get(tenant, 0) - 1
            if n <= 0:
                self._tenant_inflight.pop(tenant, None)
            else:
                self._tenant_inflight[tenant] = n

    def tenant_inflight(self) -> "dict[str, int]":
        with self._mu:
            return dict(self._tenant_inflight)

    # -- select stage -----------------------------------------------------
    #
    # Scans are a second admitted traffic class: one SELECT can pin a
    # device submesh and stream megabytes of filtered rows, so an
    # unbounded scan flood would starve the GET/PUT plane long before
    # the global inflight cap notices.  The cap is its own knob
    # (MINIO_TPU_SELECT_MAX_INFLIGHT; 0 = unlimited) and its sheds get
    # their own reason so the operator can tell scan pressure from
    # queue pressure.

    def try_enter_select(self) -> bool:
        """Take a scan slot; False -> shed 503 reason=select."""
        limit = self._select_max()
        with self._mu:
            if limit > 0 and self._select_inflight >= limit:
                return False
            self._select_inflight += 1
            return True

    def leave_select(self) -> None:
        with self._mu:
            self._select_inflight = max(0, self._select_inflight - 1)

    def select_inflight(self) -> int:
        with self._mu:
            return self._select_inflight

    # -- quota stage ------------------------------------------------------

    def quota_rejects_put(self, command: str, path: str, headers) -> bool:
        """True when a PUT's declared size cannot fit the bucket's hard
        quota per the crawler snapshot (enforceBucketQuota's
        dataUsageCache consult) — shed before any body byte is read.

        Deliberately snapshot-only: without a crawler the handler's
        synchronous quota check still runs and keeps its exact error
        code, so this stage can only ever shed earlier, never differ.
        """
        if command != "PUT":
            return False
        bucket = path.lstrip("/").split("/", 1)[0]
        if not bucket:
            return False
        try:
            size = int(headers.get("Content-Length") or 0)
        except ValueError:
            return False
        if size <= 0:
            return False
        crawler = getattr(self._s3, "crawler", None)
        if crawler is None:
            return False
        from ..objectlayer import quota as quotamod

        try:
            cfg = quotamod.config_for(self._s3.bucket_meta, bucket)
            if cfg is None or cfg.quota_type != "hard":
                return False
            bu = crawler.usage().buckets.get(bucket)
            if bu is None:
                return False
            return bu.size + size > cfg.quota
        except Exception as exc:  # noqa: BLE001 - never shed on a broken gauge
            _log.debug(
                "quota precheck failed open", extra=kv(err=str(exc))
            )
            return False
