"""S3 API error model (cmd/api-errors.go, 2102 lines in the reference).

Each error code carries its HTTP status and default message; exceptions
from lower layers map onto codes via ``from_exception`` (the toAPIError
translation, api-errors.go:1763).
"""

from __future__ import annotations

import dataclasses
from http import HTTPStatus as H

from ..objectlayer import api as olapi
from ..storage import errors as serrors
from ..utils.hashreader import BadDigest, SizeMismatch
from .auth import AuthError


@dataclasses.dataclass(frozen=True)
class APIError:
    code: str
    message: str
    status: int


_E = {
    "AccessDenied": ("Access Denied.", H.FORBIDDEN),
    "BadDigest": ("The Content-Md5 you specified did not match what we received.", H.BAD_REQUEST),
    "BucketAlreadyExists": ("The requested bucket name is not available.", H.CONFLICT),
    "BucketAlreadyOwnedByYou": ("Your previous request to create the named bucket succeeded and you already own it.", H.CONFLICT),
    "BucketNotEmpty": ("The bucket you tried to delete is not empty.", H.CONFLICT),
    "EntityTooLarge": ("Your proposed upload exceeds the maximum allowed object size.", H.BAD_REQUEST),
    "EntityTooSmall": ("Your proposed upload is smaller than the minimum allowed object size.", H.BAD_REQUEST),
    "ExpiredToken": ("The provided token has expired.", H.BAD_REQUEST),
    "IncompleteBody": ("You did not provide the number of bytes specified by the Content-Length HTTP header.", H.BAD_REQUEST),
    "InternalError": ("We encountered an internal error, please try again.", H.INTERNAL_SERVER_ERROR),
    "InvalidAccessKeyId": ("The Access Key Id you provided does not exist in our records.", H.FORBIDDEN),
    "InvalidArgument": ("Invalid Argument", H.BAD_REQUEST),
    "InvalidBucketName": ("The specified bucket is not valid.", H.BAD_REQUEST),
    "InvalidDigest": ("The Content-Md5 you specified is not valid.", H.BAD_REQUEST),
    "InvalidPart": ("One or more of the specified parts could not be found.", H.BAD_REQUEST),
    "InvalidPartOrder": ("The list of parts was not in ascending order.", H.BAD_REQUEST),
    "InvalidRange": ("The requested range is not satisfiable", H.REQUESTED_RANGE_NOT_SATISFIABLE),
    "InvalidRequest": ("Invalid Request", H.BAD_REQUEST),
    "KeyTooLongError": ("Your key is too long", H.BAD_REQUEST),
    "MalformedDate": ("Invalid date format header.", H.BAD_REQUEST),
    "MalformedXML": ("The XML you provided was not well-formed or did not validate against our published schema.", H.BAD_REQUEST),
    "MethodNotAllowed": ("The specified method is not allowed against this resource.", H.METHOD_NOT_ALLOWED),
    "MissingContentLength": ("You must provide the Content-Length HTTP header.", H.LENGTH_REQUIRED),
    "NoSuchBucket": ("The specified bucket does not exist", H.NOT_FOUND),
    "NoSuchBucketPolicy": ("The bucket policy does not exist", H.NOT_FOUND),
    "NoSuchLifecycleConfiguration": ("The lifecycle configuration does not exist", H.NOT_FOUND),
    "AllAccessDisabled": ("All access to this bucket has been disabled.", H.FORBIDDEN),
    "MalformedPolicy": ("Policy has invalid resource.", H.BAD_REQUEST),
    "NoSuchKey": ("The specified key does not exist.", H.NOT_FOUND),
    "NoSuchUpload": ("The specified multipart upload does not exist.", H.NOT_FOUND),
    "NoSuchVersion": ("The specified version does not exist.", H.NOT_FOUND),
    "NotImplemented": ("A header you provided implies functionality that is not implemented", H.NOT_IMPLEMENTED),
    "PreconditionFailed": ("At least one of the pre-conditions you specified did not hold", H.PRECONDITION_FAILED),
    "RequestNotReadyYet": ("Request is not valid yet", H.FORBIDDEN),
    "RequestTimeTooSkewed": ("The difference between the request time and the server's time is too large.", H.FORBIDDEN),
    "SignatureDoesNotMatch": ("The request signature we calculated does not match the signature you provided.", H.FORBIDDEN),
    "SignatureVersionNotSupported": ("The authorization mechanism you have provided is not supported.", H.BAD_REQUEST),
    "ServerNotInitialized": ("Server not initialized, please try again.", H.SERVICE_UNAVAILABLE),
    "OperationTimedOut": ("A timeout occurred while trying to lock a resource, please reduce your request rate", H.SERVICE_UNAVAILABLE),
    "SlowDown": ("Resource requested is unreadable, please reduce your request rate", H.SERVICE_UNAVAILABLE),
    "XAmzContentSHA256Mismatch": ("The provided 'x-amz-content-sha256' header does not match what was computed.", H.BAD_REQUEST),
    "XAmzContentChecksumMismatch": ("The provided trailing checksum does not match what was computed.", H.BAD_REQUEST),
    "MalformedPOSTRequest": ("The body of your POST request is not well-formed multipart/form-data.", H.BAD_REQUEST),
    "AuthorizationHeaderMalformed": ("The authorization header is malformed.", H.BAD_REQUEST),
    "AuthorizationQueryParametersError": ("Query-string authentication parameters are malformed.", H.BAD_REQUEST),
    "NotModified": ("Not Modified", H.NOT_MODIFIED),
}


def get(code: str, message: str = "") -> APIError:
    msg, status = _E.get(code, _E["InternalError"])
    return APIError(code, message or msg, int(status))


class S3Error(Exception):
    def __init__(self, code: str, message: str = ""):
        self.err = get(code, message)
        super().__init__(self.err.message)


def _lock_timeout():
    from ..dsync.namespace import LockTimeout

    return LockTimeout


def from_exception(e: Exception) -> APIError:
    """toAPIError: translate layer exceptions to S3 codes."""
    if isinstance(e, S3Error):
        return e.err
    if isinstance(e, AuthError):
        return get(e.code, str(e) if str(e) else "")
    mapping = [
        (olapi.BucketNotFound, "NoSuchBucket"),
        (olapi.BucketExists, "BucketAlreadyOwnedByYou"),
        (olapi.BucketNotEmpty, "BucketNotEmpty"),
        (olapi.InvalidBucketName, "InvalidBucketName"),
        (olapi.ObjectNotFound, "NoSuchKey"),
        (olapi.VersionNotFound, "NoSuchVersion"),
        (olapi.InvalidObjectName, "KeyTooLongError"),
        (olapi.InvalidRange, "InvalidRange"),
        (olapi.InvalidUploadID, "NoSuchUpload"),
        (olapi.InvalidPartOrder, "InvalidPartOrder"),
        (olapi.InvalidPart, "InvalidPart"),
        (olapi.EntityTooSmall, "EntityTooSmall"),
        (olapi.PreconditionFailed, "PreconditionFailed"),
        (olapi.ReadQuorumError, "SlowDown"),
        (olapi.WriteQuorumError, "SlowDown"),
        # lock quorum unavailable (dead peers) = service unavailable,
        # matching the reference's OperationTimedOut 503
        (_lock_timeout(), "OperationTimedOut"),
        (BadDigest, "BadDigest"),
        (SizeMismatch, "IncompleteBody"),
        (serrors.FileNotFound, "NoSuchKey"),
        (serrors.VolumeNotFound, "NoSuchBucket"),
    ]
    for cls, code in mapping:
        if isinstance(e, cls):
            return get(code)
    return get("InternalError", f"{type(e).__name__}: {e}")
