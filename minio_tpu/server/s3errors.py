"""S3 API error model (cmd/api-errors.go, 2102 lines in the reference).

Each error code carries its HTTP status and default message; exceptions
from lower layers map onto codes via ``from_exception`` (the toAPIError
translation, api-errors.go:1763).
"""

from __future__ import annotations

import dataclasses
from http import HTTPStatus as H

from ..objectlayer import api as olapi
from ..storage import errors as serrors
from ..utils.hashreader import BadDigest, SizeMismatch
from .auth import AuthError
from .s3errors_table import VARIANTS


@dataclasses.dataclass(frozen=True)
class APIError:
    code: str
    message: str
    status: int


_E = {
    "AccessDenied": ("Access Denied.", H.FORBIDDEN),
    "BadDigest": ("The Content-Md5 you specified did not match what we received.", H.BAD_REQUEST),
    "BucketAlreadyExists": ("The requested bucket name is not available.", H.CONFLICT),
    "BucketAlreadyOwnedByYou": ("Your previous request to create the named bucket succeeded and you already own it.", H.CONFLICT),
    "BucketNotEmpty": ("The bucket you tried to delete is not empty.", H.CONFLICT),
    "EntityTooLarge": ("Your proposed upload exceeds the maximum allowed object size.", H.BAD_REQUEST),
    "EntityTooSmall": ("Your proposed upload is smaller than the minimum allowed object size.", H.BAD_REQUEST),
    "ExpiredToken": ("The provided token has expired.", H.BAD_REQUEST),
    "IncompleteBody": ("You did not provide the number of bytes specified by the Content-Length HTTP header.", H.BAD_REQUEST),
    "InternalError": ("We encountered an internal error, please try again.", H.INTERNAL_SERVER_ERROR),
    "InvalidAccessKeyId": ("The Access Key Id you provided does not exist in our records.", H.FORBIDDEN),
    "InvalidArgument": ("Invalid Argument", H.BAD_REQUEST),
    "InvalidBucketName": ("The specified bucket is not valid.", H.BAD_REQUEST),
    "InvalidDigest": ("The Content-Md5 you specified is not valid.", H.BAD_REQUEST),
    "InvalidPart": ("One or more of the specified parts could not be found.", H.BAD_REQUEST),
    "InvalidPartOrder": ("The list of parts was not in ascending order.", H.BAD_REQUEST),
    "InvalidRange": ("The requested range is not satisfiable", H.REQUESTED_RANGE_NOT_SATISFIABLE),
    "InvalidRequest": ("Invalid Request", H.BAD_REQUEST),
    "KeyTooLongError": ("Your key is too long", H.BAD_REQUEST),
    "MalformedDate": ("Invalid date format header.", H.BAD_REQUEST),
    "MalformedXML": ("The XML you provided was not well-formed or did not validate against our published schema.", H.BAD_REQUEST),
    "MethodNotAllowed": ("The specified method is not allowed against this resource.", H.METHOD_NOT_ALLOWED),
    "MissingContentLength": ("You must provide the Content-Length HTTP header.", H.LENGTH_REQUIRED),
    "NoSuchBucket": ("The specified bucket does not exist", H.NOT_FOUND),
    "NoSuchBucketPolicy": ("The bucket policy does not exist", H.NOT_FOUND),
    "NoSuchLifecycleConfiguration": ("The lifecycle configuration does not exist", H.NOT_FOUND),
    "AllAccessDisabled": ("All access to this bucket has been disabled.", H.FORBIDDEN),
    "MalformedPolicy": ("Policy has invalid resource.", H.BAD_REQUEST),
    "NoSuchKey": ("The specified key does not exist.", H.NOT_FOUND),
    "NoSuchUpload": ("The specified multipart upload does not exist.", H.NOT_FOUND),
    "NoSuchVersion": ("The specified version does not exist.", H.NOT_FOUND),
    "NotImplemented": ("A header you provided implies functionality that is not implemented", H.NOT_IMPLEMENTED),
    "PreconditionFailed": ("At least one of the pre-conditions you specified did not hold", H.PRECONDITION_FAILED),
    "RequestNotReadyYet": ("Request is not valid yet", H.FORBIDDEN),
    "RequestTimeTooSkewed": ("The difference between the request time and the server's time is too large.", H.FORBIDDEN),
    "SignatureDoesNotMatch": ("The request signature we calculated does not match the signature you provided.", H.FORBIDDEN),
    "SignatureVersionNotSupported": ("The authorization mechanism you have provided is not supported.", H.BAD_REQUEST),
    "ServerNotInitialized": ("Server not initialized, please try again.", H.SERVICE_UNAVAILABLE),
    "HealAlreadyRunning": ("Heal is already running on the given path", H.BAD_REQUEST),
    "HealOverlappingPaths": ("The heal path overlaps with a running heal sequence", H.BAD_REQUEST),
    "HealNoSuchProcess": ("No heal sequence exists on the given path", H.BAD_REQUEST),
    "HealInvalidClientToken": ("Client token mismatch for the heal sequence", H.BAD_REQUEST),
    "OperationTimedOut": ("A timeout occurred while trying to lock a resource, please reduce your request rate", H.SERVICE_UNAVAILABLE),
    "SlowDown": ("Resource requested is unreadable, please reduce your request rate", H.SERVICE_UNAVAILABLE),
    "XAmzContentSHA256Mismatch": ("The provided 'x-amz-content-sha256' header does not match what was computed.", H.BAD_REQUEST),
    "XAmzContentChecksumMismatch": ("The provided trailing checksum does not match what was computed.", H.BAD_REQUEST),
    "MalformedPOSTRequest": ("The body of your POST request is not well-formed multipart/form-data.", H.BAD_REQUEST),
    "AuthorizationHeaderMalformed": ("The authorization header is malformed.", H.BAD_REQUEST),
    "AuthorizationQueryParametersError": ("Query-string authentication parameters are malformed.", H.BAD_REQUEST),
    "NotModified": ("Not Modified", H.NOT_MODIFIED),
    # -- tagging (api-errors.go ErrBucketTaggingNotFound / ErrInvalidTag)
    "NoSuchTagSet": ("The TagSet does not exist", H.NOT_FOUND),
    "InvalidTag": ("The tag provided was not a valid tag. This error can occur if the tag did not pass input validation.", H.BAD_REQUEST),
    "InvalidTagDirective": ("Unknown tag directive.", H.BAD_REQUEST),
    # -- object lock / retention / legal hold (api-errors.go:171-181)
    "InvalidBucketObjectLockConfiguration": ("Bucket is missing ObjectLockConfiguration", H.BAD_REQUEST),
    "ObjectLockConfigurationNotFoundError": ("Object Lock configuration does not exist for this bucket", H.NOT_FOUND),
    "InvalidBucketState": ("Object Lock configuration cannot be enabled on existing buckets", H.CONFLICT),
    "NoSuchObjectLockConfiguration": ("The specified object does not have a ObjectLock configuration", H.BAD_REQUEST),
    "ObjectLocked": ("Object is WORM protected and cannot be overwritten", H.BAD_REQUEST),
    "InvalidRetentionDate": ("Date must be provided in ISO 8601 format", H.BAD_REQUEST),
    "PastObjectLockRetainDate": ("the retain until date must be in the future", H.BAD_REQUEST),
    "UnknownWORMModeDirective": ("unknown WORM mode directive", H.BAD_REQUEST),
    "ObjectLockInvalidHeaders": ("x-amz-object-lock-retain-until-date and x-amz-object-lock-mode must both be supplied", H.BAD_REQUEST),
    # -- bucket config long tail
    "ServerSideEncryptionConfigurationNotFoundError": ("The server side encryption configuration was not found", H.NOT_FOUND),
    "NoSuchCORSConfiguration": ("The CORS configuration does not exist", H.NOT_FOUND),
    "NoSuchWebsiteConfiguration": ("The specified bucket does not have a website configuration", H.NOT_FOUND),
    "ReplicationConfigurationNotFoundError": ("The replication configuration was not found", H.NOT_FOUND),
    "ReplicationDestinationNotFoundError": ("The replication destination bucket does not exist", H.NOT_FOUND),
    "ReplicationTargetNotVersionedError": ("The replication target does not have versioning enabled", H.BAD_REQUEST),
    "ReplicationSourceNotVersionedError": ("The replication source does not have versioning enabled", H.BAD_REQUEST),
    "XMinioAdminBucketQuotaExceeded": ("Bucket quota exceeded", H.BAD_REQUEST),
    "XMinioAdminNoSuchQuotaConfiguration": ("The quota configuration does not exist", H.NOT_FOUND),
    # -- misc request validation
    "InvalidStorageClass": ("Invalid storage class.", H.BAD_REQUEST),
    "InvalidPolicyDocument": ("The content of the form does not meet the conditions specified in the policy document.", H.BAD_REQUEST),
    "PolicyTooLarge": ("Policy exceeds the maximum allowed document size.", H.BAD_REQUEST),
    "MissingContentMD5": ("Missing required header for this request: Content-Md5.", H.BAD_REQUEST),
    "MissingSecurityHeader": ("Your request was missing a required header", H.BAD_REQUEST),
    "MissingRequestBodyError": ("Request body is empty.", H.LENGTH_REQUIRED),
    "InvalidObjectState": ("The operation is not valid for the current state of the object.", H.FORBIDDEN),
    "InvalidRegion": ("Region does not match.", H.BAD_REQUEST),
    "InvalidPrefixMarker": ("Invalid marker prefix combination", H.BAD_REQUEST),
    "BadRequest": ("400 BadRequest", H.BAD_REQUEST),
    "InvalidDuration": ("Duration provided in the request is invalid.", H.BAD_REQUEST),
    "InvalidTokenId": ("The security token included in the request is invalid", H.FORBIDDEN),
    "RequestTimeout": ("Your socket connection to the server was not read from or written to within the timeout period.", H.BAD_REQUEST),
    "UnsupportedNotification": ("MinIO server does not support Tilde, Period characters in prefix/suffix for notifications.", H.BAD_REQUEST),
    "XMinioInvalidObjectName": ("Object name contains unsupported characters.", H.BAD_REQUEST),
    "XMinioStorageFull": ("Storage backend has reached its minimum free disk threshold. Please delete a few objects to proceed.", H.INSUFFICIENT_STORAGE),
    "XMinioObjectTampered": ("The requested object was modified and may be compromised", H.PARTIAL_CONTENT),
    "XMinioBackendDown": ("Object storage backend is unreachable", H.SERVICE_UNAVAILABLE),
    # -- STS (cmd/sts-errors.go)
    "InvalidParameterValue": ("An invalid or out-of-range value was supplied for the input parameter.", H.BAD_REQUEST),
    "STSMissingParameter": ("A required parameter for the specified action is not supplied.", H.BAD_REQUEST),
    "STSInvalidClientTokenId": ("The security token included in the request is invalid.", H.FORBIDDEN),
    "STSAccessDenied": ("Generating temporary credentials not allowed for this request.", H.FORBIDDEN),
    "STSInternalError": ("We encountered an internal error generating credentials, please try again.", H.INTERNAL_SERVER_ERROR),
    # -- S3 Select (pkg/s3select errors surfaced through api-errors.go)
    "EmptyRequestBody": ("Request body cannot be empty.", H.BAD_REQUEST),
    "UnsupportedFunction": ("Encountered an unsupported SQL function.", H.BAD_REQUEST),
    "InvalidDataSource": ("Invalid data source type. Only CSV and JSON are supported at this time.", H.BAD_REQUEST),
    "InvalidExpressionType": ("The ExpressionType is invalid. Only SQL expressions are supported at this time.", H.BAD_REQUEST),
    "InvalidRequestParameter": ("The value of a parameter in SelectRequest element is invalid. Check the service API documentation and try again.", H.BAD_REQUEST),
    "InvalidFileHeaderInfo": ("The FileHeaderInfo is invalid. Only NONE, USE, and IGNORE are supported.", H.BAD_REQUEST),
    "InvalidQuoteFields": ("The QuoteFields is invalid. Only ALWAYS and ASNEEDED are supported.", H.BAD_REQUEST),
    "InvalidJsonType": ("The JsonType is invalid. Only DOCUMENT and LINES are supported at this time.", H.BAD_REQUEST),
    "InvalidCompressionFormat": ("The file is not in a supported compression format. Only GZIP and BZIP2 are supported.", H.BAD_REQUEST),
    "InvalidTextEncoding": ("Invalid encoding type. Only UTF-8 encoding is supported at this time.", H.BAD_REQUEST),
    "ParseSelectFailure": ("The SQL expression cannot be parsed.", H.BAD_REQUEST),
    "UnsupportedSqlOperation": ("Encountered an unsupported SQL operation.", H.BAD_REQUEST),
    "UnsupportedSqlStructure": ("Encountered an unsupported SQL structure. Check the SQL Reference.", H.BAD_REQUEST),
    "UnsupportedSyntax": ("Encountered invalid syntax.", H.BAD_REQUEST),
    "MissingRequiredParameter": ("The SelectRequest entity is missing a required parameter. Check the service documentation and try again.", H.BAD_REQUEST),
    # -- S3 Select SQL lexer/parser family (pkg/s3select/sql surfaced
    #    through api-errors.go); one code per distinguishable parse
    #    state so SDK retries/diagnostics behave like upstream
    "LexerInvalidChar": ("The SQL expression contains an invalid character.", H.BAD_REQUEST),
    "LexerInvalidOperator": ("The SQL expression contains an invalid operator.", H.BAD_REQUEST),
    "LexerInvalidLiteral": ("The SQL expression contains an invalid literal.", H.BAD_REQUEST),
    "ParseUnexpectedToken": ("The SQL expression contains an unexpected token.", H.BAD_REQUEST),
    "ParseUnexpectedKeyword": ("The SQL expression contains an unexpected keyword.", H.BAD_REQUEST),
    "ParseUnexpectedOperator": ("The SQL expression contains an unexpected operator.", H.BAD_REQUEST),
    "ParseUnexpectedTerm": ("The SQL expression contains an unexpected term.", H.BAD_REQUEST),
    "ParseExpectedExpression": ("Did not find the expected SQL expression.", H.BAD_REQUEST),
    "ParseExpectedKeyword": ("Did not find the expected keyword in the SQL expression.", H.BAD_REQUEST),
    "ParseExpectedTokenType": ("Did not find the expected token in the SQL expression.", H.BAD_REQUEST),
    "ParseExpectedNumber": ("Did not find the expected number in the SQL expression.", H.BAD_REQUEST),
    "ParseExpectedIdentForAlias": ("Did not find the expected identifier for the alias in the SQL expression.", H.BAD_REQUEST),
    "ParseExpectedArgumentDelimiter": ("Did not find the expected argument delimiter in the SQL expression.", H.BAD_REQUEST),
    "ParseEmptySelect": ("The SQL expression contains an empty SELECT.", H.BAD_REQUEST),
    "ParseSelectMissingFrom": ("The SQL expression contains a missing FROM after SELECT list.", H.BAD_REQUEST),
    "ParseExpectedMember": ("The SQL expression contains an invalid member reference.", H.BAD_REQUEST),
    "ParseAsteriskIsNotAloneInSelectList": ("Other expressions are not allowed in the SELECT list when '*' is used without dot notation in the SQL expression.", H.BAD_REQUEST),
    "ParseInvalidContextForWildcardInSelectList": ("Invalid use of '*' in the SELECT list of the SQL expression.", H.BAD_REQUEST),
    "ParseCastArity": ("The SQL expression CAST has incorrect arity.", H.BAD_REQUEST),
    "ParseExpectedLeftParenAfterCast": ("Did not find the expected left parenthesis after CAST in the SQL expression.", H.BAD_REQUEST),
    "ParseExpectedTypeName": ("Did not find the expected type name after CAST in the SQL expression.", H.BAD_REQUEST),
    "ParseInvalidTypeParam": ("The SQL expression contains an invalid parameter value for a type.", H.BAD_REQUEST),
    "ParseUnsupportedSyntax": ("The SQL expression contains unsupported syntax.", H.BAD_REQUEST),
    "ParseUnsupportedSelect": ("The SQL expression contains an unsupported use of SELECT.", H.BAD_REQUEST),
    "ParseUnsupportedCallWithStar": ("Only COUNT may be used with '*' in the SQL expression.", H.BAD_REQUEST),
    "ParseUnsupportedCase": ("The SQL expression contains an unsupported use of CASE.", H.BAD_REQUEST),
    "ParseUnsupportedLiteralsGroupBy": ("The SQL expression contains an unsupported use of GROUP BY.", H.BAD_REQUEST),
    "ParseUnsupportedAlias": ("The SQL expression contains an unsupported use of an alias.", H.BAD_REQUEST),
    "ParseUnknownOperator": ("The SQL expression contains an invalid operator.", H.BAD_REQUEST),
    "ParseMalformedJoin": ("JOIN is not supported in the SQL expression.", H.BAD_REQUEST),
    "ParseNonUnaryAgregateFunctionCall": ("Only one argument is supported for aggregate functions in the SQL expression.", H.BAD_REQUEST),
    "EvaluatorInvalidArguments": ("Incorrect number of arguments in the function call in the SQL expression.", H.BAD_REQUEST),
    "EvaluatorInvalidTimestampFormatPattern": ("The timestamp format pattern contains an invalid format specifier in the SQL expression.", H.BAD_REQUEST),
    "EvaluatorBindingDoesNotExist": ("A column name or a path provided does not exist in the SQL expression.", H.BAD_REQUEST),
    "InvalidCast": ("Attempt to convert from one data type to another using CAST failed in the SQL expression.", H.BAD_REQUEST),
    "CastFailed": ("Attempt to convert from one data type to another using CAST failed in the SQL expression.", H.BAD_REQUEST),
    "InvalidDataType": ("The SQL expression contains an invalid data type.", H.BAD_REQUEST),
    "InvalidColumnIndex": ("The column index in the SQL expression is invalid.", H.BAD_REQUEST),
    "InvalidKeyPath": ("The key path in the SQL expression is invalid.", H.BAD_REQUEST),
    "InvalidTableAlias": ("The SQL expression contains an invalid table alias.", H.BAD_REQUEST),
    "IntegerOverflow": ("An integer overflow or underflow occurred in the SQL expression.", H.BAD_REQUEST),
    "LikeInvalidInputs": ("Invalid argument given to the LIKE clause in the SQL expression.", H.BAD_REQUEST),
    "IllegalSqlFunctionArgument": ("Illegal argument was used in the SQL function.", H.BAD_REQUEST),
    "IncorrectSqlFunctionArgumentType": ("Incorrect type of arguments in the function call in the SQL expression.", H.BAD_REQUEST),
    "ExpressionTooLong": ("The SQL expression is too long: the maximum byte-length for the SQL expression is 256 KB.", H.BAD_REQUEST),
    "MissingHeaders": ("Some headers in the query are missing from the file. Check the file and try again.", H.BAD_REQUEST),
    "ValueParseFailure": ("Time stamp parse failure in the SQL expression.", H.BAD_REQUEST),
    "ObjectSerializationConflict": ("The SelectRequest entity contains more than one data serialization format.", H.BAD_REQUEST),
    # -- misc long-tail (api-errors.go)
    "UnsupportedRangeHeader": ("Range header type is not supported - only bytes ranges are accepted.", H.BAD_REQUEST),
    "UnauthorizedAccess": ("You are not authorized to perform this operation.", H.UNAUTHORIZED),
    "Busy": ("The service is unavailable, please retry.", H.SERVICE_UNAVAILABLE),
    "MissingFields": ("A required field in the request is missing.", H.BAD_REQUEST),
    "NoSuchBucketLifecycle": ("The bucket lifecycle configuration does not exist.", H.NOT_FOUND),
    "IllegalVersioningConfigurationException": ("The versioning configuration specified in the request is invalid.", H.BAD_REQUEST),
    "PostPolicyInvalidKeyName": ("Invalid according to Policy: Policy Condition failed.", H.FORBIDDEN),
    "AuthorizationParametersError": ("The authorization parameters in the request are invalid.", H.BAD_REQUEST),
}


# keys whose WIRE code differs from the key (matching the reference's
# Code strings exactly - mc/madmin/SDKs dispatch on these); the key
# names stay stable for in-tree raisers
_WIRE = {
    "SignatureVersionNotSupported": "InvalidRequest",
    "RequestNotReadyYet": "AccessDenied",
    "InvalidBucketObjectLockConfiguration": "InvalidRequest",
    "ObjectLocked": "InvalidRequest",
    "InvalidRetentionDate": "InvalidRequest",
    "PastObjectLockRetainDate": "InvalidRequest",
    "UnknownWORMModeDirective": "InvalidRequest",
    "ObjectLockInvalidHeaders": "InvalidRequest",
    "InvalidTagDirective": "InvalidArgument",
    "ServerNotInitialized": "XMinioServerNotInitialized",
    "OperationTimedOut": "RequestTimeout",
    "HealNoSuchProcess": "XMinioHealNoSuchProcess",
    "HealInvalidClientToken": "XMinioHealInvalidClientToken",
    "HealAlreadyRunning": "XMinioHealAlreadyRunning",
    "HealOverlappingPaths": "XMinioHealOverlappingPaths",
    "EvaluatorBindingDoesNotExist": "ErrEvaluatorBindingDoesNotExist",
}


def get(code: str, message: str = "") -> APIError:
    """APIError for a code key.  Keys are usually the wire code; the
    fine-grained reference conditions (ErrInvalidCopyDest, ...) that
    REUSE a wire code live in s3errors_table.VARIANTS under their
    internal names and resolve to (wire code, own message)."""
    hit = _E.get(code)
    if hit is not None:
        msg, status = hit
        return APIError(
            _WIRE.get(code, code), message or msg, int(status)
        )
    var = VARIANTS.get(code)
    if var is not None:
        wire, msg, status = var
        return APIError(wire, message or msg, int(status))
    msg, status = _E["InternalError"]
    return APIError(code, message or msg, int(status))


class S3Error(Exception):
    def __init__(self, code: str, message: str = ""):
        self.err = get(code, message)
        super().__init__(self.err.message)


def _lock_timeout():
    from ..dsync.namespace import LockTimeout

    return LockTimeout


def from_exception(e: Exception) -> APIError:
    """toAPIError: translate layer exceptions to S3 codes."""
    if isinstance(e, S3Error):
        return e.err
    from ..codec.sse import SSEError

    if isinstance(e, SSEError):
        # wrong key / missing KMS / tampered ciphertext
        # (toAPIErrorCode maps crypto errors onto AccessDenied)
        return get("AccessDenied", str(e))
    if isinstance(e, AuthError):
        return get(e.code, str(e) if str(e) else "")
    if isinstance(e, NotImplementedError):
        # backend without the capability (FS versioning, gateways)
        return get("NotImplemented", str(e) or "")
    try:
        from ..gateway.client import UpstreamError
    except ImportError:
        UpstreamError = ()  # type: ignore[assignment]
    if isinstance(e, UpstreamError):
        # pass the upstream's verdict through with ITS status class
        # instead of collapsing every gateway failure into a 500
        # (gateway ErrorRespToObjectError, gateway-common.go)
        code = {
            400: "InvalidRequest",
            403: "AccessDenied",
            404: "NoSuchKey",
            409: "OperationAborted",
            503: "SlowDown",
        }.get(e.status)
        if e.code and e.code != "UpstreamError" and e.code in _E:
            return get(e.code, str(e))
        if code:
            return get(code, str(e))
        return get("InternalError", str(e))
    mapping = [
        (olapi.BucketNotFound, "NoSuchBucket"),
        (olapi.BucketExists, "BucketAlreadyOwnedByYou"),
        (olapi.BucketNotEmpty, "BucketNotEmpty"),
        (olapi.InvalidBucketName, "InvalidBucketName"),
        (olapi.ObjectNotFound, "NoSuchKey"),
        (olapi.VersionNotFound, "NoSuchVersion"),
        (olapi.InvalidObjectName, "KeyTooLongError"),
        (olapi.InvalidRange, "InvalidRange"),
        (olapi.InvalidUploadID, "NoSuchUpload"),
        (olapi.InvalidPartOrder, "InvalidPartOrder"),
        (olapi.InvalidPart, "InvalidPart"),
        (olapi.EntityTooSmall, "EntityTooSmall"),
        (olapi.PreconditionFailed, "PreconditionFailed"),
        (olapi.ReadQuorumError, "SlowDown"),
        (olapi.WriteQuorumError, "SlowDown"),
        # lock quorum unavailable (dead peers) = service unavailable,
        # matching the reference's OperationTimedOut 503
        (_lock_timeout(), "OperationTimedOut"),
        (BadDigest, "BadDigest"),
        (SizeMismatch, "IncompleteBody"),
        (serrors.FileNotFound, "NoSuchKey"),
        (serrors.VolumeNotFound, "NoSuchBucket"),
    ]
    for cls, code in mapping:
        if isinstance(e, cls):
            if code in ("SlowDown", "OperationTimedOut"):
                # quorum/lock failures carry the per-disk cause; an
                # operator debugging a 503 needs it in the body
                return get(code, f"{_E[code][0]} ({e})")
            return get(code)
    return get("InternalError", f"{type(e).__name__}: {e}")
