"""Asyncio request plane (ROADMAP items 3+4; MINIO_TPU_SERVER=async).

The reference serves thousands of connections on goroutines behind its
custom L7 listener (cmd/http/server.go); a thread-per-request stdlib
server on a GIL cannot do that — at 32 clients every blocked thread
competes for the interpreter and p99 collapses.  This plane runs N
shared-nothing event loops (``MINIO_TPU_SERVER_LOOPS``, default
``min(cores, 4)``), each loop thread owning its sockets, connections,
parser, bridges, and a slice of the bounded worker pool running the
existing synchronous handlers, so concurrency costs a queue slot
instead of a thread:

    accept -> [parse: loop_i] -> [admission: loop_i + shared budget]
    -> [handler: loop_i's pool slice] -> [codec/disk:
    parallel/iopool.py] -> response via loop_i

No cross-loop locks on the hot path: a connection lives and dies on
one loop, and the only cross-loop state a request touches is the
lock-free ``SharedBudget`` (server/admission.py) that keeps tenant and
select caps globally exact.  ``MINIO_TPU_SERVER_LOOPS=1`` is today's
single-loop plane verbatim — the bisection oracle within the async
mode, just as ``MINIO_TPU_SERVER=threaded`` bisects the whole plane.

Listener sharding uses ``SO_REUSEPORT`` where the platform offers it
(each loop gets its own bound socket; the kernel spreads accepts), and
falls back to one listener on loop 0 handing accepted sockets off
round-robin (``MINIO_TPU_SERVER_REUSEPORT=off`` forces the fallback —
useful to exercise it on Linux).

Stage boundaries are explicit queues with backpressure; when the
handler backlog is full the request is shed with 503 SlowDown *before*
any body byte is read (server/admission.py).  The handlers themselves
are unchanged — ``_Handler.route()`` runs on a worker thread over two
thin bridges:

``_LoopReader``
    Blocking file-like over the connection's ``asyncio.StreamReader``.
    Each ``read(n)`` is one ``run_coroutine_threadsafe`` round-trip, so
    a PUT body streams chunk-by-chunk from the loop straight into
    ``HashReader`` -> ``encode_begin`` with bounded memory — the loop
    never holds a full body and the worker never touches the socket.

``_LoopWriter``
    Blocking writes through ``transport.write`` + ``drain()``.  A
    ``memoryview`` passes to the transport unjoined (zero-copy GET: the
    decoded block slices the iopool assembles go to the socket without
    intermediate ``b"".join``); blocking the worker until the loop has
    consumed the buffer makes caller-side buffer reuse safe and gives
    natural per-connection flow control.

Long-lived streaming endpoints (admin trace/console, bucket event
listen) would starve a bounded pool, so they run on dedicated threads.
The threaded plane stays available as the bisection oracle
(``MINIO_TPU_SERVER=threaded``, house style of MINIO_TPU_PARITY_PLANE).

Blocking calls inside ``async def`` bodies here are a correctness bug
(one stalled coroutine stalls every connection *on its loop*): MTPU108
in minio_tpu/analysis lints for them; the bridges above are sync-side
by construction.  The fault-injection wedge (`wedge_loop`, driving the
testgrid ``wedged_loop`` chaos cell) deliberately stalls one loop with
a busy-spin to prove the blast radius stops at the loop boundary.
"""

from __future__ import annotations

import asyncio
import io
import os
import queue
import socket
import threading
import urllib.parse
import uuid
from http import client as _hclient

from . import s3errors
from . import response as xmlr
from ..utils.log import kv, logger

_log = logger("aio")

# header-block cap, matching the stdlib server's per-line ceiling
_MAX_HEAD = 1 << 16

# listen(2) backlog for sharded/fallback sockets (asyncio's default)
_LISTEN_BACKLOG = 100


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name) or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name) or default)
    except ValueError:
        return default


def _default_workers() -> int:
    """A few blocking-I/O slots per core, capped.  More workers than
    this just interleaves CPU-bound codec work (GIL thrash) and
    inflates p99 without adding throughput."""
    return min(16, max(4, 4 * (os.cpu_count() or 1)))


def _default_loops() -> int:
    """One accept loop per core up to 4: past that the shared budget
    and the disk plane dominate before accept/parse does."""
    return min(os.cpu_count() or 1, 4)


def _loop_count() -> int:
    return max(1, _env_int("MINIO_TPU_SERVER_LOOPS", _default_loops()))


def _reuseport_requested() -> bool:
    val = (os.environ.get("MINIO_TPU_SERVER_REUSEPORT") or "auto").lower()
    return val not in ("off", "0", "false", "no")


def _split(total: int, parts: int) -> "list[int]":
    """Spread ``total`` across ``parts`` slices, each at least 1."""
    base, rem = divmod(max(total, parts), parts)
    return [base + (1 if i < rem else 0) for i in range(parts)]


class _LoopReader:
    """Synchronous file-like over the owning loop's StreamReader, used
    by the handler thread.  Every call blocks the *worker*, never the
    loop.  ``owner`` is the connection's ``_ServerLoop``."""

    def __init__(self, owner: "_ServerLoop", reader: asyncio.StreamReader):
        self._owner = owner
        self._reader = reader

    def _call(self, coro):
        try:
            fut = asyncio.run_coroutine_threadsafe(coro, self._owner.loop)
            return fut.result()
        except asyncio.TimeoutError:
            raise socket.timeout("body read timed out") from None
        except (RuntimeError, ConnectionError, asyncio.CancelledError) as e:
            raise OSError(f"connection lost: {e}") from None

    def read(self, n: int = -1) -> bytes:
        timeout = self._owner.body_timeout

        async def _rd():
            return await asyncio.wait_for(self._reader.read(n), timeout)

        return self._call(_rd())

    def readline(self, limit: int = -1) -> bytes:
        """Bounded line read (internode chunked framing uses 1024)."""
        timeout = self._owner.body_timeout
        reader = self._reader

        async def _rl():
            out = bytearray()
            while limit < 0 or len(out) < limit:
                b = await asyncio.wait_for(reader.read(1), timeout)
                if not b:
                    break
                out += b
                if b == b"\n":
                    break
            return bytes(out)

        return self._call(_rl())


class _LoopWriter:
    """Synchronous writes through the owning loop's transport.

    ``write`` hands the buffer (bytes or memoryview — unjoined) to
    ``transport.write`` on the loop and blocks the worker through
    ``drain()``, so a slow client backpressures its own worker instead
    of growing an unbounded transport buffer."""

    def __init__(self, owner: "_ServerLoop", writer: asyncio.StreamWriter):
        self._owner = owner
        self._writer = writer

    def write(self, data) -> int:
        n = len(data)
        if n == 0:
            return 0
        writer = self._writer

        async def _wr():
            writer.write(data)
            await writer.drain()

        try:
            asyncio.run_coroutine_threadsafe(
                _wr(), self._owner.loop
            ).result()
        except (RuntimeError, ConnectionError, asyncio.CancelledError) as e:
            raise OSError(f"connection lost: {e}") from None
        return n

    def flush(self) -> None:  # writes are already synchronous
        pass


class _WorkerPool:
    """Bounded handler stage: a full backlog means shed, not queue."""

    def __init__(self, workers: int, backlog: int, name: str = "aio"):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, backlog))
        self.workers = max(1, workers)
        self._threads = [
            threading.Thread(
                target=self._run, name=f"{name}-worker-{i}", daemon=True
            )
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()
        self._streams: "set[threading.Thread]" = set()
        self._streams_mu = threading.Lock()
        self._stream_seq = 0
        self._name = name

    def depth(self) -> int:
        return self._q.qsize()

    def try_submit(self, fn) -> bool:
        try:
            self._q.put_nowait(fn)
            return True
        except queue.Full:
            return False

    def spawn_stream(self, fn) -> None:
        """Long-lived streaming request: dedicated thread so it cannot
        starve the bounded pool (trace/console/listen endpoints)."""
        with self._streams_mu:
            self._stream_seq += 1
            name = f"{self._name}-stream-{self._stream_seq}"
        t = threading.Thread(
            target=self._run_stream, args=(fn,), name=name, daemon=True
        )
        with self._streams_mu:
            self._streams.add(t)
        t.start()

    def _run_stream(self, fn) -> None:
        try:
            fn()
        except Exception as exc:  # noqa: BLE001
            _log.debug("stream handler failed", extra=kv(err=str(exc)))
        finally:
            with self._streams_mu:
                self._streams.discard(threading.current_thread())

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception as exc:  # noqa: BLE001
                _log.debug("handler job failed", extra=kv(err=str(exc)))

    def shutdown(self, timeout: float = 10.0) -> None:
        for _ in self._threads:
            try:
                self._q.put(None, timeout=timeout)
            except queue.Full:
                break
        for t in self._threads:
            t.join(timeout)
        with self._streams_mu:
            streams = list(self._streams)
        for t in streams:
            t.join(timeout)


class _ServerLoop:
    """One shared-nothing event loop: its own thread, listener socket,
    connection set, worker-pool slice, and lock-free stats cell.  A
    connection accepted here never touches another loop."""

    def __init__(self, plane: "AsyncPlane", index: int,
                 workers: int, backlog: int):
        self.plane = plane
        self.s3 = plane.s3
        self.adm = plane.adm
        self.index = index
        self.loop = asyncio.new_event_loop()
        self.header_timeout = plane.header_timeout
        self.body_timeout = plane.body_timeout
        self.idle_timeout = plane.idle_timeout
        self.pool = _WorkerPool(workers, backlog, name=f"aio{index}")
        self.lstats = plane.stats.add_loop()
        self._conns: "set[asyncio.StreamWriter]" = set()
        self._tasks: "set[asyncio.Task]" = set()
        self._srv = None
        self._thread: "threading.Thread | None" = None
        self.lstats.register_stage("parse", lambda: len(self._conns))
        self.lstats.register_stage("handler", self.pool.depth)

    # -- lifecycle --------------------------------------------------------

    @property
    def state(self) -> str:
        return self.lstats.state

    @state.setter
    def state(self, value: str) -> None:
        self.lstats.state = value

    def start_thread(self) -> None:
        self._thread = threading.Thread(
            target=self._run_loop, name=f"aio-loop-{self.index}",
            daemon=True,
        )
        self._thread.start()

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self.loop)
        try:
            self.loop.run_forever()
        finally:
            try:
                self.loop.close()
            except Exception as exc:  # noqa: BLE001
                _log.debug("loop close failed", extra=kv(err=str(exc)))

    def serve(self, host, port, sock, ssl_ctx) -> None:
        """Bring the listener up ON this loop (a bound SO_REUSEPORT
        socket when sharded, host/port for the single-loop plane, or
        no listener at all in handoff mode)."""

        async def _boot():
            if sock is not None:
                return await asyncio.start_server(
                    self._serve_conn, sock=sock, ssl=ssl_ctx,
                    limit=_MAX_HEAD,
                )
            return await asyncio.start_server(
                self._serve_conn, host, port, ssl=ssl_ctx,
                limit=_MAX_HEAD,
            )

        self._srv = asyncio.run_coroutine_threadsafe(
            _boot(), self.loop
        ).result(timeout=30)
        self.state = "serving"

    def mark_serving(self) -> None:
        """Handoff mode: no listener of our own, the acceptor feeds us."""
        self.state = "serving"

    def bound_port(self) -> int:
        return self._srv.sockets[0].getsockname()[1]

    async def _adopt(self, conn: socket.socket, ssl_ctx) -> None:
        """Round-robin handoff target: wrap an already-accepted socket
        in this loop's streams and serve it like a native accept."""
        conn.setblocking(False)
        reader = asyncio.StreamReader(limit=_MAX_HEAD)
        proto = asyncio.StreamReaderProtocol(reader, self._serve_conn)
        try:
            # factory, not instance: one _adopt call wraps one socket
            await self.loop.connect_accepted_socket(
                lambda: proto, conn, ssl=ssl_ctx
            )
        except (OSError, asyncio.CancelledError):
            conn.close()

    def close_listener(self) -> None:
        self.state = "draining"
        if self._srv is not None:
            self.loop.call_soon_threadsafe(self._srv.close)

    def cut_conns(self) -> None:
        """Cut remaining connections while the loop still runs: pending
        bridge reads/writes fail fast and unblock their workers."""

        def _cut():
            for w in list(self._conns):
                try:
                    w.close()
                except Exception as exc:  # noqa: BLE001
                    _log.debug(
                        "transport close failed", extra=kv(err=str(exc))
                    )

        self.loop.call_soon_threadsafe(_cut)

    def drain_tasks(self, drain_s: float) -> None:
        async def _gather():
            tasks = [t for t in self._tasks if not t.done()]
            if tasks:
                await asyncio.wait(tasks, timeout=drain_s + 5.0)

        try:
            asyncio.run_coroutine_threadsafe(
                _gather(), self.loop
            ).result(timeout=drain_s + 10.0)
        except Exception as exc:  # noqa: BLE001
            _log.debug(
                "connection drain incomplete",
                extra=kv(loop=self.index, err=str(exc)),
            )

    def stop_loop(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.state = "stopped"

    def wedge(self, seconds: float) -> None:
        """Fault injection: stall THIS loop's thread with a busy-spin
        so the testgrid wedged_loop cell can prove the blast radius is
        one shard.  A spin, not a sleep: the point is an unresponsive
        loop, and the analysis gates rightly ban sleeps on loops.  The
        spin starts after a short grace so the admin response that
        scheduled it can flush even when its own connection is owned
        by the loop being wedged."""
        import time as _time

        def _spin():
            end = _time.monotonic() + seconds
            while _time.monotonic() < end:
                pass

        self.loop.call_soon_threadsafe(
            lambda: self.loop.call_later(0.2, _spin)
        )

    # -- connection handling ----------------------------------------------

    async def _serve_conn(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._tasks.add(task)
        self._conns.add(writer)
        try:
            first = True
            while not self.s3.draining:
                head = await self._read_head(reader, writer, first)
                if head is None:
                    return
                first = False
                if not await self._handle_one(reader, writer, head):
                    return
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass
        finally:
            self._conns.discard(writer)
            if task is not None:
                self._tasks.discard(task)
            try:
                writer.close()
            except Exception as exc:  # noqa: BLE001
                _log.debug(
                    "connection close failed", extra=kv(err=str(exc))
                )

    async def _read_head(self, reader, writer, first: bool):
        """One request head (bytes through the blank line), or None on
        EOF/timeout/oversize.  The timeout caps the WHOLE head — a
        slow-loris trickling header bytes gets 408, not a held slot."""
        timeout = self.header_timeout if first else self.idle_timeout
        try:
            return await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), timeout
            )
        except asyncio.TimeoutError:
            await self._reject(writer, 408, "RequestTimeout",
                               "request header read timed out")
            return None
        except asyncio.LimitOverrunError:
            await self._reject(writer, 431, "InvalidRequest",
                               "request header block too large")
            return None
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return None  # client went away

    async def _handle_one(self, reader, writer, head: bytes) -> bool:
        """Parse + admit + dispatch one request; False ends the
        connection (keep-alive otherwise)."""
        try:
            requestline, command, raw_path, version, headers = (
                _parse_head(head)
            )
        except ValueError as e:
            await self._reject(writer, 400, "InvalidRequest", str(e))
            return False
        parsed = urllib.parse.urlsplit(raw_path)
        upath = urllib.parse.unquote(parsed.path)
        query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)

        # -- admission stage (loop-side, before any body byte): the
        # per-loop fast path is this block — no locks; the only shared
        # state is the budget's atomic counters -------------------------
        shed_reason = None
        tenant = None
        if self._admitted_path(upath):
            if self.adm.quota_rejects_put(command, upath, headers):
                shed_reason = "quota"
            else:
                tenant = self.adm.tenant_of(headers)
                if not self.adm.try_enter_tenant(tenant):
                    shed_reason, tenant = "tenant", None
        if shed_reason is None and not self._enqueue_ok(
            command, upath, query
        ):
            shed_reason = "queue"
        if shed_reason is not None:
            if tenant is not None:
                self.adm.leave_tenant(tenant)
            self.lstats.shed_inc(shed_reason)
            self.s3.metrics.observe("Shed", 503, 0.0)
            await self._reject(
                writer, 503, "SlowDown",
                "Resource requested is unreadable, please reduce your "
                f"request rate ({shed_reason})",
            )
            return False

        # -- handler stage -------------------------------------------------
        h = self.plane.handler_cls.__new__(self.plane.handler_cls)
        h.command = command
        h.path = raw_path
        h.request_version = version
        h.requestline = requestline
        h.headers = headers
        h.client_address = writer.get_extra_info("peername") or ("", 0)
        h.close_connection = _wants_close(version, headers)
        h.rfile = _LoopReader(self, reader)
        h.wfile = _LoopWriter(self, writer)
        h._plane_admitted = True
        h._loop_index = self.index
        if (
            version >= "HTTP/1.1"
            and (headers.get("Expect") or "").lower() == "100-continue"
        ):
            h._expect_100_req = True

        done = self.loop.create_future()

        def _finish():
            if not done.done():
                done.set_result(None)

        def _work():
            try:
                h.route()
            except Exception as exc:  # noqa: BLE001 - connection-fatal only
                h.close_connection = True
                _log.debug("handler failed", extra=kv(err=str(exc)))
            finally:
                if tenant is not None:
                    self.adm.leave_tenant(tenant)
                self.loop.call_soon_threadsafe(_finish)

        if self._is_streaming(command, upath, query):
            self.pool.spawn_stream(_work)
        else:
            # reserved above by _enqueue_ok probing; enqueue for real
            if not self.pool.try_submit(_work):
                if tenant is not None:
                    self.adm.leave_tenant(tenant)
                self.lstats.shed_inc("queue")
                self.s3.metrics.observe("Shed", 503, 0.0)
                await self._reject(
                    writer, 503, "SlowDown",
                    "Resource requested is unreadable, please reduce "
                    "your request rate (queue)",
                )
                return False
        await done
        return not h.close_connection and not writer.is_closing()

    # -- helpers -----------------------------------------------------------

    def _admitted_path(self, upath: str) -> bool:
        """Paths subject to tenant/quota admission: the S3 plane only —
        internode, health, and metrics endpoints bypass it exactly like
        the global admission slot in route()."""
        for prefix in self.s3.internode:
            if upath.startswith(prefix + "/"):
                return False
        return not upath.startswith(
            ("/minio/health/", "/minio-tpu/prometheus/")
        )

    def _enqueue_ok(self, command: str, upath: str, query) -> bool:
        """Backlog headroom check before taking the tenant slot; the
        real enqueue happens after the shim is built."""
        if self._is_streaming(command, upath, query):
            return True
        return not self.pool._q.full()

    def _is_streaming(self, command: str, upath: str, query) -> bool:
        from . import admin as adminmod

        if upath.startswith(adminmod.PREFIX + "/"):
            tail = upath[len(adminmod.PREFIX) + 1 :]
            if tail in ("trace", "console"):
                return True
        return command == "GET" and "events" in query

    async def _reject(
        self, writer, status: int, code: str, message: str
    ) -> None:
        """Loop-side terminal response (shed / malformed head): S3 XML
        error document, Connection: close."""
        err = s3errors.get(code)
        body = xmlr.error_xml(
            err.code, message, "/", uuid.uuid4().hex[:16]
        )
        reason = {408: "Request Timeout", 431: "Headers Too Large",
                  503: "Slow Down"}.get(status, "Error")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            "Server: MinIO-TPU\r\n"
            "Content-Type: application/xml\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode("latin-1")
        try:
            writer.write(head + body)
            await writer.drain()
        except (ConnectionError, OSError):
            pass


def _parse_head(head: bytes):
    lines = head.split(b"\r\n", 1)
    try:
        requestline = lines[0].decode("latin-1")
    except UnicodeDecodeError:
        raise ValueError("bad request line") from None
    words = requestline.split()
    if len(words) != 3:
        raise ValueError("malformed request line")
    command, raw_path, version = words
    if not version.startswith("HTTP/"):
        raise ValueError("bad HTTP version")
    try:
        headers = _hclient.parse_headers(io.BytesIO(lines[1]))
    except Exception:  # noqa: BLE001
        raise ValueError("malformed headers") from None
    return requestline, command, raw_path, version, headers


def _wants_close(version: str, headers) -> bool:
    conn = (headers.get("Connection") or "").lower()
    if version <= "HTTP/1.0":
        return "keep-alive" not in conn
    return "close" in conn


class AsyncPlane:
    """N shared-nothing event loops + per-loop worker slices serving
    the S3 surface; this object is only the boot/teardown coordinator
    and observability roll-up — no request ever runs through it."""

    def __init__(self, server):
        self.s3 = server
        self.stats = server.plane_stats
        self.adm = server.admission
        self.header_timeout = _env_float("MINIO_TPU_HEADER_TIMEOUT_S", 30.0)
        self.body_timeout = _env_float("MINIO_TPU_BODY_TIMEOUT_S", 60.0)
        self.idle_timeout = _env_float("MINIO_TPU_IDLE_TIMEOUT_S", 60.0)
        n = _loop_count()
        workers = _env_int("MINIO_TPU_SERVER_WORKERS", _default_workers())
        backlog = _env_int("MINIO_TPU_SERVER_BACKLOG", 64)
        self.loops = [
            _ServerLoop(self, i, w, b)
            for i, (w, b) in enumerate(
                zip(_split(workers, n), _split(backlog, n))
            )
        ]
        self.handler_cls = None
        self.reuseport = False
        self._accept_sock: "socket.socket | None" = None
        self._accept_task = None
        self._ssl_ctx = None
        self._rr = 0
        self._stopped = False
        self.port = 0
        # aggregate stage gauges keep the single-loop scrape shape;
        # the per-loop breakdown rides the LoopStats cells
        self.stats.register_stage(
            "parse", lambda: sum(len(sl._conns) for sl in self.loops)
        )
        self.stats.register_stage(
            "handler", lambda: sum(sl.pool.depth() for sl in self.loops)
        )

    # -- compatibility aliases (single-loop callers/tests) ----------------

    @property
    def loop(self):
        return self.loops[0].loop

    @property
    def pool(self):
        return self.loops[0].pool

    # -- lifecycle --------------------------------------------------------

    def start(self, handler_cls, host: str, port: int, ssl_ctx=None):
        self.handler_cls = handler_cls
        self._handler_cls = handler_cls  # legacy alias
        self._ssl_ctx = ssl_ctx
        for sl in self.loops:
            sl.start_thread()
        if len(self.loops) == 1:
            # today's plane verbatim: one asyncio.start_server listener
            self.loops[0].serve(host, port, None, ssl_ctx)
            self.port = self.loops[0].bound_port()
            return self
        if _reuseport_requested() and hasattr(socket, "SO_REUSEPORT"):
            try:
                self._start_reuseport(host, port, ssl_ctx)
                return self
            except OSError as exc:
                _log.info(
                    "SO_REUSEPORT shard bind failed; using handoff",
                    extra=kv(err=str(exc)),
                )
        self._start_handoff(host, port, ssl_ctx)
        return self

    def _bind_socket(self, host, port, reuseport: bool) -> socket.socket:
        infos = socket.getaddrinfo(
            host or None, port, type=socket.SOCK_STREAM,
            flags=socket.AI_PASSIVE,
        )
        family, stype, proto, _, addr = infos[0]
        s = socket.socket(family, stype, proto)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuseport:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind(addr[:2] if family == socket.AF_INET else addr)
            s.listen(_LISTEN_BACKLOG)
        except OSError:
            s.close()
            raise
        return s

    def _start_reuseport(self, host, port, ssl_ctx) -> None:
        """One bound SO_REUSEPORT socket per loop; the kernel spreads
        accepts across them (the reference's goroutine-per-listener
        served by Go's netpoller gets this for free)."""
        socks: "list[socket.socket]" = []
        bound = port
        try:
            for _ in self.loops:
                s = self._bind_socket(host, bound, reuseport=True)
                if bound == 0:
                    bound = s.getsockname()[1]
                socks.append(s)
        except OSError:
            for s in socks:
                s.close()
            raise
        for sl, s in zip(self.loops, socks):
            sl.serve(None, None, s, ssl_ctx)
        self.reuseport = True
        self.port = bound or self.loops[0].bound_port()

    def _start_handoff(self, host, port, ssl_ctx) -> None:
        """Fallback sharding: one listener, accepted sockets handed to
        loops round-robin.  Accept throughput stays single-loop but
        parse/serve still shard."""
        lsock = self._bind_socket(host, port, reuseport=False)
        self._accept_sock = lsock
        self.port = lsock.getsockname()[1]
        for sl in self.loops:
            sl.mark_serving()
        acceptor = self.loops[0]

        async def _accept_forever():
            lsock.setblocking(False)
            while True:
                try:
                    conn, _addr = await acceptor.loop.sock_accept(lsock)
                except (asyncio.CancelledError, OSError):
                    return
                target = self.loops[self._rr % len(self.loops)]
                self._rr += 1
                asyncio.run_coroutine_threadsafe(
                    target._adopt(conn, ssl_ctx), target.loop
                )

        def _spawn():
            task = acceptor.loop.create_task(_accept_forever())
            self._accept_task = task
            acceptor._tasks.add(task)

        acceptor.loop.call_soon_threadsafe(_spawn)

    def stop(self, drain_s: float = 10.0) -> None:
        import time as _time

        if self._stopped or self.loops[0].loop.is_closed():
            return
        self._stopped = True
        # 1. stop accepting on every loop
        for sl in self.loops:
            sl.close_listener()
        if self._accept_sock is not None:
            # cancel the handoff acceptor ON its loop (a cross-thread
            # socket close would leave sock_accept parked in the
            # selector), then close the listening socket there too
            acceptor, lsock = self.loops[0], self._accept_sock

            def _stop_accept():
                if self._accept_task is not None:
                    self._accept_task.cancel()
                try:
                    lsock.close()
                except OSError:
                    pass

            acceptor.loop.call_soon_threadsafe(_stop_accept)
        # 2. drain in-flight requests (admitted -> released in route())
        deadline = _time.monotonic() + drain_s
        while (
            self.stats.snapshot()["inflight"] > 0
            and _time.monotonic() < deadline
        ):
            _time.sleep(0.05)
        # 3. cut survivors and collect per-connection tasks, loop by loop
        for sl in self.loops:
            sl.cut_conns()
        for sl in self.loops:
            sl.drain_tasks(drain_s)
        # 4. retire worker slices, then the loops themselves
        for sl in self.loops:
            sl.pool.shutdown()
        for sl in self.loops:
            sl.stop_loop()

    # -- observability / fault injection ----------------------------------

    def loops_ready(self) -> bool:
        return all(sl.state == "serving" for sl in self.loops)

    def describe(self) -> dict:
        """healthinfo/readiness block: one row per loop."""
        return {
            "count": len(self.loops),
            "reuseport": self.reuseport,
            "per_loop": [
                {
                    "loop": sl.index,
                    "state": sl.state,
                    "connections": len(sl._conns),
                    "inflight": sl.lstats.inflight(),
                    "workers": sl.pool.workers,
                    "handler_depth": sl.pool.depth(),
                    "shed": dict(sl.lstats.shed),
                }
                for sl in self.loops
            ],
        }

    def wedge_loop(self, index: int, seconds: float) -> bool:
        """Stall one loop (fault injection; see _ServerLoop.wedge)."""
        if not 0 <= index < len(self.loops):
            return False
        self.loops[index].wedge(seconds)
        return True
