"""Asyncio request plane (ROADMAP item 4; MINIO_TPU_SERVER=async).

The reference serves thousands of connections on goroutines behind its
custom L7 listener (cmd/http/server.go); a thread-per-request stdlib
server on a GIL cannot do that — at 32 clients every blocked thread
competes for the interpreter and p99 collapses.  This plane keeps ONE
event-loop thread owning every socket and a small bounded worker pool
running the existing synchronous handlers, so concurrency costs a queue
slot instead of a thread:

    accept -> [parse: loop] -> [admission: loop] -> [handler: bounded
    pool] -> [codec/disk: parallel/iopool.py] -> response via loop

Stage boundaries are explicit queues with backpressure; when the
handler backlog is full the request is shed with 503 SlowDown *before*
any body byte is read (server/admission.py).  The handlers themselves
are unchanged — ``_Handler.route()`` runs on a worker thread over two
thin bridges:

``_LoopReader``
    Blocking file-like over the connection's ``asyncio.StreamReader``.
    Each ``read(n)`` is one ``run_coroutine_threadsafe`` round-trip, so
    a PUT body streams chunk-by-chunk from the loop straight into
    ``HashReader`` -> ``encode_begin`` with bounded memory — the loop
    never holds a full body and the worker never touches the socket.

``_LoopWriter``
    Blocking writes through ``transport.write`` + ``drain()``.  A
    ``memoryview`` passes to the transport unjoined (zero-copy GET: the
    decoded block slices the iopool assembles go to the socket without
    intermediate ``b"".join``); blocking the worker until the loop has
    consumed the buffer makes caller-side buffer reuse safe and gives
    natural per-connection flow control.

Long-lived streaming endpoints (admin trace/console, bucket event
listen) would starve a bounded pool, so they run on dedicated threads.
The threaded plane stays available as the bisection oracle
(``MINIO_TPU_SERVER=threaded``, house style of MINIO_TPU_PARITY_PLANE).

Blocking calls inside ``async def`` bodies here are a correctness bug
(one stalled coroutine stalls every connection): MTPU108 in
minio_tpu/analysis lints for them; the bridges above are sync-side by
construction.
"""

from __future__ import annotations

import asyncio
import io
import os
import queue
import socket
import threading
import urllib.parse
import uuid
from http import client as _hclient

from . import s3errors
from . import response as xmlr
from ..utils.log import kv, logger

_log = logger("aio")

# header-block cap, matching the stdlib server's per-line ceiling
_MAX_HEAD = 1 << 16


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name) or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name) or default)
    except ValueError:
        return default


def _default_workers() -> int:
    """A few blocking-I/O slots per core, capped.  More workers than
    this just interleaves CPU-bound codec work (GIL thrash) and
    inflates p99 without adding throughput."""
    return min(16, max(4, 4 * (os.cpu_count() or 1)))


class _LoopReader:
    """Synchronous file-like over the loop's StreamReader, used by the
    handler thread.  Every call blocks the *worker*, never the loop."""

    def __init__(self, plane: "AsyncPlane", reader: asyncio.StreamReader):
        self._plane = plane
        self._reader = reader

    def _call(self, coro):
        try:
            fut = asyncio.run_coroutine_threadsafe(coro, self._plane.loop)
            return fut.result()
        except asyncio.TimeoutError:
            raise socket.timeout("body read timed out") from None
        except (RuntimeError, ConnectionError, asyncio.CancelledError) as e:
            raise OSError(f"connection lost: {e}") from None

    def read(self, n: int = -1) -> bytes:
        timeout = self._plane.body_timeout

        async def _rd():
            return await asyncio.wait_for(self._reader.read(n), timeout)

        return self._call(_rd())

    def readline(self, limit: int = -1) -> bytes:
        """Bounded line read (internode chunked framing uses 1024)."""
        timeout = self._plane.body_timeout
        reader = self._reader

        async def _rl():
            out = bytearray()
            while limit < 0 or len(out) < limit:
                b = await asyncio.wait_for(reader.read(1), timeout)
                if not b:
                    break
                out += b
                if b == b"\n":
                    break
            return bytes(out)

        return self._call(_rl())


class _LoopWriter:
    """Synchronous writes through the loop's transport.

    ``write`` hands the buffer (bytes or memoryview — unjoined) to
    ``transport.write`` on the loop and blocks the worker through
    ``drain()``, so a slow client backpressures its own worker instead
    of growing an unbounded transport buffer."""

    def __init__(self, plane: "AsyncPlane", writer: asyncio.StreamWriter):
        self._plane = plane
        self._writer = writer

    def write(self, data) -> int:
        n = len(data)
        if n == 0:
            return 0
        writer = self._writer

        async def _wr():
            writer.write(data)
            await writer.drain()

        try:
            asyncio.run_coroutine_threadsafe(
                _wr(), self._plane.loop
            ).result()
        except (RuntimeError, ConnectionError, asyncio.CancelledError) as e:
            raise OSError(f"connection lost: {e}") from None
        return n

    def flush(self) -> None:  # writes are already synchronous
        pass


class _WorkerPool:
    """Bounded handler stage: a full backlog means shed, not queue."""

    def __init__(self, workers: int, backlog: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, backlog))
        self._threads = [
            threading.Thread(
                target=self._run, name=f"aio-worker-{i}", daemon=True
            )
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()
        self._streams: "set[threading.Thread]" = set()
        self._streams_mu = threading.Lock()
        self._stream_seq = 0

    def depth(self) -> int:
        return self._q.qsize()

    def try_submit(self, fn) -> bool:
        try:
            self._q.put_nowait(fn)
            return True
        except queue.Full:
            return False

    def spawn_stream(self, fn) -> None:
        """Long-lived streaming request: dedicated thread so it cannot
        starve the bounded pool (trace/console/listen endpoints)."""
        with self._streams_mu:
            self._stream_seq += 1
            name = f"aio-stream-{self._stream_seq}"
        t = threading.Thread(
            target=self._run_stream, args=(fn,), name=name, daemon=True
        )
        with self._streams_mu:
            self._streams.add(t)
        t.start()

    def _run_stream(self, fn) -> None:
        try:
            fn()
        except Exception as exc:  # noqa: BLE001
            _log.debug("stream handler failed", extra=kv(err=str(exc)))
        finally:
            with self._streams_mu:
                self._streams.discard(threading.current_thread())

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception as exc:  # noqa: BLE001
                _log.debug("handler job failed", extra=kv(err=str(exc)))

    def shutdown(self, timeout: float = 10.0) -> None:
        for _ in self._threads:
            try:
                self._q.put(None, timeout=timeout)
            except queue.Full:
                break
        for t in self._threads:
            t.join(timeout)
        with self._streams_mu:
            streams = list(self._streams)
        for t in streams:
            t.join(timeout)


class AsyncPlane:
    """One event loop + bounded worker pool serving the S3 surface."""

    def __init__(self, server):
        self.s3 = server
        self.stats = server.plane_stats
        self.adm = server.admission
        self.loop = asyncio.new_event_loop()
        self.header_timeout = _env_float("MINIO_TPU_HEADER_TIMEOUT_S", 30.0)
        self.body_timeout = _env_float("MINIO_TPU_BODY_TIMEOUT_S", 60.0)
        self.idle_timeout = _env_float("MINIO_TPU_IDLE_TIMEOUT_S", 60.0)
        self.pool = _WorkerPool(
            _env_int("MINIO_TPU_SERVER_WORKERS", _default_workers()),
            _env_int("MINIO_TPU_SERVER_BACKLOG", 64),
        )
        self._conns: "set[asyncio.StreamWriter]" = set()
        self._tasks: "set[asyncio.Task]" = set()
        self._srv = None
        self._thread: "threading.Thread | None" = None
        self._handler_cls = None
        self._stopped = False
        self.port = 0
        self.stats.register_stage("parse", lambda: len(self._conns))
        self.stats.register_stage("handler", self.pool.depth)

    # -- lifecycle --------------------------------------------------------

    def start(self, handler_cls, host: str, port: int, ssl_ctx=None):
        self._handler_cls = handler_cls
        self._thread = threading.Thread(
            target=self._run_loop, name="aio-loop", daemon=True
        )
        self._thread.start()

        async def _boot():
            return await asyncio.start_server(
                self._serve_conn, host, port, ssl=ssl_ctx, limit=_MAX_HEAD
            )

        self._srv = asyncio.run_coroutine_threadsafe(
            _boot(), self.loop
        ).result(timeout=30)
        self.port = self._srv.sockets[0].getsockname()[1]
        return self

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self.loop)
        try:
            self.loop.run_forever()
        finally:
            try:
                self.loop.close()
            except Exception as exc:  # noqa: BLE001
                _log.debug("loop close failed", extra=kv(err=str(exc)))

    def stop(self, drain_s: float = 10.0) -> None:
        import time as _time

        if self._stopped or self.loop.is_closed():
            return
        self._stopped = True
        if self._srv is not None:
            self.loop.call_soon_threadsafe(self._srv.close)
        # drain in-flight requests (admitted -> released in route())
        deadline = _time.monotonic() + drain_s
        while (
            self.stats.snapshot()["inflight"] > 0
            and _time.monotonic() < deadline
        ):
            _time.sleep(0.05)
        # cut remaining connections while the loop still runs: pending
        # bridge reads/writes fail fast and unblock their workers
        def _cut():
            for w in list(self._conns):
                try:
                    w.close()
                except Exception as exc:  # noqa: BLE001
                    _log.debug(
                        "transport close failed", extra=kv(err=str(exc))
                    )

        self.loop.call_soon_threadsafe(_cut)

        async def _gather():
            tasks = [t for t in self._tasks if not t.done()]
            if tasks:
                await asyncio.wait(tasks, timeout=drain_s + 5.0)

        try:
            asyncio.run_coroutine_threadsafe(
                _gather(), self.loop
            ).result(timeout=drain_s + 10.0)
        except Exception as exc:  # noqa: BLE001
            _log.debug("connection drain incomplete", extra=kv(err=str(exc)))
        self.pool.shutdown()
        self.loop.call_soon_threadsafe(self.loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- connection handling ----------------------------------------------

    async def _serve_conn(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._tasks.add(task)
        self._conns.add(writer)
        try:
            first = True
            while not self.s3.draining:
                head = await self._read_head(reader, writer, first)
                if head is None:
                    return
                first = False
                if not await self._handle_one(reader, writer, head):
                    return
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass
        finally:
            self._conns.discard(writer)
            if task is not None:
                self._tasks.discard(task)
            try:
                writer.close()
            except Exception as exc:  # noqa: BLE001
                _log.debug(
                    "connection close failed", extra=kv(err=str(exc))
                )

    async def _read_head(self, reader, writer, first: bool):
        """One request head (bytes through the blank line), or None on
        EOF/timeout/oversize.  The timeout caps the WHOLE head — a
        slow-loris trickling header bytes gets 408, not a held slot."""
        timeout = self.header_timeout if first else self.idle_timeout
        try:
            return await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), timeout
            )
        except asyncio.TimeoutError:
            await self._reject(writer, 408, "RequestTimeout",
                               "request header read timed out")
            return None
        except asyncio.LimitOverrunError:
            await self._reject(writer, 431, "InvalidRequest",
                               "request header block too large")
            return None
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return None  # client went away

    async def _handle_one(self, reader, writer, head: bytes) -> bool:
        """Parse + admit + dispatch one request; False ends the
        connection (keep-alive otherwise)."""
        try:
            requestline, command, raw_path, version, headers = (
                self._parse_head(head)
            )
        except ValueError as e:
            await self._reject(writer, 400, "InvalidRequest", str(e))
            return False
        parsed = urllib.parse.urlsplit(raw_path)
        upath = urllib.parse.unquote(parsed.path)
        query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)

        # -- admission stage (loop-side, before any body byte) ------------
        shed_reason = None
        tenant = None
        if self._admitted_path(upath):
            if self.adm.quota_rejects_put(command, upath, headers):
                shed_reason = "quota"
            else:
                tenant = self.adm.tenant_of(headers)
                if not self.adm.try_enter_tenant(tenant):
                    shed_reason, tenant = "tenant", None
        if shed_reason is None and not self._enqueue_ok(
            command, upath, query
        ):
            shed_reason = "queue"
        if shed_reason is not None:
            if tenant is not None:
                self.adm.leave_tenant(tenant)
            self.stats.shed_inc(shed_reason)
            self.s3.metrics.observe("Shed", 503, 0.0)
            await self._reject(
                writer, 503, "SlowDown",
                "Resource requested is unreadable, please reduce your "
                f"request rate ({shed_reason})",
            )
            return False

        # -- handler stage -------------------------------------------------
        h = self._handler_cls.__new__(self._handler_cls)
        h.command = command
        h.path = raw_path
        h.request_version = version
        h.requestline = requestline
        h.headers = headers
        h.client_address = writer.get_extra_info("peername") or ("", 0)
        h.close_connection = self._wants_close(version, headers)
        h.rfile = _LoopReader(self, reader)
        h.wfile = _LoopWriter(self, writer)
        h._plane_admitted = True
        if (
            version >= "HTTP/1.1"
            and (headers.get("Expect") or "").lower() == "100-continue"
        ):
            h._expect_100_req = True

        done = self.loop.create_future()

        def _finish():
            if not done.done():
                done.set_result(None)

        def _work():
            try:
                h.route()
            except Exception as exc:  # noqa: BLE001 - connection-fatal only
                h.close_connection = True
                _log.debug("handler failed", extra=kv(err=str(exc)))
            finally:
                if tenant is not None:
                    self.adm.leave_tenant(tenant)
                self.loop.call_soon_threadsafe(_finish)

        if self._is_streaming(command, upath, query):
            self.pool.spawn_stream(_work)
        else:
            # reserved above by _enqueue_ok probing; enqueue for real
            if not self.pool.try_submit(_work):
                if tenant is not None:
                    self.adm.leave_tenant(tenant)
                self.stats.shed_inc("queue")
                self.s3.metrics.observe("Shed", 503, 0.0)
                await self._reject(
                    writer, 503, "SlowDown",
                    "Resource requested is unreadable, please reduce "
                    "your request rate (queue)",
                )
                return False
        await done
        return not h.close_connection and not writer.is_closing()

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _parse_head(head: bytes):
        lines = head.split(b"\r\n", 1)
        try:
            requestline = lines[0].decode("latin-1")
        except UnicodeDecodeError:
            raise ValueError("bad request line") from None
        words = requestline.split()
        if len(words) != 3:
            raise ValueError("malformed request line")
        command, raw_path, version = words
        if not version.startswith("HTTP/"):
            raise ValueError("bad HTTP version")
        try:
            headers = _hclient.parse_headers(io.BytesIO(lines[1]))
        except Exception:  # noqa: BLE001
            raise ValueError("malformed headers") from None
        return requestline, command, raw_path, version, headers

    @staticmethod
    def _wants_close(version: str, headers) -> bool:
        conn = (headers.get("Connection") or "").lower()
        if version <= "HTTP/1.0":
            return "keep-alive" not in conn
        return "close" in conn

    def _admitted_path(self, upath: str) -> bool:
        """Paths subject to tenant/quota admission: the S3 plane only —
        internode, health, and metrics endpoints bypass it exactly like
        the global admission slot in route()."""
        for prefix in self.s3.internode:
            if upath.startswith(prefix + "/"):
                return False
        return not upath.startswith(
            ("/minio/health/", "/minio-tpu/prometheus/")
        )

    def _enqueue_ok(self, command: str, upath: str, query) -> bool:
        """Backlog headroom check before taking the tenant slot; the
        real enqueue happens after the shim is built."""
        if self._is_streaming(command, upath, query):
            return True
        return not self._q_full()

    def _q_full(self) -> bool:
        return self.pool._q.full()

    def _is_streaming(self, command: str, upath: str, query) -> bool:
        from . import admin as adminmod

        if upath.startswith(adminmod.PREFIX + "/"):
            tail = upath[len(adminmod.PREFIX) + 1 :]
            if tail in ("trace", "console"):
                return True
        return command == "GET" and "events" in query

    async def _reject(
        self, writer, status: int, code: str, message: str
    ) -> None:
        """Loop-side terminal response (shed / malformed head): S3 XML
        error document, Connection: close."""
        err = s3errors.get(code)
        body = xmlr.error_xml(
            err.code, message, "/", uuid.uuid4().hex[:16]
        )
        reason = {408: "Request Timeout", 431: "Headers Too Large",
                  503: "Slow Down"}.get(status, "Error")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            "Server: MinIO-TPU\r\n"
            "Content-Type: application/xml\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode("latin-1")
        try:
            writer.write(head + body)
            await writer.drain()
        except (ConnectionError, OSError):
            pass
