"""S3 XML response rendering (cmd/api-response.go).

Hand-built with xml.etree: responses are small and schema-fixed; the S3
namespace is applied on the root element like encodeResponse.
"""

from __future__ import annotations

import datetime
import urllib.parse
import xml.etree.ElementTree as ET

S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _el(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _render(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(
        root, encoding="unicode"
    ).encode()


def _iso(ns: int) -> str:
    return (
        datetime.datetime.fromtimestamp(
            ns / 1e9, tz=datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3]
        + "Z"
    )


def error_xml(
    code: str, message: str, resource: str, request_id: str
) -> bytes:
    root = ET.Element("Error")
    _el(root, "Code", code)
    _el(root, "Message", message)
    _el(root, "Resource", resource)
    _el(root, "RequestId", request_id)
    _el(root, "HostId", "minio-tpu")
    return _render(root)


def list_buckets_xml(buckets, owner="minio") -> bytes:
    root = ET.Element(
        "ListAllMyBucketsResult", xmlns=S3_NS
    )
    own = _el(root, "Owner")
    _el(own, "ID", owner)
    _el(own, "DisplayName", owner)
    bs = _el(root, "Buckets")
    for b in buckets:
        be = _el(bs, "Bucket")
        _el(be, "Name", b.name)
        _el(be, "CreationDate", _iso(b.created_ns))
    return _render(root)


def _obj_entry(parent, o, encode: bool):
    c = _el(parent, "Contents")
    _el(c, "Key", _maybe_encode(o.name, encode))
    _el(c, "LastModified", _iso(o.mod_time_ns))
    _el(c, "ETag", f'"{o.etag}"')
    _el(c, "Size", o.size)
    _el(c, "StorageClass", "STANDARD")


def _maybe_encode(s: str, encode: bool) -> str:
    return urllib.parse.quote(s) if encode else s


def list_objects_v1_xml(
    bucket, prefix, marker, delimiter, max_keys, result, encode: bool
) -> bytes:
    root = ET.Element("ListBucketResult", xmlns=S3_NS)
    _el(root, "Name", bucket)
    _el(root, "Prefix", _maybe_encode(prefix, encode))
    _el(root, "Marker", _maybe_encode(marker, encode))
    _el(root, "MaxKeys", max_keys)
    if delimiter:
        _el(root, "Delimiter", _maybe_encode(delimiter, encode))
    _el(root, "IsTruncated", "true" if result.is_truncated else "false")
    if result.is_truncated and result.next_marker:
        _el(root, "NextMarker", _maybe_encode(result.next_marker, encode))
    for o in result.objects:
        _obj_entry(root, o, encode)
    for p in result.prefixes:
        cp = _el(root, "CommonPrefixes")
        _el(cp, "Prefix", _maybe_encode(p, encode))
    return _render(root)


def list_objects_v2_xml(
    bucket, prefix, delimiter, max_keys, start_after,
    continuation_token, result, encode: bool,
) -> bytes:
    root = ET.Element("ListBucketResult", xmlns=S3_NS)
    _el(root, "Name", bucket)
    _el(root, "Prefix", _maybe_encode(prefix, encode))
    _el(root, "MaxKeys", max_keys)
    if delimiter:
        _el(root, "Delimiter", _maybe_encode(delimiter, encode))
    _el(root, "KeyCount", len(result.objects) + len(result.prefixes))
    if start_after:
        _el(root, "StartAfter", _maybe_encode(start_after, encode))
    if continuation_token:
        _el(root, "ContinuationToken", continuation_token)
    _el(root, "IsTruncated", "true" if result.is_truncated else "false")
    if result.is_truncated and result.next_marker:
        import base64

        _el(
            root,
            "NextContinuationToken",
            base64.urlsafe_b64encode(
                result.next_marker.encode()
            ).decode(),
        )
    for o in result.objects:
        _obj_entry(root, o, encode)
    for p in result.prefixes:
        cp = _el(root, "CommonPrefixes")
        _el(cp, "Prefix", _maybe_encode(p, encode))
    return _render(root)


def location_xml(region: str = "") -> bytes:
    root = ET.Element("LocationConstraint", xmlns=S3_NS)
    root.text = region
    return _render(root)


def initiate_multipart_xml(bucket, key, upload_id) -> bytes:
    root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_NS)
    _el(root, "Bucket", bucket)
    _el(root, "Key", key)
    _el(root, "UploadId", upload_id)
    return _render(root)


def complete_multipart_xml(location, bucket, key, etag) -> bytes:
    root = ET.Element("CompleteMultipartUploadResult", xmlns=S3_NS)
    _el(root, "Location", location)
    _el(root, "Bucket", bucket)
    _el(root, "Key", key)
    _el(root, "ETag", f'"{etag}"')
    return _render(root)


def list_parts_xml(bucket, key, upload_id, parts) -> bytes:
    root = ET.Element("ListPartsResult", xmlns=S3_NS)
    _el(root, "Bucket", bucket)
    _el(root, "Key", key)
    _el(root, "UploadId", upload_id)
    _el(root, "StorageClass", "STANDARD")
    _el(root, "IsTruncated", "false")
    for p in parts:
        pe = _el(root, "Part")
        _el(pe, "PartNumber", p.part_number)
        _el(pe, "LastModified", _iso(p.mod_time_ns))
        _el(pe, "ETag", f'"{p.etag}"')
        _el(pe, "Size", p.size)
    return _render(root)


def list_uploads_xml(bucket, uploads) -> bytes:
    root = ET.Element("ListMultipartUploadsResult", xmlns=S3_NS)
    _el(root, "Bucket", bucket)
    _el(root, "IsTruncated", "false")
    for u in uploads:
        ue = _el(root, "Upload")
        _el(ue, "Key", u.object)
        _el(ue, "UploadId", u.upload_id)
        _el(ue, "StorageClass", "STANDARD")
        _el(ue, "Initiated", _iso(u.initiated_ns))
    return _render(root)


def copy_object_xml(etag, mod_time_ns) -> bytes:
    root = ET.Element("CopyObjectResult", xmlns=S3_NS)
    _el(root, "LastModified", _iso(mod_time_ns))
    _el(root, "ETag", f'"{etag}"')
    return _render(root)


def copy_part_xml(etag, mod_time_ns) -> bytes:
    """UploadPartCopy response (CopyObjectPartResponse)."""
    root = ET.Element("CopyPartResult", xmlns=S3_NS)
    _el(root, "LastModified", _iso(mod_time_ns))
    _el(root, "ETag", f'"{etag}"')
    return _render(root)


def delete_result_xml(deleted: list[str], errors: list[tuple]) -> bytes:
    root = ET.Element("DeleteResult", xmlns=S3_NS)
    for key in deleted:
        de = _el(root, "Deleted")
        _el(de, "Key", key)
    for key, code, msg in errors:
        ee = _el(root, "Error")
        _el(ee, "Key", key)
        _el(ee, "Code", code)
        _el(ee, "Message", msg)
    return _render(root)


def post_response_xml(location, bucket, key, etag) -> bytes:
    """201 body for POST policy uploads with success_action_status=201."""
    root = ET.Element("PostResponse")
    _el(root, "Location", location)
    _el(root, "Bucket", bucket)
    _el(root, "Key", key)
    _el(root, "ETag", f'"{etag}"')
    return _render(root)


def list_versions_xml(
    bucket, prefix, key_marker, version_id_marker, delimiter,
    max_keys, res, encode: bool = False,
) -> bytes:
    """ListVersionsResult: Version + DeleteMarker entries
    (generateListVersionsResponse, cmd/api-response.go)."""
    root = ET.Element("ListVersionsResult", xmlns=S3_NS)
    _el(root, "Name", bucket)
    _el(root, "Prefix", _maybe_encode(prefix, encode))
    _el(root, "KeyMarker", _maybe_encode(key_marker, encode))
    if version_id_marker:
        _el(root, "VersionIdMarker", version_id_marker)
    if delimiter:
        _el(root, "Delimiter", _maybe_encode(delimiter, encode))
    _el(root, "MaxKeys", max_keys)
    _el(root, "IsTruncated", "true" if res.is_truncated else "false")
    if res.is_truncated:
        _el(root, "NextKeyMarker", _maybe_encode(res.next_key_marker, encode))
        _el(root, "NextVersionIdMarker", res.next_version_id_marker)
    for o in res.versions:
        tag = "DeleteMarker" if o.delete_marker else "Version"
        ve = _el(root, tag)
        _el(ve, "Key", _maybe_encode(o.name, encode))
        _el(ve, "VersionId", o.version_id or "null")
        _el(ve, "IsLatest", "true" if o.is_latest else "false")
        _el(ve, "LastModified", _iso(o.mod_time_ns))
        if not o.delete_marker:
            _el(ve, "ETag", f'"{o.etag}"')
            _el(ve, "Size", o.size)
            _el(ve, "StorageClass", "STANDARD")
        own = _el(ve, "Owner")
        _el(own, "ID", "minio")
        _el(own, "DisplayName", "minio")
    for p in res.prefixes:
        cp = _el(root, "CommonPrefixes")
        _el(cp, "Prefix", _maybe_encode(p, encode))
    return _render(root)
