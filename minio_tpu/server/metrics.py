"""Prometheus metrics (cmd/metrics.go:66-507).

A process-local registry fed by the request middleware plus live
gauges scraped from the object layer (per-disk usage) and the heal
routine, rendered in the Prometheus text exposition format at
``/minio-tpu/prometheus/metrics``.
"""

from __future__ import annotations

import threading
import time

START_TIME = time.time()


class Metrics:
    """Thread-safe counters for the serving path."""

    def __init__(self):
        self._mu = threading.Lock()
        # (api, code) -> count
        self.requests: "dict[tuple[str, str], int]" = {}
        # api -> [count, total_seconds]
        self.latency: "dict[str, list]" = {}
        self.bytes_rx = 0
        self.bytes_tx = 0

    def observe(
        self,
        api: str,
        code: int,
        seconds: float,
        bytes_in: int = 0,
        bytes_out: int = 0,
    ) -> None:
        with self._mu:
            key = (api, str(code))
            self.requests[key] = self.requests.get(key, 0) + 1
            lat = self.latency.setdefault(api, [0, 0.0])
            lat[0] += 1
            lat[1] += seconds
            self.bytes_rx += bytes_in
            self.bytes_tx += bytes_out

    # -- rendering --------------------------------------------------------

    def render(self, object_layer=None, heal=None, queue=None) -> bytes:
        """The exposition document; live gauges are sampled now."""
        out: list[str] = []

        def emit(name, mtype, help_, samples):
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                lbl = (
                    "{"
                    + ",".join(f'{k}="{v}"' for k, v in labels.items())
                    + "}"
                    if labels
                    else ""
                )
                out.append(f"{name}{lbl} {value}")

        with self._mu:
            reqs = dict(self.requests)
            lat = {k: list(v) for k, v in self.latency.items()}
            rx, tx = self.bytes_rx, self.bytes_tx

        emit(
            "miniotpu_s3_requests_total",
            "counter",
            "S3 requests by API and HTTP code",
            [
                ({"api": api, "code": code}, n)
                for (api, code), n in sorted(reqs.items())
            ],
        )
        emit(
            "miniotpu_s3_request_seconds_total",
            "counter",
            "Cumulative request wall time by API",
            [
                ({"api": api}, f"{total:.6f}")
                for api, (_n, total) in sorted(lat.items())
            ],
        )
        emit(
            "miniotpu_s3_request_seconds_count",
            "counter",
            "Requests counted toward request_seconds by API",
            [({"api": api}, n) for api, (n, _t) in sorted(lat.items())],
        )
        emit(
            "miniotpu_s3_rx_bytes_total", "counter",
            "Bytes received from S3 clients", [({}, rx)],
        )
        emit(
            "miniotpu_s3_tx_bytes_total", "counter",
            "Bytes sent to S3 clients", [({}, tx)],
        )
        emit(
            "miniotpu_process_uptime_seconds", "gauge",
            "Seconds since process start",
            [({}, f"{time.time() - START_TIME:.1f}")],
        )

        if object_layer is not None:
            disks, usage = _disk_samples(object_layer)
            emit(
                "miniotpu_disks_total", "gauge",
                "Configured disks", [({}, disks[0])],
            )
            emit(
                "miniotpu_disks_offline", "gauge",
                "Offline disks", [({}, disks[1])],
            )
            emit(
                "miniotpu_disk_storage_used_bytes", "gauge",
                "Used bytes per disk",
                [({"disk": ep}, u) for ep, (u, _f, _t) in usage],
            )
            emit(
                "miniotpu_disk_storage_available_bytes", "gauge",
                "Free bytes per disk",
                [({"disk": ep}, f) for ep, (_u, f, _t) in usage],
            )
            emit(
                "miniotpu_disk_storage_total_bytes", "gauge",
                "Capacity per disk",
                [({"disk": ep}, t) for ep, (_u, _f, t) in usage],
            )
        if heal is not None:
            emit(
                "miniotpu_heal_objects_healed_total", "counter",
                "Objects healed by the background routine",
                [({}, heal.healed)],
            )
            emit(
                "miniotpu_heal_objects_failed_total", "counter",
                "Background heals that failed",
                [({}, heal.failed)],
            )
        if queue is not None:
            emit(
                "miniotpu_heal_queue_depth", "gauge",
                "Tasks waiting in the heal queue",
                [({}, len(queue))],
            )
        return ("\n".join(out) + "\n").encode()


def _iter_disks(object_layer):
    zones = getattr(object_layer, "zones", None)
    if zones is not None:
        for z in zones:
            yield from _iter_disks(z)
        return
    sets = getattr(object_layer, "sets", None)
    if sets is not None:
        for s in sets:
            yield from _iter_disks(s)
        return
    yield from getattr(object_layer, "disks", [])


def _disk_samples(object_layer):
    total = offline = 0
    usage = []
    for d in _iter_disks(object_layer):
        total += 1
        if d is None or not _safe_online(d):
            offline += 1
            continue
        try:
            info = d.disk_info()
            usage.append(
                (info.endpoint, (info.used, info.free, info.total))
            )
        except Exception:  # noqa: BLE001
            offline += 1
    return (total, offline), usage


def _safe_online(d) -> bool:
    try:
        return d.is_online()
    except Exception:  # noqa: BLE001
        return False
