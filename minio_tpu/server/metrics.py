"""Prometheus metrics (cmd/metrics.go:66-507).

A process-local registry fed by the request middleware plus live
gauges scraped from the object layer (per-disk usage + per-API disk
latencies), the heal routine, the codec kernel telemetry registry
(codec/telemetry.py), and the audit log, rendered in the Prometheus
text exposition format 0.0.4 at ``/minio-tpu/prometheus/metrics``.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left

START_TIME = time.time()

# Serving-path latency distributions (cmd/metrics.go httpRequestsDuration).
# TTFB buckets reach lower: first byte on a cache/metadata hit is sub-ms.
DURATION_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
TTFB_BUCKETS = (
    0.001, 0.003, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def _escape_label(v) -> str:
    """Label-value escaping per the text-format spec: backslash first."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    """HELP text allows everything except raw newlines and backslashes."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_bound(b: float) -> str:
    """Bucket boundary as Prometheus renders it: 0.05, 1, 2.5."""
    return format(b, "g")


class Histogram:
    """Thread-safe fixed-bucket histogram keyed by one label value.

    Observations land in the first bucket whose upper bound is >= the
    value (``le`` semantics); values beyond the last bound go to the
    implicit ``+Inf`` overflow slot.  ``collect()`` returns cumulative
    bucket counts ready for ``_bucket``/``_sum``/``_count`` rendering.
    """

    def __init__(self, buckets: "tuple[float, ...]"):
        self.buckets = tuple(sorted(buckets))
        self._mu = threading.Lock()
        # key -> [per-bucket counts..., overflow]
        self._counts: "dict[str, list[int]]" = {}
        self._sums: "dict[str, float]" = {}

    def observe(self, key: str, value: float) -> None:
        if value < 0:
            value = 0.0
        idx = bisect_left(self.buckets, value)
        with self._mu:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
            counts[idx] += 1
            self._sums[key] += value

    def collect(self):
        """Per key: (cumulative bucket counts incl. +Inf, sum, count)."""
        with self._mu:
            snap = {
                k: (list(v), self._sums[k]) for k, v in self._counts.items()
            }
        out = []
        for key in sorted(snap):
            counts, total = snap[key]
            cum, acc = [], 0
            for c in counts:
                acc += c
                cum.append(acc)
            out.append((key, cum, total, acc))
        return out


class Metrics:
    """Thread-safe counters for the serving path."""

    def __init__(self):
        self._mu = threading.Lock()
        # (api, code) -> count
        self.requests: "dict[tuple[str, str], int]" = {}
        # api -> [count, total_seconds]
        self.latency: "dict[str, list]" = {}
        self.bytes_rx = 0
        self.bytes_tx = 0
        self.duration_hist = Histogram(DURATION_BUCKETS)
        self.ttfb_hist = Histogram(TTFB_BUCKETS)

    def observe(
        self,
        api: str,
        code: int,
        seconds: float,
        bytes_in: int = 0,
        bytes_out: int = 0,
        ttfb: "float | None" = None,
    ) -> None:
        with self._mu:
            key = (api, str(code))
            self.requests[key] = self.requests.get(key, 0) + 1
            lat = self.latency.setdefault(api, [0, 0.0])
            lat[0] += 1
            lat[1] += seconds
            self.bytes_rx += bytes_in
            self.bytes_tx += bytes_out
        self.duration_hist.observe(api, seconds)
        if ttfb is not None:
            self.ttfb_hist.observe(api, ttfb)

    # -- rendering --------------------------------------------------------

    def render(
        self, object_layer=None, heal=None, queue=None, audit=None,
        plane=None,
    ) -> bytes:
        """The exposition document; live gauges are sampled now."""
        out: list[str] = []

        def emit(name, mtype, help_, samples):
            out.append(f"# HELP {name} {_escape_help(help_)}")
            out.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                lbl = (
                    "{"
                    + ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in labels.items()
                    )
                    + "}"
                    if labels
                    else ""
                )
                out.append(f"{name}{lbl} {value}")

        def emit_histogram(name, help_, hist, label):
            out.append(f"# HELP {name} {_escape_help(help_)}")
            out.append(f"# TYPE {name} histogram")
            for key, cum, total, count in hist.collect():
                kv = f'{label}="{_escape_label(key)}"'
                for bound, c in zip(hist.buckets, cum):
                    out.append(
                        f'{name}_bucket{{{kv},le="{_fmt_bound(bound)}"}} {c}'
                    )
                out.append(f'{name}_bucket{{{kv},le="+Inf"}} {count}')
                out.append(f"{name}_sum{{{kv}}} {total:.6f}")
                out.append(f"{name}_count{{{kv}}} {count}")

        with self._mu:
            reqs = dict(self.requests)
            lat = {k: list(v) for k, v in self.latency.items()}
            rx, tx = self.bytes_rx, self.bytes_tx

        emit(
            "miniotpu_s3_requests_total",
            "counter",
            "S3 requests by API and HTTP code",
            [
                ({"api": api, "code": code}, n)
                for (api, code), n in sorted(reqs.items())
            ],
        )
        emit(
            "miniotpu_s3_request_seconds_total",
            "counter",
            "Cumulative request wall time by API",
            [
                ({"api": api}, f"{total:.6f}")
                for api, (_n, total) in sorted(lat.items())
            ],
        )
        emit(
            # counters must not end in _count (reserved for histogram
            # series); see MTPU104 in minio_tpu/analysis
            "miniotpu_s3_request_seconds_observations_total",
            "counter",
            "Requests counted toward request_seconds by API",
            [({"api": api}, n) for api, (n, _t) in sorted(lat.items())],
        )
        emit_histogram(
            "miniotpu_s3_request_duration_seconds",
            "S3 request wall-time distribution by API",
            self.duration_hist,
            "api",
        )
        emit_histogram(
            "miniotpu_s3_ttfb_seconds",
            "Time to first response byte by API",
            self.ttfb_hist,
            "api",
        )
        emit(
            "miniotpu_s3_rx_bytes_total", "counter",
            "Bytes received from S3 clients", [({}, rx)],
        )
        emit(
            "miniotpu_s3_tx_bytes_total", "counter",
            "Bytes sent to S3 clients", [({}, tx)],
        )
        emit(
            "miniotpu_process_uptime_seconds", "gauge",
            "Seconds since process start",
            [({}, f"{time.time() - START_TIME:.1f}")],
        )

        self._emit_codec(emit)
        self._emit_read_cache(emit)
        self._emit_select(emit)
        self._emit_disk_health(emit)

        if object_layer is not None:
            disks, usage = _disk_samples(object_layer)
            emit(
                "miniotpu_disks_total", "gauge",
                "Configured disks", [({}, disks[0])],
            )
            emit(
                "miniotpu_disks_offline", "gauge",
                "Offline disks", [({}, disks[1])],
            )
            emit(
                "miniotpu_disk_storage_used_bytes", "gauge",
                "Used bytes per disk",
                [({"disk": ep}, u) for ep, (u, _f, _t) in usage],
            )
            emit(
                "miniotpu_disk_storage_available_bytes", "gauge",
                "Free bytes per disk",
                [({"disk": ep}, f) for ep, (_u, f, _t) in usage],
            )
            emit(
                "miniotpu_disk_storage_total_bytes", "gauge",
                "Capacity per disk",
                [({"disk": ep}, t) for ep, (_u, _f, t) in usage],
            )
            self._emit_disk_api(emit, object_layer)
        if heal is not None:
            emit(
                "miniotpu_heal_objects_healed_total", "counter",
                "Objects healed by the background routine",
                [({}, heal.healed)],
            )
            emit(
                "miniotpu_heal_objects_failed_total", "counter",
                "Background heals that failed",
                [({}, heal.failed)],
            )
        if queue is not None:
            emit(
                "miniotpu_heal_queue_depth", "gauge",
                "Tasks waiting in the heal queue",
                [({}, len(queue))],
            )
        if audit is not None:
            emit(
                "miniotpu_audit_entries_dropped_total", "counter",
                "Audit entries lost to target write failures",
                [({}, getattr(audit, "dropped", 0))],
            )
        if plane is not None:
            # server-plane admission/backpressure families (PlaneStats
            # snapshot, server/admission.py); shed reasons are
            # zero-filled so the label set is stable across scrapes
            emit(
                "miniotpu_server_inflight_requests", "gauge",
                "Admitted S3 requests currently executing",
                [({}, plane.get("inflight", 0))],
            )
            emit(
                "miniotpu_server_stage_queue_depth", "gauge",
                "Requests waiting per server-plane stage",
                [
                    ({"stage": stage}, depth)
                    for stage, depth in sorted(
                        plane.get("stage_depth", {}).items()
                    )
                ],
            )
            from .admission import SHED_REASONS

            shed = plane.get("shed", {})
            emit(
                "miniotpu_server_shed_total", "counter",
                "Requests shed by admission control, by reason",
                [
                    ({"reason": r}, shed.get(r, 0))
                    for r in SHED_REASONS
                ],
            )
            loops = plane.get("loops") or []
            if loops:
                # multi-loop plane breakdown: one series per loop for
                # every family (and per loop x reason for sheds), all
                # zero-filled from the loop list so a scrape's shape
                # never depends on which loop saw traffic
                emit(
                    "miniotpu_server_loop_connections", "gauge",
                    "Open connections owned by each server loop",
                    [
                        ({"loop": str(s["loop"])},
                         s["stage_depth"].get("parse", 0))
                        for s in loops
                    ],
                )
                emit(
                    "miniotpu_server_loop_inflight_requests", "gauge",
                    "Admitted requests executing per server loop",
                    [
                        ({"loop": str(s["loop"])}, s["inflight"])
                        for s in loops
                    ],
                )
                emit(
                    "miniotpu_server_loop_handler_queue_depth", "gauge",
                    "Requests queued for each loop's worker slice",
                    [
                        ({"loop": str(s["loop"])},
                         s["stage_depth"].get("handler", 0))
                        for s in loops
                    ],
                )
                emit(
                    "miniotpu_server_loop_shed_total", "counter",
                    "Requests shed per server loop, by reason",
                    [
                        ({"loop": str(s["loop"]), "reason": r},
                         s["shed"].get(r, 0))
                        for s in loops
                        for r in SHED_REASONS
                    ],
                )
        return ("\n".join(out) + "\n").encode()

    @staticmethod
    def _emit_select(emit):
        """S3 Select pushdown families; every engine/reason cell is
        zero-filled so the label set is stable whether or not a scan
        has run (or the device engine exists on this node)."""
        from ..s3select.device import STATS, SelectStats

        snap = STATS.snapshot()
        emit(
            "miniotpu_select_requests_total", "counter",
            "Select evaluations by executing engine",
            [
                ({"engine": e}, snap["requests"].get(e, 0))
                for e in SelectStats.ENGINES
            ],
        )
        emit(
            "miniotpu_select_fallback_total", "counter",
            "Device-scan fallbacks to the host engines, by reason",
            [
                ({"reason": r}, snap["fallbacks"].get(r, 0))
                for r in SelectStats.REASONS
            ],
        )
        emit(
            "miniotpu_select_scanned_bytes_total", "counter",
            "Object bytes scanned by select evaluations",
            [({}, snap["scanned_bytes"])],
        )
        emit(
            "miniotpu_select_returned_bytes_total", "counter",
            "Result bytes produced by select evaluations",
            [({}, snap["returned_bytes"])],
        )
        emit(
            "miniotpu_select_device_seconds_total", "counter",
            "Wall seconds spent in the device scan phase",
            [({}, f"{snap['device_seconds']:.6f}")],
        )

    @staticmethod
    def _emit_read_cache(emit):
        """Tiered read-cache families; every (family, tier) cell is
        zero-filled so dashboards see identical shapes whether the
        cache is off, cold, or hot."""
        from .. import cache as rcache

        st = rcache.read_cache_stats()
        tiers = st["tiers"]

        def per_tier(field):
            return [
                ({"tier": t}, tiers[t][field]) for t in rcache.TIERS
            ]

        emit(
            "miniotpu_cache_hits_total", "counter",
            "Read-cache group hits by tier (digest re-verified)",
            per_tier("hits"),
        )
        emit(
            "miniotpu_cache_misses_total", "counter",
            "Read-cache group misses by tier",
            per_tier("misses"),
        )
        emit(
            "miniotpu_cache_evictions_total", "counter",
            "Read-cache groups evicted under budget pressure by tier",
            per_tier("evictions"),
        )
        emit(
            "miniotpu_cache_rejects_total", "counter",
            "Read-cache admissions rejected by tier (frequency contest"
            " losses and digest-verification drops)",
            per_tier("rejects"),
        )
        emit(
            "miniotpu_cache_entries", "gauge",
            "Read-cache resident groups by tier",
            per_tier("entries"),
        )
        emit(
            "miniotpu_cache_occupancy_bytes", "gauge",
            "Read-cache resident bytes by tier",
            per_tier("occupancy_bytes"),
        )
        emit(
            "miniotpu_cache_budget_bytes", "gauge",
            "Read-cache configured capacity by tier",
            per_tier("capacity_bytes"),
        )
        emit(
            "miniotpu_cache_demotions_total", "counter",
            "Device-tier groups demoted (written back) to the host tier",
            [({}, st["demotions"])],
        )
        emit(
            "miniotpu_cache_invalidations_total", "counter",
            "Object invalidations applied to the read cache",
            [({}, st["invalidations"])],
        )
        adm = st["admission"]
        emit(
            "miniotpu_cache_admission_events_total", "counter",
            "TinyLFU admission-filter events by kind",
            [
                ({"kind": kind}, adm[kind])
                for kind in (
                    "recorded", "seeded", "admitted", "rejected"
                )
            ],
        )

    @staticmethod
    def _emit_codec(emit):
        """Codec kernel families from the process-wide KernelStats."""
        from ..codec.telemetry import KERNEL_STATS

        snap = KERNEL_STATS.snapshot()
        ops = snap["ops"]
        emit(
            "miniotpu_codec_ops_total", "counter",
            "Codec backend kernel invocations by op and backend",
            [
                ({"op": o["op"], "backend": o["backend"]}, o["calls"])
                for o in ops
            ],
        )
        emit(
            "miniotpu_codec_bytes_total", "counter",
            "Bytes processed by codec kernels by op and backend",
            [
                ({"op": o["op"], "backend": o["backend"]}, o["bytes"])
                for o in ops
            ],
        )
        emit(
            "miniotpu_codec_seconds_total", "counter",
            "Host-observed device seconds in codec kernels",
            [
                (
                    {"op": o["op"], "backend": o["backend"]},
                    f'{o["seconds"]:.6f}',
                )
                for o in ops
            ],
        )
        b = snap["batch"]
        emit(
            "miniotpu_codec_batch_flushes_total", "counter",
            "Coalesced codec batch flushes", [({}, b["flushes"])],
        )
        emit(
            "miniotpu_codec_batch_jobs_total", "counter",
            "Jobs coalesced into codec batch flushes",
            [({}, b["jobs"])],
        )
        emit(
            "miniotpu_codec_batch_blocks_total", "counter",
            "Blocks merged across codec batch flushes",
            [({}, b["blocks"])],
        )
        emit(
            "miniotpu_codec_batch_wait_seconds_total", "counter",
            "Cumulative queue wait across coalesced codec jobs",
            [({}, f'{b["wait_seconds"]:.6f}')],
        )
        streams = snap["streams"]
        emit(
            "miniotpu_codec_streams_total", "counter",
            "Erasure-coded object streams by kind",
            [({"op": s["kind"]}, s["streams"]) for s in streams],
        )
        emit(
            "miniotpu_codec_stream_bytes_total", "counter",
            "Object bytes pushed through erasure streams by kind",
            [({"op": s["kind"]}, s["bytes"]) for s in streams],
        )
        emit(
            "miniotpu_codec_stream_heal_required_total", "counter",
            "Decoded streams that reported shards needing heal",
            [({}, snap["heal_required"])],
        )
        d2h = snap.get("d2h", [])
        emit(
            "miniotpu_codec_d2h_bytes_total", "counter",
            "Device->host codec readback bytes by plane (data|parity)",
            [({"plane": r["plane"]}, r["bytes"]) for r in d2h],
        )
        emit(
            "miniotpu_codec_d2h_transfers_total", "counter",
            "Device->host codec readback transfers by plane",
            [({"plane": r["plane"]}, r["transfers"]) for r in d2h],
        )
        h2d = {r["plane"]: r for r in snap.get("h2d", [])}
        emit(
            "miniotpu_codec_h2d_bytes_total", "counter",
            "Host->device codec staging bytes by plane (data|parity)",
            [({"plane": p}, h2d.get(p, {}).get("bytes", 0))
             for p in ("data", "parity")],
        )
        emit(
            "miniotpu_codec_h2d_transfers_total", "counter",
            "Host->device codec staging transfers by plane",
            [({"plane": p}, h2d.get(p, {}).get("transfers", 0))
             for p in ("data", "parity")],
        )
        ow = snap.get("overlap_windows", {})
        emit(
            "miniotpu_codec_overlap_windows_total", "counter",
            "Transfer/compute overlap windows opened by direction "
            "(put = encode side, get = verify/reconstruct side)",
            [({"direction": d}, ow.get(d, 0)) for d in ("put", "get")],
        )
        pc = snap.get("parity_cache", {})
        emit(
            "miniotpu_codec_parity_cache_bytes", "gauge",
            "Device-resident parity plane bytes currently cached",
            [({}, pc.get("occupancy_bytes", 0))],
        )
        emit(
            "miniotpu_codec_parity_cache_entries", "gauge",
            "Device-resident parity planes currently cached",
            [({}, pc.get("entries", 0))],
        )
        emit(
            "miniotpu_codec_parity_cache_evictions_total", "counter",
            "Parity planes drained early by write-back eviction",
            [({}, pc.get("evictions", 0))],
        )
        hedge = snap.get("hedge", {})
        emit(
            "miniotpu_hedge_launched_total", "counter",
            "Duplicate shard reads launched past the p99 deadline",
            [({}, hedge.get("launched", 0))],
        )
        emit(
            "miniotpu_hedge_won_total", "counter",
            "Hedged reads that delivered intact shard frames",
            [({}, hedge.get("won", 0))],
        )
        emit(
            "miniotpu_hedge_wasted_total", "counter",
            "Hedged reads abandoned without contributing",
            [({}, hedge.get("wasted", 0))],
        )
        placement = snap.get("placement", {})
        emit(
            "miniotpu_codec_placement_total", "counter",
            "Merged-batch placement decisions (span = full mesh,"
            " route = least-loaded submesh)",
            [
                ({"policy": outcome}, placement.get(outcome, 0))
                for outcome in ("span", "route")
            ],
        )
        submeshes = snap.get("submeshes", [])
        emit(
            "miniotpu_codec_submesh_queue_depth", "gauge",
            "In-flight merged batches per codec submesh",
            [
                ({"submesh": s["submesh"]}, s["depth"])
                for s in submeshes
            ],
        )
        emit(
            "miniotpu_codec_submesh_queue_depth_peak", "gauge",
            "High-water mark of in-flight batches per codec submesh",
            [
                ({"submesh": s["submesh"]}, s["depth_hwm"])
                for s in submeshes
            ],
        )
        stages = snap["stages"]
        emit(
            "miniotpu_codec_stage_seconds_total", "counter",
            "Per-stream stage time (assemble/codec/disk) by op",
            [
                (
                    {"op": s["op"], "stage": s["stage"]},
                    f'{s["seconds"]:.6f}',
                )
                for s in stages
            ],
        )
        io = snap["iopool"]
        emit(
            "miniotpu_iopool_jobs_total", "counter",
            "I/O fan-out jobs completed per pool queue",
            [({"queue": q["queue"]}, q["jobs"]) for q in io["queues"]],
        )
        emit(
            "miniotpu_iopool_bytes_total", "counter",
            "Shard bytes moved through the I/O fan-out per pool queue",
            [({"queue": q["queue"]}, q["bytes"]) for q in io["queues"]],
        )
        emit(
            "miniotpu_iopool_busy_seconds_total", "counter",
            "Worker time spent inside I/O jobs per pool queue",
            [
                ({"queue": q["queue"]}, f'{q["busy_seconds"]:.6f}')
                for q in io["queues"]
            ],
        )
        emit(
            "miniotpu_iopool_queue_depth_peak", "gauge",
            "High-water mark of any fan-out queue's backlog",
            [({}, io["depth_hwm"])],
        )
        emit(
            "miniotpu_iopool_slowest_job_seconds", "gauge",
            "Longest single I/O job observed (the slowest-disk signal)",
            [({}, f'{io["slowest_job_seconds"]:.6f}')],
        )

    @staticmethod
    def _emit_disk_health(emit):
        """Breaker states + read-latency quantiles (storage/health.py)."""
        from ..storage import health as disk_health

        reg = disk_health.registry()
        snap = reg.snapshot()
        states = reg.states()
        emit(
            "miniotpu_disk_state", "gauge",
            "Circuit-breaker state per disk"
            " (0=healthy, 1=suspect, 2=tripped)",
            [
                ({"disk": ep}, st)
                for ep, st in sorted(states.items())
            ],
        )
        p99s = [
            ({"disk": ep}, f'{row["read_p99_seconds"]:.6f}')
            for ep, row in sorted(snap["disks"].items())
            if row.get("read_p99_seconds") is not None
        ]
        pool_p99 = snap["pool"]["read_p99_seconds"]
        if pool_p99 is not None:
            p99s.append(({"disk": "_pool"}, f"{pool_p99:.6f}"))
        emit(
            "miniotpu_disk_read_p99_seconds", "gauge",
            "Streaming p99 of shard-read latency per disk"
            " (_pool = pool-wide, the hedge-deadline input)",
            p99s,
        )
        emit(
            "miniotpu_disk_breaker_trips_total", "counter",
            "Circuit-breaker trips per disk",
            [
                ({"disk": ep}, row["trips"])
                for ep, row in sorted(snap["disks"].items())
            ],
        )

    @staticmethod
    def _emit_disk_api(emit, object_layer):
        """Per-disk per-API families from any MeteredDisk in the layer."""
        calls, errors, seconds = [], [], []
        p99s = []
        for d in _iter_disks(object_layer):
            stats_fn = getattr(d, "api_stats", None)
            if not callable(stats_fn):
                continue
            try:
                ep, stats = d.metered_endpoint(), stats_fn()
            except Exception:  # noqa: BLE001
                continue
            for api, row in sorted(stats.items()):
                kv = {"disk": ep, "api": api}
                calls.append((kv, row["calls"]))
                errors.append((kv, row["errors"]))
                seconds.append((kv, f'{row["seconds"]:.6f}'))
                if row.get("p99_seconds") is not None:
                    p99s.append((kv, f'{row["p99_seconds"]:.6f}'))
        emit(
            "miniotpu_disk_api_calls_total", "counter",
            "Storage API calls by disk and API", calls,
        )
        emit(
            "miniotpu_disk_api_errors_total", "counter",
            "Storage API errors by disk and API", errors,
        )
        emit(
            "miniotpu_disk_api_seconds_total", "counter",
            "Cumulative storage API latency by disk and API", seconds,
        )
        emit(
            "miniotpu_disk_api_p99_seconds", "gauge",
            "Streaming p99 latency by disk and API (P2 estimator)",
            p99s,
        )


def _iter_disks(object_layer):
    zones = getattr(object_layer, "zones", None)
    if zones is not None:
        for z in zones:
            yield from _iter_disks(z)
        return
    sets = getattr(object_layer, "sets", None)
    if sets is not None:
        for s in sets:
            yield from _iter_disks(s)
        return
    yield from getattr(object_layer, "disks", [])


def _disk_samples(object_layer):
    total = offline = 0
    usage = []
    for d in _iter_disks(object_layer):
        total += 1
        if d is None or not _safe_online(d):
            offline += 1
            continue
        try:
            info = d.disk_info()
            usage.append(
                (info.endpoint, (info.used, info.free, info.total))
            )
        except Exception:  # noqa: BLE001
            offline += 1
    return (total, offline), usage


def _safe_online(d) -> bool:
    try:
        return d.is_online()
    except Exception:  # noqa: BLE001
        return False
