"""HTTP tracing + audit logging + console-log capture
(cmd/http-tracer.go:99 Trace, cmd/logger/audit.go:129 AuditLog,
cmd/consolelogger.go).

Every S3/admin request produces a TraceInfo published to the node's
trace PubSub AND appended to a sequence-numbered ring buffer - the
ring is what peers poll (`tracebuf?since=N`) so `admin trace` streams
cluster-wide without holding a connection per peer.  The audit log is
an independent JSON-lines sink (file via MINIO_TPU_AUDIT_LOG_FILE).
Console capture attaches a logging.Handler feeding the same ring
mechanism for `admin console`.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from ..utils.pubsub import PubSub

RING_MAX = 4096


class SeqRing:
    """Sequence-numbered ring buffer; readers poll with `since`.

    Sequences are contiguous (each append is +1), so a reader's cursor
    maps to a buffer offset arithmetically: `since` is O(returned)
    rather than a full-ring scan - peers polling `tracebuf?since=N`
    were rescanning all 4096 entries per poll per peer.
    """

    def __init__(self, maxlen: int = RING_MAX):
        self._mu = threading.Lock()
        self._maxlen = maxlen
        self._buf: list = []
        self._head = 0  # index of the OLDEST retained item once full
        self._seq = 0

    def append(self, item: dict) -> int:
        with self._mu:
            self._seq += 1
            if len(self._buf) < self._maxlen:
                self._buf.append(item)
            else:
                self._buf[self._head] = item
                self._head = (self._head + 1) % self._maxlen
            return self._seq

    def since(self, seq: int, limit: int = 1000) -> "tuple[int, list]":
        """Entries with sequence > seq -> (cursor, items).  The cursor
        is the sequence of the LAST RETURNED item - when `limit`
        truncates, the remainder is picked up by the next poll rather
        than silently skipped."""
        with self._mu:
            n = len(self._buf)
            first = self._seq - n + 1  # seq of the oldest retained item
            start = max(seq + 1, first)
            if n == 0 or start > self._seq:
                return self._seq, []
            count = min(self._seq - start + 1, limit)
            base = self._head + (start - first)
            items = [self._buf[(base + i) % n] for i in range(count)]
            return start + count - 1, items


class Tracer:
    """Per-node trace hub: pubsub for local subscribers + the ring
    peers poll."""

    def __init__(self, node: str = ""):
        self.node = node
        self.pubsub = PubSub()
        self.ring = SeqRing()
        # count ring polls as interest so traced nodes keep recording
        self._last_poll = 0.0

    @property
    def active(self) -> bool:
        return (
            self.pubsub.num_subscribers > 0
            or time.monotonic() - self._last_poll < 10.0
        )

    def publish(self, info: dict) -> None:
        info.setdefault("node", self.node)
        self.pubsub.publish(info)
        self.ring.append(info)

    def poll(self, since: int) -> "tuple[int, list]":
        self._last_poll = time.monotonic()
        return self.ring.since(since)


def trace_info(
    node: str,
    method: str,
    path: str,
    query: str,
    status: int,
    duration_s: float,
    bytes_in: int,
    bytes_out: int,
    client: str,
    api: str,
) -> dict:
    """The pkg/trace.Info DTO shape, trimmed to JSON-friendly fields."""
    return {
        "node": node,
        "time": time.time(),
        "api": api,
        "method": method,
        "path": path,
        "query": query,
        "status": status,
        "duration_ms": round(duration_s * 1e3, 3),
        "rx": bytes_in,
        "tx": bytes_out,
        "client": client,
    }


class AuditLog:
    """Per-request audit entries as JSON lines
    (logger.AuditLog, cmd/logger/audit.go:129)."""

    def __init__(self, path: "str | None" = None):
        self.path = path or os.environ.get(
            "MINIO_TPU_AUDIT_LOG_FILE", ""
        )
        self._mu = threading.Lock()
        # write failures: counted (miniotpu_audit_entries_dropped_total)
        # and warned about once, not silently swallowed
        self.dropped = 0
        self._warned = False

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    def log(self, entry: dict) -> None:
        if not self.path:
            return
        entry.setdefault("version", "1")
        entry.setdefault("time", time.time())
        line = json.dumps(entry) + "\n"
        try:
            with self._mu, open(self.path, "a", encoding="utf-8") as f:
                f.write(line)
        except OSError as exc:
            with self._mu:
                self.dropped += 1
                warn = not self._warned
                self._warned = True
            if warn:
                logging.getLogger("minio_tpu.audit").warning(
                    "audit log write failed; entries are being dropped "
                    "(target=%s error=%s) - further drops counted in "
                    "miniotpu_audit_entries_dropped_total",
                    self.path,
                    exc,
                )


class ConsoleCapture(logging.Handler):
    """Ring-buffered capture of this process's structured logs
    (cmd/consolelogger.go HTTPConsoleLoggerSys)."""

    def __init__(self, node: str = ""):
        super().__init__()
        self.node = node
        self.ring = SeqRing()
        # emit() runs inside the logging machinery, so a failure cannot
        # itself be logged (infinite recursion); count it instead
        self.dropped = 0

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.ring.append(
                {
                    "node": self.node,
                    "time": record.created,
                    "level": record.levelname,
                    "name": record.name,
                    "msg": record.getMessage(),
                }
            )
        except Exception:  # logging must never raise; count the drop
            self.dropped += 1

    def install(self) -> "ConsoleCapture":
        # the framework logger stops propagation once log.setup runs,
        # so capture must attach at "minio_tpu", not the root
        logging.getLogger("minio_tpu").addHandler(self)
        return self

    def uninstall(self) -> None:
        logging.getLogger("minio_tpu").removeHandler(self)
