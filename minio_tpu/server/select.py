"""SelectObjectContent glue (cmd/object-handlers.go:91
SelectObjectContentHandler -> pkg/s3select).

The object is spooled through the normal erasure-decode read path
(decompression/SSE seams included), evaluated by minio_tpu.s3select,
and the EventStream frames are written as one response.
"""

from __future__ import annotations

import tempfile

from ..s3select import S3Select, SelectError
from ..s3select.engine import SelectRequest
from .s3errors import S3Error

# spool to disk past this size; select sources are usually small-ish
SPOOL_MEM = 16 << 20


def handle_select(handler, bucket, key, info, body) -> None:
    try:
        req = SelectRequest.from_xml(body)
        sel = S3Select(req)
    except SelectError as e:
        raise S3Error(
            e.code if e.code in _KNOWN else "InvalidRequestParameter",
            e.msg,
        ) from None
    with tempfile.SpooledTemporaryFile(max_size=SPOOL_MEM) as spool, \
            tempfile.SpooledTemporaryFile(max_size=SPOOL_MEM) as out:
        # full-object read through the erasure/SSE/compression stack
        # SSE-C objects are selectable with their key (the reference
        # routes select reads through getObjectNInfo, which decrypts)
        handler.s3.object_layer.get_object(
            bucket, key, spool, sse=handler._read_sse(info)
        )
        spool.seek(0)
        try:
            # result frames spool too: a huge SELECT * result must not
            # live in RAM (code-review r4 finding)
            sel.evaluate(spool, info.size, out.write)
        except SelectError as e:
            raise S3Error(
                e.code if e.code in _KNOWN else "InvalidRequestParameter",
                e.msg,
            ) from None
        total = out.tell()
        out.seek(0)
        handler.send_response(200)
        handler.send_header("Server", "MinIO-TPU")
        handler.send_header("Content-Type", "application/octet-stream")
        handler.send_header("Content-Length", str(total))
        handler.end_headers()
        while True:
            chunk = out.read(1 << 20)
            if not chunk:
                break
            handler.wfile.write(chunk)
            handler._resp_bytes += len(chunk)


def _known_codes():
    from . import s3errors

    return frozenset(s3errors._E)


_KNOWN = _known_codes()
