"""SelectObjectContent glue (cmd/object-handlers.go:91
SelectObjectContentHandler -> pkg/s3select).

Scans are the server's second admitted traffic class
(MINIO_TPU_SELECT_MAX_INFLIGHT, shed reason ``select``).  A
device-capable statement over an object whose groups all sit in the
device cache tier scans the device-resident plane directly — zero
shard reads, candidate rows only across D2H; everything else is
spooled through the normal erasure-decode read path
(decompression/SSE seams included) and evaluated on host.  Either
way the EventStream frames are written as one response.
"""

from __future__ import annotations

import io
import tempfile

from ..s3select import S3Select, SelectError
from ..s3select.engine import SelectRequest
from .s3errors import S3Error

# spool to disk past this size; select sources are usually small-ish
SPOOL_MEM = 16 << 20


class _SpoolReader(io.RawIOBase):
    """Readable adapter over SpooledTemporaryFile.

    Until Python 3.11 SpooledTemporaryFile does not implement the io
    ABC probes (``readable()`` & co.), so handing the spool straight
    to the select engines blows up inside their TextIOWrapper."""

    def __init__(self, spool):
        self._spool = spool

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        data = self._spool.read(len(b))
        n = len(data)
        b[:n] = data
        return n


def _spool_reader(spool):
    return spool if hasattr(spool, "readable") else _SpoolReader(spool)


def _device_source(handler, bucket, key, info, sel):
    """(plane, nbytes) when this scan can run on the device cache
    tier, else None.  Never raises: any wrinkle falls back to the
    spooled read path the handler was already taking."""
    try:
        if not sel.device_capable():
            return None
        fn = getattr(
            handler.s3.object_layer, "device_scan_source", None
        )
        if fn is None:
            return None
        return fn(bucket, key)
    except Exception:  # noqa: BLE001 - pushdown is best-effort
        return None


def handle_select(handler, bucket, key, info, body) -> None:
    try:
        req = SelectRequest.from_xml(body)
        sel = S3Select(req)
    except SelectError as e:
        raise S3Error(
            e.code if e.code in _KNOWN else "InvalidRequestParameter",
            e.msg,
        ) from None
    adm = getattr(handler.s3, "admission", None)
    if adm is not None:
        if not adm.try_enter_select():
            adm.stats.shed_inc("select")
            raise S3Error("OperationMaxedOut", "scan capacity reached")
    try:
        _run_select(handler, bucket, key, info, sel)
    finally:
        if adm is not None:
            adm.leave_select()


def _run_select(handler, bucket, key, info, sel) -> None:
    with tempfile.SpooledTemporaryFile(max_size=SPOOL_MEM) as spool, \
            tempfile.SpooledTemporaryFile(max_size=SPOOL_MEM) as out:
        src = _device_source(handler, bucket, key, info, sel)
        try:
            if src is not None:
                # result frames spool either way: a huge SELECT *
                # result must not live in RAM (code-review r4 finding)
                sel.evaluate(
                    None, info.size, out.write, device_source=src
                )
            else:
                # full-object read through the erasure/SSE/compression
                # stack.  SSE-C objects are selectable with their key
                # (the reference routes select reads through
                # getObjectNInfo, which decrypts)
                handler.s3.object_layer.get_object(
                    bucket, key, spool, sse=handler._read_sse(info)
                )
                spool.seek(0)
                sel.evaluate(_spool_reader(spool), info.size, out.write)
        except SelectError as e:
            raise S3Error(
                e.code if e.code in _KNOWN else "InvalidRequestParameter",
                e.msg,
            ) from None
        total = out.tell()
        out.seek(0)
        handler.send_response(200)
        handler.send_header("Server", "MinIO-TPU")
        handler.send_header("Content-Type", "application/octet-stream")
        handler.send_header("Content-Length", str(total))
        handler.end_headers()
        while True:
            chunk = out.read(1 << 20)
            if not chunk:
                break
            handler.wfile.write(chunk)
            handler._resp_bytes += len(chunk)


def _known_codes():
    from . import s3errors

    return frozenset(s3errors._E)


_KNOWN = _known_codes()
