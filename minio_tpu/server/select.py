"""SelectObjectContent glue (cmd/object-handlers.go:91 ->
pkg/s3select).  Full engine lands in minio_tpu/s3select/."""

from __future__ import annotations

from .s3errors import S3Error


def handle_select(handler, bucket, key, info, body) -> None:
    raise S3Error("NotImplemented", "SelectObjectContent")
