"""Background healing: MRF queue, heal routine, fresh-disk monitor.

The reference splits this across three pieces that we mirror:

- MRF ("most recently failed", erasure.go:40-45 + addPartial,
  erasure-object.go:999): writes that missed some disks enqueue the
  object for immediate heal instead of waiting for a crawl.
- Heal routine (healRoutine.run, background-heal-ops.go:77): one
  background consumer draining heal tasks against the object layer,
  throttled so it cannot starve foreground I/O.
- Fresh-disk monitor (monitorLocalDisksAndHeal,
  background-newdisks-heal-ops.go:124 + healErasureSet,
  global-heal.go:92): detects a replaced/wiped drive (format.json gone),
  re-stamps it from the set's reference format, and sweeps the set's
  buckets + objects through the heal queue so the new drive converges
  with no operator action.
"""

from __future__ import annotations

import collections
import dataclasses
import threading

from ..utils.log import kv, logger

_log = logger("heal")


@dataclasses.dataclass(frozen=True)
class HealTask:
    """One unit of heal work; object == "" heals the bucket only."""

    bucket: str
    object: str = ""
    version_id: str = ""


class HealQueue:
    """Deduplicating FIFO feeding the heal routine (the healTask
    channel analogue, bounded by dedup rather than depth)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._queue: collections.deque = collections.deque()
        self._pending: set = set()

    def push(self, task: HealTask) -> None:
        with self._cond:
            if task in self._pending:
                return
            self._pending.add(task)
            self._queue.append(task)
            self._cond.notify()

    def push_object(
        self, bucket: str, object_name: str, version_id: str = ""
    ) -> None:
        self.push(HealTask(bucket, object_name, version_id))

    def pop(self, timeout: "float | None" = None) -> "HealTask | None":
        with self._cond:
            if not self._queue and not self._cond.wait(timeout):
                return None
            if not self._queue:
                return None
            task = self._queue.popleft()
            self._pending.discard(task)
            # in-flight until task_done(): popped-but-unprocessed tasks
            # must keep drain() waiting (no gap where the queue looks
            # empty while a heal is mid-run)
            self._inflight += 1
            return task

    _inflight = 0

    def task_done(self) -> None:
        with self._mu:
            self._inflight -= 1

    def idle(self) -> bool:
        with self._mu:
            return not self._queue and self._inflight == 0

    def __len__(self) -> int:
        with self._mu:
            return len(self._queue)


class HealRoutine:
    """Daemon consumer draining the queue against the object layer."""

    def __init__(
        self,
        object_layer,
        queue: HealQueue,
        throttle_s: float = 0.0,
    ):
        self._ol = object_layer
        self.queue = queue
        self._throttle = throttle_s
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.healed = 0  # counters for admin/metrics
        self.failed = 0

    def start(self) -> "HealRoutine":
        self._thread = threading.Thread(
            target=self._run, name="heal-routine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait for the queue to empty (tests / admin heal barrier)."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.queue.idle():
                return True
            time.sleep(0.05)
        return False

    def _run(self) -> None:
        while not self._stop.is_set():
            task = self.queue.pop(timeout=0.25)
            if task is None:
                continue
            try:
                if task.object:
                    self._ol.heal_object(
                        task.bucket, task.object, task.version_id
                    )
                else:
                    self._ol.heal_bucket(task.bucket)
                self.healed += 1
            except Exception as e:  # noqa: BLE001 - retried by later triggers
                self.failed += 1
                from ..utils import log

                log.logger("heal").warning(
                    "heal task failed",
                    extra=log.kv(
                        bucket=task.bucket,
                        object=task.object,
                        error=f"{type(e).__name__}: {e}",
                    ),
                )
            finally:
                self.queue.task_done()
            import os

            # config seam: runtime-editable via admin set-config-kv;
            # a malformed value must never kill this thread
            try:
                throttle = float(
                    os.environ.get("MINIO_TPU_HEAL_THROTTLE_S")
                    or self._throttle
                )
            except ValueError:
                throttle = self._throttle
            if throttle:
                self._stop.wait(throttle)


class FreshDiskMonitor:
    """Detects wiped/replaced local drives and heals them back in.

    Every interval, each LOCAL disk of every set is probed for its
    format document.  A reachable disk without one is a replacement:
    it is re-stamped with the uuid its slot records in the set's
    reference format (HealFormat semantics, erasure-sets.go:1328), and
    the set's namespace is swept into the heal queue (healErasureSet).
    """

    def __init__(
        self,
        zones_layer,
        queue: HealQueue,
        interval_s: float = 10.0,
    ):
        self._zones = zones_layer
        self._queue = queue
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.stamped = 0

    def start(self) -> "FreshDiskMonitor":
        self._thread = threading.Thread(
            target=self._run, name="fresh-disk-monitor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _effective_interval(self) -> float:
        import os

        try:
            v = float(
                os.environ.get("MINIO_TPU_FRESH_DISK_INTERVAL_S")
                or self._interval
            )
        except ValueError:
            return self._interval
        return v if v >= 1.0 else max(self._interval, 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self._effective_interval()):
            try:
                self.scan_once()
            except Exception as exc:
                _log.warning("background heal scan failed", extra=kv(err=str(exc)))

    def scan_once(self) -> int:
        """One probe pass; returns how many fresh disks were stamped."""
        from ..objectlayer.format import (
            FormatErasure,
            read_format,
            write_format,
        )

        stamped = 0
        for zone in getattr(self._zones, "zones", [self._zones]):
            ref = getattr(zone, "format_ref", None)
            if ref is None:
                continue
            for s_idx, eset in enumerate(zone.sets):
                fresh: list[int] = []
                for d_idx, disk in enumerate(eset.disks):
                    if disk is None:
                        continue
                    # probe THROUGH the decorator chain (DiskIDCheck,
                    # MeteredDisk - in either stacking order) to the
                    # raw disk: the ID check (rightly) fails every op
                    # on an unformatted drive, but this monitor's
                    # whole job is resurrecting exactly those drives
                    raw = disk
                    while True:
                        inner = (
                            raw.__dict__.get("unwrapped")
                            if hasattr(raw, "__dict__")
                            else None
                        )
                        if inner is None:
                            break
                        raw = inner
                    # stamped at boot (load_or_init_format hole fill):
                    # still needs its set swept
                    if getattr(raw, "_freshly_stamped", False):
                        raw._freshly_stamped = False
                        fresh.append(d_idx)
                        continue
                    if not raw.is_local() or not raw.is_online():
                        continue
                    try:
                        fmt = read_format(raw)
                    except Exception:  # noqa: BLE001
                        continue  # corrupt format: operator decision
                    if fmt is not None:
                        continue
                    # replaced drive: restore staging vol + identity
                    # (write_format recreates .sys itself)
                    try:
                        write_format(
                            raw,
                            FormatErasure(
                                id=ref.id,
                                this=ref.sets[s_idx][d_idx],
                                sets=ref.sets,
                            ),
                        )
                        fresh.append(d_idx)
                        stamped += 1
                    except Exception:  # noqa: BLE001
                        continue
                if fresh:
                    self._sweep_set(eset)
        self.stamped += stamped
        return stamped

    def _sweep_set(self, eset) -> None:
        """Enqueue every bucket + object of the set for heal
        (healErasureSet, global-heal.go:92)."""
        buckets: dict[str, None] = {}
        for disk in eset.disks:
            if disk is None or not disk.is_online():
                continue
            try:
                for v in disk.list_vols():
                    buckets[v.name] = None
            except Exception:  # noqa: BLE001
                continue
        for bucket in buckets:
            self._queue.push(HealTask(bucket))
            names: dict[str, None] = {}
            for disk in eset.disks:
                if disk is None or not disk.is_online():
                    continue
                try:
                    for name in disk.walk(bucket):
                        names[name] = None
                except Exception:  # noqa: BLE001
                    continue
            for name in names:
                self._queue.push(HealTask(bucket, name))
