"""Resumable heal sequences with client tokens
(cmd/admin-heal-ops.go).

A heal *sequence* is a background namespace walk healing every object
under ``bucket/prefix``.  Launching one returns a ``client_token``;
the client then polls with that token and receives the result items
accumulated since its last poll (PopHealStatusJSON semantics,
admin-heal-ops.go:266) - the sequence survives between polls, a
disconnected client resumes by token, and a crashed client's
sequence is garbage-collected ``KEEP_ENDED_S`` after it ends.

Differences from the reference, deliberate: sequence state is
in-memory per node (the reference's is too); the walk drives the
object layer's ``list_objects``/``heal_object`` instead of a raw disk
walk, so REST-remote disks and zones come along for free.
"""

from __future__ import annotations

import threading
import time
import uuid

# ended sequences stay queryable this long (keepHealSeqStateDuration)
KEEP_ENDED_S = 600.0
# per-sequence cap of unpopped result items: a client that stops
# polling must not grow memory without bound
MAX_UNPOPPED = 10000


class HealSequenceError(Exception):
    def __init__(self, code: str, msg: str):
        super().__init__(msg)
        self.code = code


class HealSequence:
    def __init__(self, object_layer, bucket: str, prefix: str = "",
                 dry_run: bool = False, remove_corrupted: bool = False,
                 client_address: str = ""):
        self._ol = object_layer
        self.bucket = bucket
        self.prefix = prefix
        self.dry_run = dry_run
        self.remove_corrupted = remove_corrupted
        self.client_token = uuid.uuid4().hex
        self.client_address = client_address
        self.start_time = time.time()
        self.end_time = 0.0
        self.status = "running"  # running|finished|stopped|failed
        self.failure = ""
        self.current_path = ""  # resume/progress marker
        self.scanned = 0
        self.healed = 0
        self.failed = 0
        self._items: list = []
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"heal-seq-{bucket}/{prefix}",
        )

    @property
    def path(self) -> str:
        return f"{self.bucket}/{self.prefix}".rstrip("/")

    def start(self) -> "HealSequence":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def has_ended(self) -> bool:
        return self.status != "running"

    # -- the walk ---------------------------------------------------------

    def _record(self, item: dict) -> None:
        with self._mu:
            if len(self._items) < MAX_UNPOPPED:
                self._items.append(item)

    def _run(self) -> None:
        try:
            self._heal_bucket()
            marker = ""
            while not self._stop.is_set():
                res = self._ol.list_objects(
                    self.bucket, self.prefix, marker, "", 1000
                )
                for oi in res.objects:
                    if self._stop.is_set():
                        break
                    self._heal_one(oi.name)
                if self._stop.is_set() or not res.is_truncated:
                    break
                marker = res.next_marker
            self.status = (
                "stopped" if self._stop.is_set() else "finished"
            )
        except Exception as e:  # noqa: BLE001
            self.status = "failed"
            self.failure = f"{type(e).__name__}: {e}"
        finally:
            self.end_time = time.time()

    def _heal_bucket(self) -> None:
        try:
            res = self._ol.heal_bucket(
                self.bucket, dry_run=self.dry_run
            )
            self._record(
                {
                    "type": "bucket",
                    "bucket": self.bucket,
                    "detail": res,
                }
            )
        except Exception as e:  # noqa: BLE001
            self._record(
                {
                    "type": "bucket",
                    "bucket": self.bucket,
                    "error": str(e),
                }
            )

    def _heal_one(self, name: str) -> None:
        self.current_path = f"{self.bucket}/{name}"
        self.scanned += 1
        try:
            res = self._ol.heal_object(
                self.bucket, name, dry_run=self.dry_run
            )
        except Exception as e:  # noqa: BLE001
            self.failed += 1
            self._record(
                {
                    "type": "object",
                    "bucket": self.bucket,
                    "object": name,
                    "error": str(e),
                }
            )
            return
        if res.get("healed") or (
            self.dry_run and res.get("outdated")
        ):
            self.healed += 1
            self._record(
                {"type": "object", **res}
            )

    # -- status polling ---------------------------------------------------

    def pop_status(self) -> dict:
        """Status document + result items accumulated since the last
        poll (the reference pops items per status call)."""
        with self._mu:
            items, self._items = self._items, []
        return {
            "client_token": self.client_token,
            "start_time": self.start_time,
            "status": self.status,
            **({"failure": self.failure} if self.failure else {}),
            "current_path": self.current_path,
            "scanned": self.scanned,
            "healed": self.healed,
            "failed": self.failed,
            "items": items,
        }


class AllHealState:
    """Registry of running/recent heal sequences
    (allHealState, admin-heal-ops.go:103)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._seqs: "dict[str, HealSequence]" = {}

    def _gc_locked(self) -> None:
        now = time.time()
        for p in [
            p
            for p, s in self._seqs.items()
            if s.has_ended() and now - s.end_time > KEEP_ENDED_S
        ]:
            del self._seqs[p]

    def launch(self, seq: HealSequence,
               force_start: bool = False) -> dict:
        # force-start first drains the old walker OUTSIDE the registry
        # lock (a join under _mu would stall every status poll for up
        # to the join timeout), then registers the replacement; if the
        # old walker is wedged past the timeout, proceed anyway - it
        # has been stopped and exits at its next object boundary
        old = None
        with self._mu:
            existing = self._seqs.get(seq.path)
            if existing is not None and not existing.has_ended():
                if not force_start:
                    raise HealSequenceError(
                        "HealAlreadyRunning",
                        "Heal is already running on the given path "
                        "(use force-start to stop and start afresh); "
                        f"token is {existing.client_token}",
                    )
                existing.stop()
                old = existing
        if old is not None:
            old._thread.join(timeout=30)
        with self._mu:
            self._gc_locked()
            current = self._seqs.get(seq.path)
            if (
                current is not None
                and current is not old
                and not current.has_ended()
            ):
                # a concurrent launch won the race while we drained
                raise HealSequenceError(
                    "HealAlreadyRunning",
                    "Heal is already running on the given path; "
                    f"token is {current.client_token}",
                )
            # overlap guard: a parent and child path healing
            # concurrently would double-heal and race renames
            for p, s in self._seqs.items():
                if s.has_ended() or p == seq.path:
                    continue
                # '/'-boundary aware: 'bkt' overlaps 'bkt/a' but NOT
                # the sibling bucket 'bkt2'
                if p.startswith(seq.path + "/") or seq.path.startswith(
                    p + "/"
                ):
                    raise HealSequenceError(
                        "HealOverlappingPaths",
                        f"heal sequence overlaps with running path {p}",
                    )
            self._seqs[seq.path] = seq
        seq.start()
        return {
            "client_token": seq.client_token,
            "client_address": seq.client_address,
            "start_time": seq.start_time,
        }

    def pop_status(self, path: str, client_token: str) -> dict:
        with self._mu:
            seq = self._seqs.get(path.rstrip("/"))
        if seq is None:
            raise HealSequenceError(
                "HealNoSuchProcess",
                f"no heal sequence on {path!r}",
            )
        if client_token != seq.client_token:
            raise HealSequenceError(
                "HealInvalidClientToken",
                "client token mismatch",
            )
        return seq.pop_status()

    def stop(self, path: str) -> dict:
        with self._mu:
            seq = self._seqs.get(path.rstrip("/"))
        if seq is None:
            raise HealSequenceError(
                "HealNoSuchProcess",
                f"no heal sequence on {path!r}",
            )
        seq.stop()
        return {"status": "stopping", "client_token": seq.client_token}
