"""Host-side GF(2^8) arithmetic and Reed-Solomon matrix construction.

This is the control-plane math behind the TPU erasure codec: tiny (k+m)-sized
matrices are built and inverted here with numpy, then compiled into device
kernels (see minio_tpu/ops/rs.py).  The device never does table lookups.

Reference parity: klauspost/reedsolomon v1.9.9 (the dependency wrapped by
cmd/erasure-coding.go:54-64 in the reference), which uses the AES-agnostic
Reed-Solomon polynomial x^8+x^4+x^3+x^2+1 (0x11d) and a Vandermonde-derived
systematic generator matrix (reedsolomon.go buildMatrix).  We reproduce that
construction exactly so shard geometry and reconstruction semantics match.
"""

from __future__ import annotations

import functools

import numpy as np

# The Reed-Solomon field polynomial used by klauspost/reedsolomon (0x11d).
POLY = 0x11D
FIELD = 256


@functools.lru_cache(maxsize=None)
def _tables() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables for GF(2^8) under POLY, generator 2."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    exp[255:510] = exp[0:255]
    return exp, log


def gf_mul(a: int, b: int) -> int:
    """Scalar GF(2^8) multiply (table based)."""
    if a == 0 or b == 0:
        return 0
    exp, log = _tables()
    return int(exp[log[a] + log[b]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    exp, log = _tables()
    return int(exp[(log[a] - log[b]) % 255])


def gf_inv(a: int) -> int:
    return gf_div(1, a)


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    exp, log = _tables()
    return int(exp[(log[a] * n) % 255])


@functools.lru_cache(maxsize=None)
def mul_table() -> np.ndarray:
    """Full 256x256 GF(2^8) product table (64 KiB) for vectorized host math."""
    exp, log = _tables()
    a = np.arange(256)
    la = log[a][:, None] + log[a][None, :]
    t = exp[la.clip(0, 509)]
    t = t.copy()
    t[0, :] = 0
    t[:, 0] = 0
    return t


def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of uint8 matrices (host, for tiny matrices)."""
    t = mul_table()
    # products[i,j,l] = a[i,l]*b[l,j]; XOR-reduce over l.
    prods = t[a[:, None, :], b.T[None, :, :]]
    return np.bitwise_xor.reduce(prods, axis=2).astype(np.uint8)


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix via Gauss-Jordan elimination.

    Raises ValueError if singular (caller treats this as "data irrecoverable",
    mirroring reedsolomon.ErrTooFewShards semantics at the Erasure layer).
    """
    n = m.shape[0]
    t = mul_table()
    aug = np.concatenate([m.astype(np.uint8), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise ValueError("singular matrix in GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = gf_inv(int(aug[col, col]))
        aug[col] = t[aug[col], inv]
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] ^= t[aug[col], int(aug[row, col])]
    return aug[:, n:].copy()


@functools.lru_cache(maxsize=None)
def rs_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """Systematic (data+parity) x data generator matrix.

    Same construction as klauspost/reedsolomon buildMatrix: take the
    (n x k) Vandermonde matrix V[r, c] = r^c, then left-multiply by the
    inverse of its top k x k block so the data rows become the identity.
    Any k rows of the result are linearly independent, which is the
    reconstruction guarantee the Erasure layer relies on
    (cmd/erasure-coding.go:89-113).
    """
    k, m = data_shards, parity_shards
    n = k + m
    if not (0 < k and 0 <= m and n <= FIELD):
        raise ValueError(f"invalid erasure config {k}+{m}")
    vand = np.zeros((n, k), dtype=np.uint8)
    for r in range(n):
        for c in range(k):
            vand[r, c] = gf_pow(r, c)
    top_inv = mat_inv(vand[:k, :k])
    return mat_mul(vand, top_inv)


def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (parity x data) rows of the systematic generator matrix."""
    return rs_matrix(data_shards, parity_shards)[data_shards:, :].copy()


@functools.lru_cache(maxsize=4096)
def reconstruction_matrix(
    data_shards: int, parity_shards: int, present: tuple[int, ...]
) -> np.ndarray:
    """Matrix mapping k surviving shards back to the k data shards.

    ``present`` lists >=k surviving shard indices (0..k-1 data, k..n-1 parity);
    only the first k are used.  Mirrors reedsolomon.Reconstruct's sub-matrix
    inversion.
    """
    k = data_shards
    rows = sorted(present)[:k]
    if len(rows) < k:
        raise ValueError(
            f"need {k} shards to reconstruct, have {len(rows)}"
        )
    gen = rs_matrix(data_shards, parity_shards)
    sub = gen[list(rows), :]
    return mat_inv(sub)


def encode_ref(data: np.ndarray, parity_shards: int) -> np.ndarray:
    """Pure-numpy reference encoder used by tests as the known answer.

    data: (k, length) uint8 -> parity (m, length) uint8.
    """
    k = data.shape[0]
    pm = parity_matrix(k, parity_shards)
    t = mul_table()
    out = np.zeros((parity_shards, data.shape[1]), dtype=np.uint8)
    for r in range(parity_shards):
        acc = np.zeros(data.shape[1], dtype=np.uint8)
        for c in range(k):
            acc ^= t[pm[r, c], data[c]]
        out[r] = acc
    return out
