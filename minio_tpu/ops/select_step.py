"""Device-side S3 Select scan kernels (SWAR over uint64 word planes).

Layout contract
---------------

* ``arr`` is the chunk's bytes as a flat uint8 plane, padded to a
  multiple of 512 bytes with a filler byte that is never a newline,
  field delimiter, quote, CR, or NUL (the engine uses ``b"x"``), and
  always ending (before the pad) in a newline.
* Flag words are uint64 with ``0x80`` set in each byte lane that
  matches; the word view is a little-endian bitcast of 8 consecutive
  bytes, so lane ``i`` of word ``w`` is byte ``8*w + i``.  uint64
  requires x64 — every caller wraps these entry points in
  ``jax.experimental.enable_x64()`` (the flag is part of the jit
  cache key, so the contract checker does the same).
* Shifted lane flags come from static slices of a zero-padded word
  buffer (``W(k)`` = lanes of bytes at p+k), memoized and shared
  across atoms, so the whole screen stays one fused elementwise
  pass; rolling flag words per shift would cost a full memory pass
  each, and a screen needs ~20 shifts.  Wide planes are screened in
  ``WINDOW_WORDS`` cache blocks over that one shared buffer, so the
  flag temporaries stay LLC-resident and window edges keep full
  byte context.
* ``screen_chunk`` is the only O(N) pass: it fuses byte
  classification, the statement-compiled candidate screen, the hazard
  scalar, and per-64-byte (8-word) block popcount sums.  The screen is
  CONSERVATIVE — it may flag rows that do not match, never the
  reverse; exactness lives entirely in the host engines that re-filter
  the candidate rows.  Everything after it is O(candidates).
* Candidate flags sit on the ``\\n`` (anchor mode ``row``) or on any
  field-opening terminator (anchor mode ``field``); the byte AFTER a
  flagged position starts the screened field.

Screen atoms (static, hashable) compiled by s3select/device.py:

* ``("len", lo, hi)`` — first field length in [lo, hi] (a terminator
  at offset length+1 from the flag).
* ``("deep", k)``     — no terminator within the first k field bytes.
* ``("byte0", lo, hi)`` — first field byte in [lo, hi] (ASCII).
* ``("nd", k)``       — a non-digit, non-terminator byte within the
  first k field bytes.
* ``("lex", lit, mode)`` — field lexicographically <, <=, ==, >=, >
  the literal byte string (mode in "lt|le|eq|ge|gt"), exact over the
  first ``len(lit)`` bytes plus the terminator.

MTPU204: every jitted entry point here has a contract block in
minio_tpu/analysis/kernel_contracts.py.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

PAD_BYTE = 0x78  # b"x": never nl/fd/quote/CR/NUL
BLOCK_BYTES = 512  # plane padding granularity (callers pad to this)
POP_WORDS = 8  # words per popcount block (64 bytes): the reshape
# factor of screen_chunk's block sums and extract_positions' ranks
MAX_LEX = 8  # lex/byte-chain depth cap (screen shifts stay bounded)
WINDOW_WORDS = 1 << 18  # 2 MiB per screen window (cache blocking)

_LO = 0x0101010101010101
_HI = 0x8080808080808080


def _u64(x) -> jnp.ndarray:
    return jnp.uint64(np.uint64(x))


def _words(arr):
    """Little-endian uint64 view of the byte plane."""
    return lax.bitcast_convert_type(arr.reshape(-1, 8), jnp.uint64)


def _swar_eq(w, byte):
    """0x80 flag in each lane equal to ``byte``."""
    x = w ^ _u64(byte * _LO)
    return (x - _u64(_LO)) & ~x & _u64(_HI)


def _swar_ge(w, c):
    """0x80 flag where lane >= c; only meaningful for ASCII lanes
    (< 0x80) — non-ASCII lanes are ORed in separately by callers that
    need them."""
    return ((w & ~_u64(_HI)) + _u64((0x80 - c) * _LO)) & _u64(_HI)


def _atom_words(atom, W, term_at, digit_at):
    """Flag-words for one screen atom, anchored one byte BEFORE the
    field (i.e. on the opening terminator).  ``W(k)`` is the word
    plane shifted so lane p carries byte p+k; ``term_at(k)`` /
    ``digit_at(k)`` are the memoized terminator / digit flags on it.
    A mask the old roll-based kernel built as ``byteshift(f(w), k)``
    is ``f(W(k))`` here — same flags, no shift pass."""
    kind = atom[0]
    if kind == "len":
        lo, hi = atom[1], atom[2]
        m = _u64(0)
        for ln in range(lo, hi + 1):
            m = m | term_at(ln + 1)
        return m
    if kind == "deep":
        k = atom[1]
        seen = _u64(0)
        for i in range(1, k + 1):
            seen = seen | term_at(i)
        return ~seen & _u64(_HI)
    if kind == "byte0":
        lo, hi = atom[1], atom[2]
        w1 = W(1)
        m = _swar_ge(w1, lo) & ~_swar_ge(w1, hi + 1)
        if lo == 0:
            # ASCII-only trick misses nothing at the low end, but a
            # [0, hi] range must not claim non-ASCII lanes
            m = m & ~(w1 & _u64(_HI))
        return m
    if kind == "nd":
        k = atom[1]
        seen = _u64(0)
        hit = _u64(0)
        for i in range(1, k + 1):
            nd = ~digit_at(i) & ~term_at(i) & _u64(_HI)
            hit = hit | (nd & ~seen)
            seen = seen | term_at(i)
        return hit
    if kind == "lex":
        lit, mode = atom[1], atom[2]
        n = min(len(lit), MAX_LEX)
        pref = _u64(_HI)  # field[:i] == lit[:i] so far (i = 0)
        hit = _u64(0)
        for i in range(n):
            wi = W(i + 1)
            if mode in ("lt", "le"):
                below = _swar_ge(wi, 0) & ~_swar_ge(wi, lit[i]) \
                    if lit[i] > 0 else _u64(0)
                hit = hit | (pref & below)
                # strict prefix (field ends first) sorts below
                hit = hit | (pref & term_at(i + 1))
            elif mode in ("gt", "ge"):
                above = (_swar_ge(wi, lit[i] + 1) | (wi & _u64(_HI))) \
                    if lit[i] < 0x7F else (wi & _u64(_HI))
                hit = hit | (pref & above & ~term_at(i + 1))
            pref = pref & _swar_eq(wi, lit[i])
        endv = term_at(n + 1)
        if mode in ("eq", "le", "ge"):
            if len(lit) <= MAX_LEX:
                hit = hit | (pref & endv)
            else:
                hit = hit | pref  # prefix-truncated: keep conservative
        if mode in ("gt", "ge"):
            hit = hit | (pref & ~endv)  # longer field, lit is a prefix
        if mode == "lt" and len(lit) > MAX_LEX:
            hit = hit | pref  # can't see past the cap: conservative
        return hit
    raise ValueError(f"unknown screen atom {atom!r}")


def _max_shift(atoms, sci_guard: bool) -> int:
    """Largest forward byte offset any atom (or the hazard pass)
    reads — sizes the zero pad behind the word buffer."""
    m = 1  # bare-CR hazard looks at p+1
    for branch in atoms:
        for atom in branch:
            kind = atom[0]
            if kind == "len":
                m = max(m, atom[2] + 1)
            elif kind in ("deep", "nd"):
                m = max(m, atom[1])
            elif kind == "byte0":
                m = max(m, 1)
            elif kind == "lex":
                m = max(m, min(len(atom[1]), MAX_LEX) + 1)
    return m


@functools.partial(
    jax.jit, static_argnames=("fd", "qc", "atoms", "anchor", "sci_guard")
)
def screen_chunk(
    arr, *, fd: int, qc: int, atoms, anchor: str, sci_guard: bool
):
    """The O(N) fused pass.

    Returns ``(cand, blk, nrows, hazard)``: candidate flag-words
    (uint64), per-64-byte (``POP_WORDS``-word) block candidate
    popcounts (int32), total row count (int32 scalar), and the hazard
    scalar (bool) — quote, bare
    CR, or NUL anywhere in the chunk sends the whole chunk to the
    host engine.  ``atoms`` is a tuple of tuples of screen atoms: the
    outer level ORs (one entry per OR branch), the inner level ANDs.

    Shifted lane flags come from static SLICES of a zero-padded word
    buffer (two slices + two bit-shifts per distinct byte offset,
    memoized and shared across atoms), not from rolling flag words:
    a roll is a full memory pass, and a screen needs ~20 shifts.
    Zero words past the plane end reproduce the roll-based shift's
    fill exactly, so the candidate set is unchanged.

    The screen is cache-blocked: planes wider than ``WINDOW_WORDS``
    are screened window by window (an unrolled loop over static
    slices), so each window's ~6 materialised flag temporaries stay
    LLC-resident instead of spilling to DRAM.  Every window still
    slices the ONE shared padded buffer, so cross-window lookahead,
    the sci guard's byte ``p-1``, and the bare-CR check all read real
    neighbouring bytes — the output is bit-identical to a
    single-window pass.
    """
    w = _words(arr)
    nw = w.shape[0]
    qmax = _max_shift(atoms, sci_guard) // 8 + 1

    def window(s: int, m: int):
        """cand flags + packed block sums for words [s, s+m).

        Each window gets its own small padded buffer — one front word
        (byte ``p-1`` context: the previous window's last word, or
        zero at the plane start), the window's words, then real
        lookahead words from the next window where the plane has
        them, zeros past its end.  The buffer is LLC-sized, so every
        memoized shifted view reads cache-resident lanes."""
        t = min(qmax + 1, nw - s - m)  # real lookahead words available
        front = (
            lax.slice(w, (s - 1,), (s,))
            if s
            else jnp.zeros(1, jnp.uint64)
        )
        pieces = [front, lax.slice(w, (s,), (s + m + t,))]
        if t < qmax + 1:
            pieces.append(jnp.zeros(qmax + 1 - t, jnp.uint64))
        wp = jnp.concatenate(pieces)
        shifted: dict = {}

        def W(k: int):
            got = shifted.get(k)
            if got is None:
                q, r = divmod(k, 8)
                lo = lax.slice(wp, (q + 1,), (q + 1 + m,))
                if r:
                    hi = lax.slice(wp, (q + 2,), (q + 2 + m,))
                    got = (lo >> _u64(8 * r)) | (hi << _u64(64 - 8 * r))
                else:
                    got = lo
                shifted[k] = got
            return got

        def term_at(k: int):
            got = shifted.get(("t", k))
            if got is None:
                wk = W(k)
                got = _swar_eq(wk, 10) | _swar_eq(wk, fd)
                shifted[("t", k)] = got
            return got

        def digit_at(k: int):
            got = shifted.get(("d", k))
            if got is None:
                wk = W(k)
                got = _swar_ge(wk, 0x30) & ~_swar_ge(wk, 0x3A)
                shifted[("d", k)] = got
            return got

        ww = W(0)
        nl = _swar_eq(ww, 10)
        base = nl if anchor == "row" else term_at(0)
        hit = _u64(0)
        for branch in atoms:
            bm = _u64(_HI)
            for atom in branch:
                bm = bm & _atom_words(atom, W, term_at, digit_at)
            hit = hit | bm
        cand = base & hit
        hazflags = (
            _swar_eq(ww, qc)
            | (_swar_eq(ww, 13) & ~_swar_eq(W(1), 10))
            | _swar_eq(ww, 0)
        )
        if sci_guard:
            # a digit-prefixed exponent field ("1000e-8") coerces
            # numeric with a value no length/shape atom can bound:
            # any digit immediately followed by e/E sends the chunk
            # to the host
            e = _swar_eq(ww, 0x65) | _swar_eq(ww, 0x45)
            hazflags = hazflags | (e & digit_at(-1))
        # one reduction pass for all three aggregates: pack the
        # per-word candidate popcount (<=8, bits 0-6 after the
        # POP_WORDS-word block sum), newline popcount (bits 7-13) and
        # hazard bit (bits 14+) into one int32 per word, block-sum
        # once, then unpack per block
        combo = (
            lax.population_count(cand).astype(jnp.int32)
            | (lax.population_count(nl).astype(jnp.int32) << 7)
            | ((hazflags != 0).astype(jnp.int32) << 14)
        )
        bsum = combo.reshape(-1, POP_WORDS).sum(axis=1, dtype=jnp.int32)
        # materialise each window's pair behind a barrier: without it
        # XLA folds the windows into the two output concatenates and
        # recomputes the whole screen once per output
        return lax.optimization_barrier((cand, bsum))

    parts = [
        window(s, min(WINDOW_WORDS, nw - s))
        for s in range(0, nw, WINDOW_WORDS)
    ]
    if len(parts) == 1:
        cand, bs = parts[0]
    else:
        cand = jnp.concatenate([p[0] for p in parts])
        bs = jnp.concatenate([p[1] for p in parts])
    return (
        cand,
        bs & 127,
        ((bs >> 7) & 127).sum(dtype=jnp.int32),
        (bs >> 14).any(),
    )


@functools.partial(jax.jit, static_argnames=("cap",))
def extract_positions(cand, cum, *, cap: int):
    """Byte positions of the first ``cap`` candidate flags.

    ``cum`` is the inclusive cumsum of the block popcounts; ranks
    beyond the true count return clamped garbage the caller slices
    off (it knows the count from ``cum[-1]``)."""
    k = jnp.arange(cap, dtype=jnp.int32)
    blk = jnp.searchsorted(cum, k, side="right").astype(jnp.int32)
    blk = jnp.minimum(blk, cum.shape[0] - 1)
    base = jnp.where(blk > 0, cum[jnp.maximum(blk - 1, 0)], 0)
    lr = k - base
    wrds = cand.reshape(-1, POP_WORDS)[blk]
    pcs = lax.population_count(wrds).astype(jnp.int32)
    pref = jnp.cumsum(pcs, axis=1) - pcs
    inw = (pref <= lr[:, None]) & (lr[:, None] < pref + pcs)
    wsel = jnp.argmax(inw, axis=1).astype(jnp.int32)
    word = jnp.take_along_axis(wrds, wsel[:, None], axis=1)[:, 0]
    need = (
        lr - jnp.take_along_axis(pref, wsel[:, None], axis=1)[:, 0] + 1
    )
    need = jnp.maximum(need, 1).astype(jnp.uint64)
    p = jnp.zeros(cap, dtype=jnp.int32)
    half = 32
    while half:
        lowmask = (_u64(1) << _u64(half)) - _u64(1)
        c = lax.population_count(word & lowmask).astype(jnp.uint64)
        go = c < need
        need = jnp.where(go, need - c, need)
        word = jnp.where(go, word >> _u64(half), word)
        p = jnp.where(go, p + half, p)
        half //= 2
    return ((blk * POP_WORDS + wsel) << 3) + (p >> 3)


@functools.partial(jax.jit, static_argnames=("window",))
def row_spans(arr, anchors, *, window: int):
    """Length of the row starting at ``anchor + 1``: offset of the
    first newline in a forward window, and whether one was found
    (rows wider than the window are host-verified)."""
    start = anchors + 1
    gidx = start[:, None] + jnp.arange(window, dtype=jnp.int32)[None, :]
    mat = arr[jnp.clip(gidx, 0, arr.shape[0] - 1)]
    isnl = mat == 10
    found = isnl.any(axis=1)
    return jnp.argmax(isnl, axis=1).astype(jnp.int32), found


@functools.partial(jax.jit, static_argnames=("window",))
def anchors_back(arr, hits, *, window: int):
    """Row anchor (position of the preceding newline, -1 for row 0)
    for mid-row field hits, via a backward window scan; ``found`` is
    False when the window ended before a newline or the chunk start."""
    offs = jnp.arange(window, dtype=jnp.int32)
    gidx = hits[:, None] - offs[None, :]
    mat = arr[jnp.clip(gidx, 0, arr.shape[0] - 1)]
    isnl = (mat == 10) & (gidx >= 0)
    off = jnp.argmax(isnl, axis=1).astype(jnp.int32)
    anynl = isnl.any(axis=1)
    reach0 = (hits - (window - 1)) <= 0
    anch = jnp.where(anynl, hits - off, jnp.int32(-1))
    return anch, anynl | reach0


@functools.partial(jax.jit, static_argnames=("window",))
def gather_rows(arr, starts, *, window: int):
    """(C, window) uint8 view of the rows at ``starts`` — the
    result-proportional buffer the drain seam copies to host."""
    gidx = starts[:, None] + jnp.arange(window, dtype=jnp.int32)[None, :]
    return arr[jnp.clip(gidx, 0, arr.shape[0] - 1)]
