"""TPU-native GF(2^8) Reed-Solomon encode/reconstruct as JAX programs.

Replaces the AVX2/NEON galois-multiply assembly in klauspost/reedsolomon
v1.9.9 (consumed by the reference at cmd/erasure-coding.go:54-64 and driven
from cmd/erasure-encode.go / erasure-decode.go).  The design is TPU-first
rather than a port of the byte-table SIMD approach:

* Bytes are packed 4-per-lane into uint32 words, so every VPU lane processes
  4 field elements per op (SWAR).  No gathers, no byte tables on device.
* Multiplication by the generator-matrix constants uses the "xtime powers"
  decomposition: for each data shard we materialize x, 2x, 4x, ..., 128x
  (seven SWAR doublings), and each parity word is then a pure XOR-reduction
  of the powers selected by the bits of its matrix constants.  For EC 8+4
  this is ~56 doublings + ~130 XORs per 32 bytes of data - entirely
  elementwise, so XLA fuses the whole stripe into one VPU kernel and the
  op stays HBM-bound rather than gather-bound.
* The generator matrix is a compile-time constant (one jit cache entry per
  erasure config), while reconstruction uses a *traced* matrix so that any
  missing-shard pattern reuses one compiled program (no recompilation storm
  on degraded reads, the analogue of reedsolomon.Reconstruct's per-call
  sub-matrix inversion).

Shard layout convention matches cmd/erasure-coding.go: shard i of n sits in
row i; rows [0,k) are data, rows [k,n) are parity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf

# SWAR constants for 4 packed GF(2^8) elements per uint32 lane.
_LOW7 = np.uint32(0x7F7F7F7F)
_HIGH1 = np.uint32(0x80808080)
_POLY_LOW = np.uint32(gf.POLY & 0xFF)  # 0x1d replicated via multiply


def _xtime(words: jax.Array) -> jax.Array:
    """Multiply 4 packed field elements by x (i.e. 2) in one SWAR step."""
    carries = (words & _HIGH1) >> 7  # 0x01 in each byte that overflows
    return ((words & _LOW7) << 1) ^ (carries * _POLY_LOW)


def _powers(words: jax.Array) -> list[jax.Array]:
    """[x, 2x, 4x, ..., 128x] for packed words - the mul-by-constant basis."""
    ps = [words]
    for _ in range(7):
        ps.append(_xtime(ps[-1]))
    return ps


def bytes_to_words(shards: jax.Array) -> jax.Array:
    """(..., length) uint8 -> (..., length//4) uint32 (length % 4 == 0)."""
    if shards.dtype != jnp.uint8:
        raise TypeError(f"expected uint8 shards, got {shards.dtype}")
    if shards.shape[-1] % 4:
        raise ValueError("shard length must be a multiple of 4 bytes")
    return jax.lax.bitcast_convert_type(
        shards.reshape(*shards.shape[:-1], shards.shape[-1] // 4, 4), jnp.uint32
    )


def words_to_bytes(words: jax.Array) -> jax.Array:
    """(..., w) uint32 -> (..., 4*w) uint8."""
    out = jax.lax.bitcast_convert_type(words, jnp.uint8)
    return out.reshape(*words.shape[:-1], words.shape[-1] * 4)


def _encode_words(data_words: jax.Array, matrix: np.ndarray) -> jax.Array:
    """(k, w) uint32 -> (m, w) uint32 parity via static XOR-select.

    ``matrix`` is the (m, k) parity block of the systematic generator
    matrix; it is baked into the traced program (constants prune XORs for
    zero bits at trace time).
    """
    k = data_words.shape[0]
    m = matrix.shape[0]
    assert matrix.shape == (m, k)
    if m == 0:
        return jnp.zeros((0, data_words.shape[1]), dtype=jnp.uint32)
    powers = [_powers(data_words[i]) for i in range(k)]
    rows = []
    for r in range(m):
        acc = None
        for c in range(k):
            coeff = int(matrix[r, c])
            for b in range(8):
                if (coeff >> b) & 1:
                    term = powers[c][b]
                    acc = term if acc is None else acc ^ term
        if acc is None:
            acc = jnp.zeros_like(data_words[0])
        rows.append(acc)
    return jnp.stack(rows)


def _matmul_static(words: jax.Array, matrix: np.ndarray) -> jax.Array:
    """Static-matrix GF matmul: Pallas kernel on TPU, fused XLA elsewhere.

    Trace-time dispatch: on the TPU backend the tiled VMEM kernel
    (rs_pallas.matmul_words) is ~15x the fused-XLA path; CPU tests and
    the virtual multi-chip mesh take the portable jnp path.
    """
    if jax.default_backend() == "tpu":
        from . import rs_pallas

        return rs_pallas.matmul_words(matrix, words, interpret=False)
    return _encode_words(words, matrix)


def _matmul_words_dynamic(shards_words: jax.Array, matrix: jax.Array) -> jax.Array:
    """(s, w) uint32 x traced (o, s) uint8 matrix -> (o, w) uint32.

    Used for reconstruction, where the matrix depends on which shards
    survived: bits of the (traced) constants become XOR masks so a single
    compiled program serves every erasure pattern.
    """
    s, w = shards_words.shape
    o = matrix.shape[0]
    m32 = matrix.astype(jnp.uint32)  # (o, s)
    # Accumulate without materializing an (o, s, 8, w) intermediate: walk
    # the xtime chain of each survivor lazily and fold masked terms into
    # the (o, w) accumulator; stays HBM-friendly.
    acc = jnp.zeros((o, w), dtype=jnp.uint32)
    for i in range(s):
        p = shards_words[i]
        for b in range(8):
            bit = (m32[:, i] >> np.uint32(b)) & np.uint32(1)  # (o,)
            mask = (bit * jnp.uint32(0xFFFFFFFF))[:, None]
            acc = acc ^ (mask & p[None, :])
            if b != 7:
                p = _xtime(p)
    return acc


def _xor_reduce(x: jax.Array, axis: int) -> jax.Array:
    """XOR-reduce along an axis (lax.reduce with bitwise xor)."""
    return jax.lax.reduce(
        x, np.uint32(0), jax.lax.bitwise_xor, (axis,)
    )


@functools.partial(jax.jit, static_argnames=("data_shards", "parity_shards"))
def _encode_jit(data: jax.Array, data_shards: int, parity_shards: int) -> jax.Array:
    matrix = gf.parity_matrix(data_shards, parity_shards)
    words = bytes_to_words(data)
    parity = _matmul_static(words, matrix)
    return words_to_bytes(parity)


def encode(data: jax.Array | np.ndarray, parity_shards: int) -> jax.Array:
    """Encode (k, length) uint8 data shards -> (m, length) parity shards.

    Device analogue of reedsolomon.Encode as called from
    Erasure.EncodeData (cmd/erasure-coding.go:66-86).
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    return _encode_jit(data, data.shape[0], parity_shards)


@functools.partial(
    jax.jit, static_argnames=("data_shards", "parity_shards", "want_parity")
)
def _reconstruct_jit(
    shards: jax.Array,
    present_mask: jax.Array,
    recon_matrix: jax.Array,
    data_shards: int,
    parity_shards: int,
    want_parity: bool,
) -> jax.Array:
    """Rebuild all n shards from >=k survivors.

    shards: (n, length) uint8 with garbage rows where present_mask is 0.
    recon_matrix: (k, k) traced GF matrix mapping the first k survivors
    (in index order, compacted) back to data shards.
    """
    k, m = data_shards, parity_shards
    n = k + m
    words = bytes_to_words(shards)  # (n, w)
    # Compact the first k surviving rows to the top, in index order - the
    # row order reconstruction_matrix() was built against.
    order = jnp.argsort(
        jnp.where(present_mask > 0, jnp.arange(n), n + jnp.arange(n))
    )
    survivors = words[order[:k]]
    data_words = _matmul_words_dynamic(survivors, recon_matrix)  # (k, w)
    if want_parity:
        parity = _encode_words(data_words, gf.parity_matrix(k, m))
        all_words = jnp.concatenate([data_words, parity], axis=0)
    else:
        all_words = data_words
    rebuilt = words_to_bytes(all_words)
    keep = present_mask[: rebuilt.shape[0], None].astype(bool)
    return jnp.where(keep, shards[: rebuilt.shape[0]], rebuilt)


@functools.partial(
    jax.jit,
    static_argnames=("present", "data_shards", "parity_shards", "want_parity"),
)
def _reconstruct_static_jit(
    shards: jax.Array,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
    want_parity: bool,
) -> jax.Array:
    """Static-pattern reconstruct: the erasure pattern is baked into the
    compiled program, so the matrix XOR-select is pruned at trace time
    (same cost profile as encode).

    Production reads hit few distinct patterns - a dead drive yields the
    same pattern for every object in the set, and heal sweeps
    (cmd/erasure-lowlevel-heal.go) fix one pattern across the whole set -
    so the per-pattern jit cache amortizes; `reconstruct` keeps the
    dynamic-matrix fallback for pattern churn.
    """
    k, m = data_shards, parity_shards
    idx = tuple(i for i, p in enumerate(present) if p)[:k]
    rm = gf.reconstruction_matrix(k, m, idx)
    words = bytes_to_words(shards)
    survivors = jnp.stack([words[i] for i in idx])
    data_words = _matmul_static(survivors, rm)
    if want_parity:
        parity = _matmul_static(data_words, gf.parity_matrix(k, m))
        all_words = jnp.concatenate([data_words, parity], axis=0)
    else:
        all_words = data_words
    rebuilt = words_to_bytes(all_words)
    keep = np.asarray(present[: rebuilt.shape[0]])[:, None]
    return jnp.where(keep, shards[: rebuilt.shape[0]], rebuilt)


def reconstruct(
    shards: jax.Array | np.ndarray,
    present: "np.ndarray | list[bool]",
    data_shards: int,
    parity_shards: int,
    data_only: bool = True,
    static_pattern: bool = True,
) -> jax.Array:
    """Device analogue of reedsolomon.ReconstructData / Reconstruct.

    ``shards``: (n, length) uint8; rows with present[i] == False are ignored.
    Returns (k, length) when data_only (DecodeDataBlocks path,
    cmd/erasure-coding.go:89-98) else (n, length) (Heal path,
    cmd/erasure-lowlevel-heal.go:28-48).
    """
    present = np.asarray(present, dtype=bool)
    n = data_shards + parity_shards
    if present.shape != (n,):
        raise ValueError(f"present mask must have {n} entries")
    idx = tuple(int(i) for i in np.nonzero(present)[0])
    if len(idx) < data_shards:
        raise ValueError(
            f"need {data_shards} shards, have {len(idx)}"
        )
    shards = jnp.asarray(shards, dtype=jnp.uint8)
    if static_pattern:
        out = _reconstruct_static_jit(
            shards,
            tuple(bool(b) for b in present),
            data_shards,
            parity_shards,
            not data_only,
        )
    else:
        rm = gf.reconstruction_matrix(data_shards, parity_shards, idx)
        mask = jnp.asarray(present.astype(np.uint8))
        out = _reconstruct_jit(
            shards,
            mask,
            jnp.asarray(rm),
            data_shards,
            parity_shards,
            not data_only,
        )
    return out[:data_shards] if data_only else out
