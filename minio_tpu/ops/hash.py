"""phash256: the framework's TPU-native bitrot checksum.

Role-equivalent to HighwayHash-256 in the reference (the default bitrot
algorithm, cmd/bitrot.go:41-58 / cmd/xl-storage-format-v1.go:119), but
designed for a vector machine instead of 64-bit scalar SIMD:

* HighwayHash chains 32-byte packets sequentially - a ~40k-step dependency
  chain per 1 MiB shard block, unusable on TPU.  phash256 is a two-level
  construction: every uint32 word is mixed with a position-derived key
  (splitmix32 of its index - computed in parallel), and the mixes are
  XOR-reduced in independent partitions.  Depth is O(log n), lanes map
  onto the 8x128 VPU.
* Each word contributes to two independent 32-bit mixes (different odd
  multipliers), and the digest interleaves 4 partitions of each, so a
  corrupted/moved/dropped word escapes detection with probability ~2^-64.
  This is an integrity checksum against bitrot, like the reference's
  HighwayHash use - not a cryptographic MAC.
* uint64 is avoided entirely (TPU has no 64-bit integer lanes).

Host (numpy) and device (jnp) implementations are bit-identical; tests
assert agreement and corruption-detection properties.

Threat model
------------
phash256 defends against ACCIDENTAL corruption only - bit flips from
decaying media, torn writes, firmware bugs, truncation.  For a random
flip the two independent 32-bit mixes per word give a miss probability
of ~2^-64 per partition pair, far below the residual error rate of the
disks underneath.  It does NOT resist a deliberate forger: the
position-derived keys (splitmix32 of the word index, line ~55) are
fixed and public, so an adversary who can write shard bytes can also
compute matching digests - there is no secret anywhere in the
construction.  This matches how the reference deploys its bitrot
hashes: HighwayHash-256 is keyed in principle, but cmd/bitrot.go:41-58
uses a MAGIC, HARD-CODED key for exactly this role ("hash channel
separation", not secrecy), so its deployment is equally forgeable and
both systems treat on-disk tamper-resistance as out of scope (an
attacker with write access to a drive can rewrite xl.meta wholesale,
digests included).  Confidentiality/integrity against adversaries is
layered above: SSE (AES-GCM, authenticated) for object data, signed
requests for the API plane.

Keyed escape hatch: if a deployment ever needs an unforgeable bitrot
digest, derive the per-word keys from a secret instead of the public
index mix - ``key = _mix(idx * _C1 + secret32)`` keeps the same
O(log n) shape and lane layout; only the key schedule changes.  The
bitrot registry (codec/bitrot.py) already dispatches per-algorithm, so
a "phash256k" entry can coexist with stored objects.
"""

from __future__ import annotations

import numpy as np

# odd constants from splitmix64/murmur3 literature, truncated to 32 bits
_C1 = np.uint32(0x9E3779B9)  # golden ratio
_C2 = np.uint32(0x85EBCA6B)
_C3 = np.uint32(0xC2B2AE35)
_M1 = np.uint32(0xCC9E2D51)
_M2 = np.uint32(0x1B873593)

PHASH_SIZE = 32  # digest bytes
_PARTS = 4  # partitions per mix lane; 2 mixes x 4 parts = 8 u32 words


def _mix_np(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint32, copy=True)
    x ^= x >> np.uint32(16)
    x *= _C2
    x ^= x >> np.uint32(13)
    x *= _C3
    x ^= x >> np.uint32(16)
    return x


def _digest_np(words: np.ndarray, nbytes: int) -> np.ndarray:
    n = words.shape[0]
    pad = (-n) % _PARTS
    if pad:
        words = np.concatenate([words, np.zeros(pad, np.uint32)])
    idx = np.arange(words.shape[0], dtype=np.uint32)
    key = _mix_np(idx * _C1 + np.uint32(1))
    m1 = _mix_np((words ^ key) * _M1)
    m2 = _mix_np((words + key) * _M2)
    # Strided (word-index mod 4) partitions: any contiguous chunk of the
    # stream reduces to 4 partials independently, which lets the device
    # kernel fold tile partials in any order (see rs_pallas fused kernel).
    p1 = np.bitwise_xor.reduce(m1.reshape(-1, _PARTS), axis=0)
    p2 = np.bitwise_xor.reduce(m2.reshape(-1, _PARTS), axis=0)
    out = np.concatenate([p1, p2])
    # fold in total length so truncation/extension changes every word
    lenmix = (np.uint64(nbytes) * np.uint64(_C1)).astype(np.uint32)
    out = _mix_np(out ^ lenmix + np.arange(8, dtype=np.uint32))
    return out


def phash256_host_batched(words: np.ndarray, nbytes: int) -> np.ndarray:
    """Host digest over the last axis: (..., w) uint32 -> (..., 8) uint32.

    Vectorized numpy twin of phash256_words_batched (bit-identical); used
    by the CPU codec backend so host and device shard files interoperate.
    """
    n = words.shape[-1]
    if n % _PARTS:
        raise ValueError(f"word count {n} must be a multiple of {_PARTS}")
    idx = np.arange(n, dtype=np.uint32)
    key = _mix_np(idx * _C1 + np.uint32(1))
    m1 = _mix_np((words ^ key) * _M1)
    m2 = _mix_np((words + key) * _M2)
    lead = words.shape[:-1]
    p1 = np.bitwise_xor.reduce(
        m1.reshape(*lead, n // _PARTS, _PARTS), axis=-2
    )
    p2 = np.bitwise_xor.reduce(
        m2.reshape(*lead, n // _PARTS, _PARTS), axis=-2
    )
    out = np.concatenate([p1, p2], axis=-1)
    lenmix = (np.uint64(nbytes) * np.uint64(_C1)).astype(np.uint32)
    return _mix_np(out ^ lenmix + np.arange(8, dtype=np.uint32))


def phash256_host(data: bytes | np.ndarray) -> bytes:
    """256-bit parallel bitrot digest of a byte string (host reference)."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8)
    nbytes = buf.shape[0]
    pad = (-nbytes) % 4
    if pad:
        buf = np.concatenate([buf, np.zeros(pad, np.uint8)])
    words = buf.view(np.uint32)
    return _digest_np(words, nbytes).tobytes()


def _mix_jnp(x):
    import jax.numpy as jnp

    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _C2
    x = x ^ (x >> 13)
    x = x * _C3
    x = x ^ (x >> 16)
    return x


def phash256_words(words, nbytes: int):
    """Device digest of a (w,) uint32 word array -> (8,) uint32.

    ``nbytes`` is the true byte length represented (static).  Word count
    must already be a multiple of 4 (the erasure layer pads shards to
    32-byte multiples, mirroring how the reference pads shards to
    ShardSize, cmd/erasure-coding.go:115-117).
    """
    import jax
    import jax.numpy as jnp

    (n,) = words.shape
    if n % _PARTS:
        raise ValueError(f"word count {n} must be a multiple of {_PARTS}")
    return phash256_words_batched(words[None], nbytes)[0]


def phash256_words_batched(words, nbytes: int):
    """Device digest over the LAST axis: (..., w) uint32 -> (..., 8).

    Vectorized over leading axes with no vmap - every op is a full-size
    array op, so hashing (n_shards, batch, w) is one VPU pass.
    """
    import jax
    import jax.numpy as jnp

    n = words.shape[-1]
    if n % _PARTS:
        raise ValueError(f"word count {n} must be a multiple of {_PARTS}")
    lead = words.shape[:-1]
    idx = jax.lax.iota(jnp.uint32, n)
    key = _mix_jnp(idx * _C1 + jnp.uint32(1))
    m1 = _mix_jnp((words ^ key) * _M1)
    m2 = _mix_jnp((words + key) * _M2)
    red = lambda m: jax.lax.reduce(
        m.reshape(*lead, n // _PARTS, _PARTS),
        np.uint32(0),
        jax.lax.bitwise_xor,
        (len(lead),),
    )
    out = jnp.concatenate([red(m1), red(m2)], axis=-1)  # (..., 8)
    return _mix_jnp(
        out ^ jnp.uint32(nbytes) * _C1 + jax.lax.iota(jnp.uint32, 8)
    )


def tile_partials(words, key):
    """XOR partials of one contiguous tile for the fused Pallas kernel.

    words, key: (w,) uint32 (key = _mix(global_index * C1 + 1) for the
    tile's global word positions).  Returns (8,) uint32: 4 partials of the
    m1 mix then 4 of m2.  XOR-fold partials of all tiles, then apply
    finalize_partials to obtain phash256_words output.
    """
    import jax
    import jax.numpy as jnp

    n = words.shape[-1]
    m1 = _mix_jnp((words ^ key) * _M1)
    m2 = _mix_jnp((words + key) * _M2)
    red = lambda m: jax.lax.reduce(
        m.reshape(n // _PARTS, _PARTS),
        np.uint32(0),
        jax.lax.bitwise_xor,
        (0,),
    )
    return jnp.concatenate([red(m1), red(m2)])


def tile_partials_batched(words, offset):
    """XOR partials of one contiguous sub-chunk over the LAST axis.

    words: (..., w) uint32 with w a multiple of _PARTS; offset: scalar
    uint32 global word index of the chunk start, TRACED so every
    sub-chunk of a stream reuses one compiled program.  offset must be
    a multiple of _PARTS (the strided word-index-mod-4 partitions must
    stay aligned across chunks); the codec sub-chunk sizing guarantees
    this by cutting on parity-group boundaries.  Returns (..., 8)
    partials — XOR-fold the chunks in any order, then apply
    finalize_partials to obtain phash256_words_batched output.
    """
    import jax
    import jax.numpy as jnp

    n = words.shape[-1]
    if n % _PARTS:
        raise ValueError(f"word count {n} must be a multiple of {_PARTS}")
    lead = words.shape[:-1]
    idx = jnp.uint32(offset) + jax.lax.iota(jnp.uint32, n)
    key = _mix_jnp(idx * _C1 + jnp.uint32(1))
    m1 = _mix_jnp((words ^ key) * _M1)
    m2 = _mix_jnp((words + key) * _M2)
    red = lambda m: jax.lax.reduce(
        m.reshape(*lead, n // _PARTS, _PARTS),
        np.uint32(0),
        jax.lax.bitwise_xor,
        (len(lead),),
    )
    return jnp.concatenate([red(m1), red(m2)], axis=-1)


def finalize_partials(partials, nbytes: int):
    """Length-fold of XOR-combined tile partials: (..., 8) -> (..., 8)."""
    import jax
    import jax.numpy as jnp

    return _mix_jnp(
        partials
        ^ jnp.uint32(nbytes) * _C1
        + jax.lax.iota(jnp.uint32, 8)
    )
