"""Pallas TPU kernels for the GF(2^8) shard codec hot path.

Two device formulations of "GF matrix @ shards" (the klauspost/reedsolomon
role behind cmd/erasure-coding.go:54-64):

1. SWAR/VPU kernel (`matmul_words`, the default): shards live as uint32
   words (4 field elements per lane).  Multiply-by-constant uses the
   xtime-powers decomposition with the generator matrix baked into the
   kernel at trace time, so each tile is a straight-line XOR chain over
   VMEM-resident vectors - no tables, no gathers, no dtype conversions.
   Measured ~450 GiB/s data throughput at EC 8+4 on v5e-1 (HBM-bound:
   the kernel reads each data byte and writes each parity byte once).

2. MXU bit-matrix kernel (`gf_matmul_mxu`): GF(2^8) mul-by-constant is an
   8x8 linear map over GF(2), so the whole codec lifts to one
   (8o x 8s) @ (8s x T) bf16 matmul per tile, mod 2.  Higher arithmetic
   intensity but pays ~30 VPU ops/byte in bit unpack/repack, which caps it
   below the SWAR kernel at storage geometries (k <= 16).  Kept as the
   backend for very wide/dense matrices and as MXU reference.

Both run under interpret mode for CPU tests; production dispatch lives in
rs.encode / rs.reconstruct.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import gf, rs

# uint32 words per shard per tile (16 KiB of shard bytes per grid step)
_TW = 4096
# lane-dim tile for the MXU kernel: bytes per shard per grid step
_T_BLK = 8192


def _swar_kernel(matrix: np.ndarray):
    """Build a Pallas kernel computing out = matrix GF@ data over a tile.

    matrix (o, s) is a Python-time constant: zero coefficients and zero
    bits are pruned from the XOR chain at trace time, and xtime powers of
    each input row are materialized lazily up to the highest bit any
    coefficient in that column uses (see _swar_rows).
    """
    o, _ = matrix.shape

    def kernel(data_ref, out_ref):
        rows = _swar_rows(matrix, data_ref[...])
        for r in range(o):
            out_ref[r, :] = rows[r]

    return kernel


@functools.partial(
    jax.jit, static_argnames=("matrix_key", "o", "s", "interpret")
)
def _matmul_words_jit(
    words, matrix_key: bytes, o: int, s: int, interpret: bool
):
    matrix = np.frombuffer(matrix_key, dtype=np.uint8).reshape(o, s)
    w = words.shape[1]
    pad = (-w) % _TW
    if pad:
        words = jnp.pad(words, ((0, 0), (0, pad)))
    pw = w + pad
    out = pl.pallas_call(
        _swar_kernel(matrix),
        out_shape=jax.ShapeDtypeStruct((o, pw), jnp.uint32),
        grid=(pw // _TW,),
        in_specs=[pl.BlockSpec((s, _TW), lambda i: (0, i))],
        out_specs=pl.BlockSpec((o, _TW), lambda i: (0, i)),
        interpret=interpret,
    )(words)
    return out[:, :w] if pad else out


def matmul_words(
    matrix: np.ndarray, words, interpret: "bool | None" = None
):
    """(o, s) static GF matrix @ (s, w) uint32 shard words -> (o, w)."""
    o, s = matrix.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    key = np.ascontiguousarray(matrix, dtype=np.uint8).tobytes()
    return _matmul_words_jit(words, key, o, s, interpret)


def encode_words(data_words, parity_shards: int, interpret=None):
    """Pallas RS encode on packed words: (k, w) -> (m, w)."""
    k = data_words.shape[0]
    return matmul_words(
        gf.parity_matrix(k, parity_shards), data_words, interpret
    )


# ---------------------------------------------------------------------------
# Fused encode + bitrot hash (the PutObject device pass)
# ---------------------------------------------------------------------------


def _fused_kernel_factory(matrix: np.ndarray, tw: int):
    from . import hash as phash

    m, k = matrix.shape
    n = k + m

    def kernel(data_ref, parity_ref, hacc_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _zero():
            hacc_ref[...] = jnp.zeros_like(hacc_ref)

        data = data_ref[0]  # (k, tw)
        # ---- encode (same XOR chain as _swar_kernel, inlined) ----
        parity_rows = _swar_rows(matrix, data)
        all_rows = jnp.concatenate(
            [data, jnp.stack(parity_rows)], axis=0
        )  # (n, tw)
        parity_ref[0] = all_rows[k:]
        # ---- hash partials for this tile, all shards at once ----
        gidx = i * tw + jax.lax.broadcasted_iota(jnp.uint32, (1, tw), 1)
        key = phash._mix_jnp(gidx * phash._C1 + jnp.uint32(1))  # (1, tw)
        m1 = phash._mix_jnp((all_rows ^ key) * phash._M1)
        m2 = phash._mix_jnp((all_rows + key) * phash._M2)

        def red(x):
            # XOR-fold the lane dim down to 4: every halving step keeps
            # index-mod-4 classes intact (all widths are multiples of 4),
            # so the result is exactly the strided partition XOR.  Mosaic
            # has no reduce_xor and no lane-dim shape casts; slices + xor
            # lower cleanly.
            width = tw
            while width > 4:
                width //= 2
                x = x[:, :width] ^ x[:, width : 2 * width]
            return x  # (n, 4)

        partials = jnp.concatenate([red(m1), red(m2)], axis=1)  # (n, 8)
        hacc_ref[0] = hacc_ref[0] ^ partials

    return kernel


def _swar_rows(matrix: np.ndarray, data) -> list:
    """Shared XOR-chain: parity rows of a (k, t) uint32 tile (traced)."""
    o, s = matrix.shape
    need_bits = [
        max((int(matrix[r, c]).bit_length() for r in range(o)), default=0)
        for c in range(s)
    ]
    powers: list[list] = []
    for c in range(s):
        p = data[c, :]
        ps = [p]
        for _ in range(max(need_bits[c] - 1, 0)):
            p = rs._xtime(p)
            ps.append(p)
        powers.append(ps)
    rows = []
    for r in range(o):
        acc = None
        for c in range(s):
            coeff = int(matrix[r, c])
            for b in range(8):
                if (coeff >> b) & 1:
                    t = powers[c][b]
                    acc = t if acc is None else acc ^ t
        if acc is None:
            acc = jnp.zeros_like(data[0, :])
        rows.append(acc)
    return rows


@functools.partial(
    jax.jit, static_argnames=("parity_shards", "interpret")
)
def encode_hash_fused(words, parity_shards: int, interpret: bool = False):
    """One kernel pass: (B, k, w) data words -> ((B, m, w) parity words,
    (B, n, 8) un-finalized phash partials covering data AND parity rows).

    Grid is (batch, w-tiles); the hash-partial output block for a stripe is
    revisited across its w-tiles and XOR-accumulated in VMEM, so HBM
    traffic is exactly data-in + parity-out (data shards never round-trip:
    the host already holds their bytes).  Finalize partials with
    hash.finalize_partials(partials, shard_len_bytes).
    """
    B, k, w = words.shape
    m = parity_shards
    n = k + m
    matrix = gf.parity_matrix(k, m)
    if w % _TW:
        raise ValueError(f"words per shard ({w}) must be a multiple of {_TW}")
    kernel = _fused_kernel_factory(matrix, _TW)
    parity, hacc = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
            jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
        ),
        grid=(B, w // _TW),
        in_specs=[pl.BlockSpec((1, k, _TW), lambda b, i: (b, 0, i))],
        out_specs=(
            pl.BlockSpec((1, m, _TW), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, n, 8), lambda b, i: (b, 0, 0)),
        ),
        interpret=interpret,
    )(words)
    return parity, hacc


# ---------------------------------------------------------------------------
# MXU bit-matrix variant
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _bit_matrix(matrix_bytes: bytes, o: int, s: int) -> np.ndarray:
    """Lift an (o, s) GF(2^8) matrix to its (8o, 8s) GF(2) representation.

    Row 8r+t, column 8c+b is bit t of matrix[r,c] * x^b: the contribution
    of input-byte-c's bit b to output-byte-r's bit t.
    """
    matrix = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(o, s)
    out = np.zeros((8 * o, 8 * s), dtype=np.float32)
    for r in range(o):
        for c in range(s):
            v = int(matrix[r, c])
            for b in range(8):
                prod = gf.gf_mul(v, 1 << b)
                for t in range(8):
                    out[8 * r + t, 8 * c + b] = (prod >> t) & 1
    return out


def _mxu_kernel(mat_ref, data_ref, out_ref):
    o8 = mat_ref.shape[0]
    s, t = data_ref.shape
    x = data_ref[:].astype(jnp.int32)  # (s, T)
    bits = jnp.stack(
        [(x >> b) & 1 for b in range(8)], axis=1
    )  # (s, 8, T), row order 8c+b after reshape
    bits = bits.reshape(8 * s, t).astype(jnp.bfloat16)
    counts = jnp.dot(
        mat_ref[:].astype(jnp.bfloat16),
        bits,
        preferred_element_type=jnp.float32,
    )  # (8o, T); exact small integers
    pbits = counts.astype(jnp.int32) & 1
    pbits = pbits.reshape(o8 // 8, 8, t)
    acc = pbits[:, 0, :]
    for tbit in range(1, 8):
        acc = acc | (pbits[:, tbit, :] << tbit)
    out_ref[:] = acc.astype(jnp.uint8)


@functools.partial(
    jax.jit, static_argnames=("matrix_key", "o", "s", "interpret")
)
def _mxu_matmul_jit(shards, matrix_key: bytes, o: int, s: int, interpret):
    length = shards.shape[1]
    pad = (-length) % _T_BLK
    if pad:
        shards = jnp.pad(shards, ((0, 0), (0, pad)))
    plen = length + pad
    mat = jnp.asarray(_bit_matrix(matrix_key, o, s))
    out = pl.pallas_call(
        _mxu_kernel,
        out_shape=jax.ShapeDtypeStruct((o, plen), jnp.uint8),
        grid=(plen // _T_BLK,),
        in_specs=[
            pl.BlockSpec((8 * o, 8 * s), lambda i: (0, 0)),
            pl.BlockSpec((s, _T_BLK), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((o, _T_BLK), lambda i: (0, i)),
        interpret=interpret,
    )(mat, shards)
    return out[:, :length] if pad else out


def gf_matmul_mxu(
    matrix: np.ndarray, shards, interpret: "bool | None" = None
) -> jax.Array:
    """(o, s) GF matrix @ (s, length) u8 shards on the MXU (see module doc)."""
    o, s = matrix.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    shards = jnp.asarray(shards, dtype=jnp.uint8)
    key = np.ascontiguousarray(matrix, dtype=np.uint8).tobytes()
    return _mxu_matmul_jit(shards, key, o, s, interpret)
