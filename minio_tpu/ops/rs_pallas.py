"""Pallas TPU kernels for the GF(2^8) shard codec hot path.

Two device formulations of "GF matrix @ shards" (the klauspost/reedsolomon
role behind cmd/erasure-coding.go:54-64):

1. SWAR/VPU kernel (`matmul_words`, the default): shards live as uint32
   words (4 field elements per lane).  Multiply-by-constant uses the
   xtime-powers decomposition with the generator matrix baked into the
   kernel at trace time, so each tile is a straight-line XOR chain over
   VMEM-resident vectors - no tables, no gathers, no dtype conversions.
   Measured ~450 GiB/s data throughput at EC 8+4 on v5e-1 (HBM-bound:
   the kernel reads each data byte and writes each parity byte once).

2. MXU bit-matrix kernel (`gf_matmul_mxu`): GF(2^8) mul-by-constant is an
   8x8 linear map over GF(2), so the whole codec lifts to one
   (8o x 8s) @ (8s x T) bf16 matmul per tile, mod 2.  Higher arithmetic
   intensity but pays ~30 VPU ops/byte in bit unpack/repack, which caps it
   below the SWAR kernel at storage geometries (k <= 16).  Kept as the
   backend for very wide/dense matrices and as MXU reference.

Both run under interpret mode for CPU tests; production dispatch lives in
rs.encode / rs.reconstruct.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf, rs

# uint32 words per shard per tile (16 KiB of shard bytes per grid step)
_TW = 4096
# lane-dim tile for the MXU kernel: bytes per shard per grid step
_T_BLK = 8192


def _swar_kernel(matrix: np.ndarray):
    """Build a Pallas kernel computing out = matrix GF@ data over a tile.

    matrix (o, s) is a Python-time constant: zero coefficients and zero
    bits are pruned from the XOR chain at trace time, and xtime powers of
    each input row are materialized lazily up to the highest bit any
    coefficient in that column uses (see _swar_rows).
    """
    o, _ = matrix.shape

    def kernel(data_ref, out_ref):
        rows = _swar_rows(matrix, data_ref[...])
        for r in range(o):
            out_ref[r, :] = rows[r]

    return kernel


@functools.partial(
    jax.jit, static_argnames=("matrix_key", "o", "s", "interpret")
)
def _matmul_words_jit(
    words, matrix_key: bytes, o: int, s: int, interpret: bool
):
    matrix = np.frombuffer(matrix_key, dtype=np.uint8).reshape(o, s)
    w = words.shape[1]
    pad = (-w) % _TW
    if pad:
        words = jnp.pad(words, ((0, 0), (0, pad)))
    pw = w + pad
    out = pl.pallas_call(
        _swar_kernel(matrix),
        out_shape=jax.ShapeDtypeStruct((o, pw), jnp.uint32),
        grid=(pw // _TW,),
        in_specs=[pl.BlockSpec((s, _TW), lambda i: (0, i))],
        out_specs=pl.BlockSpec((o, _TW), lambda i: (0, i)),
        interpret=interpret,
    )(words)
    return out[:, :w] if pad else out


def matmul_words(
    matrix: np.ndarray, words, interpret: "bool | None" = None
):
    """(o, s) static GF matrix @ (s, w) uint32 shard words -> (o, w)."""
    o, s = matrix.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    key = np.ascontiguousarray(matrix, dtype=np.uint8).tobytes()
    return _matmul_words_jit(words, key, o, s, interpret)


def encode_words(data_words, parity_shards: int, interpret=None):
    """Pallas RS encode on packed words: (k, w) -> (m, w)."""
    k = data_words.shape[0]
    return matmul_words(
        gf.parity_matrix(k, parity_shards), data_words, interpret
    )


# ---------------------------------------------------------------------------
# Fused encode + bitrot hash (the PutObject device pass)
# ---------------------------------------------------------------------------


def _tile_hash_partials(all_rows, i, tw: int):
    """phash256 partials of (rows, tw) shard words at w-tile index i.

    Shared by every fused kernel; XOR-accumulate the (rows, 8) result
    into a revisited output block and finalize with
    hash.finalize_partials outside the kernel.
    """
    from . import hash as phash

    gidx = i * tw + jax.lax.broadcasted_iota(jnp.uint32, (1, tw), 1)
    key = phash._mix_jnp(gidx * phash._C1 + jnp.uint32(1))  # (1, tw)
    m1 = phash._mix_jnp((all_rows ^ key) * phash._M1)
    m2 = phash._mix_jnp((all_rows + key) * phash._M2)

    def red(x):
        # XOR-fold the lane dim down to 4: every halving step keeps
        # index-mod-4 classes intact (all widths are multiples of 4),
        # so the result is exactly the strided partition XOR.  Mosaic
        # has no reduce_xor and no lane-dim shape casts; slices + xor
        # lower cleanly.
        width = tw
        while width > 4:
            width //= 2
            x = x[:, :width] ^ x[:, width : 2 * width]
        return x  # (rows, 4)

    return jnp.concatenate([red(m1), red(m2)], axis=1)  # (rows, 8)


def _fused_kernel_factory(matrix: np.ndarray, tw: int):
    m, k = matrix.shape

    def kernel(data_ref, parity_ref, hacc_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _zero():
            hacc_ref[...] = jnp.zeros_like(hacc_ref)

        data = data_ref[0]  # (k, tw)
        # ---- encode (same XOR chain as _swar_kernel, inlined) ----
        parity_rows = _swar_rows(matrix, data)
        all_rows = jnp.concatenate(
            [data, jnp.stack(parity_rows)], axis=0
        )  # (n, tw)
        parity_ref[0] = all_rows[k:]
        # ---- hash partials for this tile, all shards at once ----
        hacc_ref[0] = hacc_ref[0] ^ _tile_hash_partials(all_rows, i, tw)

    return kernel


def _swar_rows(matrix: np.ndarray, data) -> list:
    """Shared XOR-chain: parity rows of a (k, t) uint32 tile (traced)."""
    o, s = matrix.shape
    need_bits = [
        max((int(matrix[r, c]).bit_length() for r in range(o)), default=0)
        for c in range(s)
    ]
    powers: list[list] = []
    for c in range(s):
        p = data[c, :]
        ps = [p]
        for _ in range(max(need_bits[c] - 1, 0)):
            p = rs._xtime(p)
            ps.append(p)
        powers.append(ps)
    rows = []
    for r in range(o):
        acc = None
        for c in range(s):
            coeff = int(matrix[r, c])
            for b in range(8):
                if (coeff >> b) & 1:
                    t = powers[c][b]
                    acc = t if acc is None else acc ^ t
        if acc is None:
            acc = jnp.zeros_like(data[0, :])
        rows.append(acc)
    return rows


@functools.partial(
    jax.jit, static_argnames=("parity_shards", "interpret")
)
def encode_hash_fused(words, parity_shards: int, interpret: bool = False):
    """One kernel pass: (B, k, w) data words -> ((B, m, w) parity words,
    (B, n, 8) un-finalized phash partials covering data AND parity rows).

    Grid is (batch, w-tiles); the hash-partial output block for a stripe is
    revisited across its w-tiles and XOR-accumulated in VMEM, so HBM
    traffic is exactly data-in + parity-out (data shards never round-trip:
    the host already holds their bytes).  Finalize partials with
    hash.finalize_partials(partials, shard_len_bytes).
    """
    B, k, w = words.shape
    m = parity_shards
    n = k + m
    matrix = gf.parity_matrix(k, m)
    if w % _TW:
        raise ValueError(f"words per shard ({w}) must be a multiple of {_TW}")
    kernel = _fused_kernel_factory(matrix, _TW)
    parity, hacc = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
            jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
        ),
        grid=(B, w // _TW),
        in_specs=[pl.BlockSpec((1, k, _TW), lambda b, i: (b, 0, i))],
        out_specs=(
            pl.BlockSpec((1, m, _TW), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, n, 8), lambda b, i: (b, 0, 0)),
        ),
        interpret=interpret,
    )(words)
    return parity, hacc


# ---------------------------------------------------------------------------
# MXU bit-matrix variant
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _bit_matrix(matrix_bytes: bytes, o: int, s: int) -> np.ndarray:
    """Lift an (o, s) GF(2^8) matrix to its (8o, 8s) GF(2) representation.

    Row 8r+t, column 8c+b is bit t of matrix[r,c] * x^b: the contribution
    of input-byte-c's bit b to output-byte-r's bit t.
    """
    matrix = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(o, s)
    out = np.zeros((8 * o, 8 * s), dtype=np.float32)
    for r in range(o):
        for c in range(s):
            v = int(matrix[r, c])
            for b in range(8):
                prod = gf.gf_mul(v, 1 << b)
                for t in range(8):
                    out[8 * r + t, 8 * c + b] = (prod >> t) & 1
    return out


def _mxu_kernel(mat_ref, data_ref, out_ref):
    o8 = mat_ref.shape[0]
    s, t = data_ref.shape
    x = data_ref[:].astype(jnp.int32)  # (s, T)
    bits = jnp.stack(
        [(x >> b) & 1 for b in range(8)], axis=1
    )  # (s, 8, T), row order 8c+b after reshape
    bits = bits.reshape(8 * s, t).astype(jnp.bfloat16)
    counts = jnp.dot(
        mat_ref[:].astype(jnp.bfloat16),
        bits,
        preferred_element_type=jnp.float32,
    )  # (8o, T); exact small integers
    pbits = counts.astype(jnp.int32) & 1
    pbits = pbits.reshape(o8 // 8, 8, t)
    acc = pbits[:, 0, :]
    for tbit in range(1, 8):
        acc = acc | (pbits[:, tbit, :] << tbit)
    out_ref[:] = acc.astype(jnp.uint8)


@functools.partial(
    jax.jit, static_argnames=("matrix_key", "o", "s", "interpret")
)
def _mxu_matmul_jit(shards, matrix_key: bytes, o: int, s: int, interpret):
    length = shards.shape[1]
    pad = (-length) % _T_BLK
    if pad:
        shards = jnp.pad(shards, ((0, 0), (0, pad)))
    plen = length + pad
    mat = jnp.asarray(_bit_matrix(matrix_key, o, s))
    out = pl.pallas_call(
        _mxu_kernel,
        out_shape=jax.ShapeDtypeStruct((o, plen), jnp.uint8),
        grid=(plen // _T_BLK,),
        in_specs=[
            pl.BlockSpec((8 * o, 8 * s), lambda i: (0, 0)),
            pl.BlockSpec((s, _T_BLK), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((o, _T_BLK), lambda i: (0, i)),
        interpret=interpret,
    )(mat, shards)
    return out[:, :length] if pad else out


def gf_matmul_mxu(
    matrix: np.ndarray, shards, interpret: "bool | None" = None
) -> jax.Array:
    """(o, s) GF matrix @ (s, length) u8 shards on the MXU (see module doc)."""
    o, s = matrix.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    shards = jnp.asarray(shards, dtype=jnp.uint8)
    key = np.ascontiguousarray(matrix, dtype=np.uint8).tobytes()
    return _mxu_matmul_jit(shards, key, o, s, interpret)


# ---------------------------------------------------------------------------
# One-kernel codec (fused1): single pass per direction
# ---------------------------------------------------------------------------


def _mxu_rows(matrix: np.ndarray, data, mat=None) -> list:
    """MXU formulation of _swar_rows: (s, t) u32 tile -> o output rows.

    Lifts the bytewise GF(2^8) product to the (8o, 8s) GF(2) bit matrix
    (_bit_matrix) and evaluates all four byte positions of every word in
    ONE bf16 matmul mod 2: the codec is byte-local, so byte positions
    stack on the lane dim.  Exact because every intermediate is a small
    integer (bit-counts <= 8s < 2^8) carried in f32.

    ``mat`` is the pre-lifted bit matrix when called inside a Pallas
    kernel (kernels cannot capture traced constants, so the caller
    threads it through an input ref); None rebuilds it from ``matrix``.
    """
    o, s = matrix.shape
    if o == 0:
        return []
    t = data.shape[-1]
    if mat is None:
        key = np.ascontiguousarray(matrix, dtype=np.uint8).tobytes()
        mat = jnp.asarray(_bit_matrix(key, o, s))
    mat = mat.astype(jnp.bfloat16)
    # (s, 4t): byte plane j of every word, side by side on the lane dim
    bts = jnp.concatenate(
        [(data >> jnp.uint32(8 * j)) & jnp.uint32(0xFF) for j in range(4)],
        axis=-1,
    ).astype(jnp.int32)
    bits = jnp.stack(
        [(bts >> b) & 1 for b in range(8)], axis=1
    )  # (s, 8, 4t): row order 8c+b after reshape
    bits = bits.reshape(8 * s, 4 * t).astype(jnp.bfloat16)
    counts = jnp.dot(mat, bits, preferred_element_type=jnp.float32)
    pbits = (counts.astype(jnp.int32) & 1).reshape(o, 8, 4 * t)
    acc8 = pbits[:, 0, :].astype(jnp.uint32)
    for tbit in range(1, 8):
        acc8 = acc8 | (pbits[:, tbit, :].astype(jnp.uint32) << tbit)
    out = acc8[:, :t]
    for j in range(1, 4):
        out = out | (acc8[:, j * t : (j + 1) * t] << jnp.uint32(8 * j))
    return [out[r] for r in range(o)]


def _rows_fn(formulation: str):
    if formulation == "swar":
        return _swar_rows
    if formulation == "mxu":
        return _mxu_rows
    raise ValueError(f"unknown codec formulation: {formulation!r}")


def _fused1_kernel_factory(
    matrix: np.ndarray, tw: int, group: int, formulation: str
):
    m, k = matrix.shape
    mxu = _rows_fn(formulation) is _mxu_rows
    gpt = tw // group if group else 0

    def impl(data_ref, parity_ref, hacc_ref, flags_ref, packed_ref,
             kept_ref, mat):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _zero():
            hacc_ref[...] = jnp.zeros_like(hacc_ref)
            if group:
                packed_ref[...] = jnp.zeros_like(packed_ref)
                for r in range(m):
                    kept_ref[r] = 0

        data = data_ref[0]  # (k, tw)
        parity_rows = (
            _mxu_rows(matrix, data, mat) if mxu else _swar_rows(matrix, data)
        )
        all_rows = jnp.concatenate(
            [data, jnp.stack(parity_rows)], axis=0
        )  # (n, tw)
        parity_ref[0] = all_rows[k:]
        hacc_ref[0] = hacc_ref[0] ^ _tile_hash_partials(all_rows, i, tw)
        if not group:
            return
        # ---- occupancy flags + prefix pack of this tile's groups ----
        # The packed row block is resident in VMEM for the whole w-tile
        # loop of a stripe; an SMEM counter per parity row carries the
        # next free group slot across the (sequential) grid steps.  Zero
        # groups are never stored: the row starts zeroed, which makes
        # the result bit-identical to the legacy argsort pack
        # (codec_step.pack_nonzero_groups).
        flags = []
        for r in range(m):
            flags.append(
                [
                    jnp.any(
                        parity_rows[r][j * group : (j + 1) * group] != 0
                    )
                    for j in range(gpt)
                ]
            )
        flags_ref[0] = jnp.stack(
            [jnp.stack(fr).astype(jnp.uint32) for fr in flags]
        )
        for r in range(m):
            off = kept_ref[r]
            for j in range(gpt):

                @pl.when(flags[r][j])
                def _store(off=off, r=r, j=j):
                    packed_ref[0, r, pl.ds(off * group, group)] = (
                        parity_rows[r][j * group : (j + 1) * group]
                    )

                off = off + flags[r][j].astype(jnp.int32)
            kept_ref[r] = off

    if mxu and group:

        def kernel(mat_ref, data_ref, parity_ref, hacc_ref, flags_ref,
                   packed_ref, kept_ref):
            impl(data_ref, parity_ref, hacc_ref, flags_ref, packed_ref,
                 kept_ref, mat_ref[...])

    elif mxu:

        def kernel(mat_ref, data_ref, parity_ref, hacc_ref):
            impl(data_ref, parity_ref, hacc_ref, None, None, None,
                 mat_ref[...])

    elif group:

        def kernel(data_ref, parity_ref, hacc_ref, flags_ref, packed_ref,
                   kept_ref):
            impl(data_ref, parity_ref, hacc_ref, flags_ref, packed_ref,
                 kept_ref, None)

    else:

        def kernel(data_ref, parity_ref, hacc_ref):
            impl(data_ref, parity_ref, hacc_ref, None, None, None, None)

    return kernel


def _mxu_operand(matrix: np.ndarray, grid_dims: int = 2):
    """(bit-matrix input list, matching in_spec list) for an MXU kernel.

    ``grid_dims`` picks the index-map arity: 2 for the (batch, w-tile)
    fused grids, 1 for the pipelined (batch,) grids whose w loop runs
    inside the kernel."""
    o, s = matrix.shape
    key = np.ascontiguousarray(matrix, dtype=np.uint8).tobytes()
    mat = jnp.asarray(_bit_matrix(key, o, s))
    index_map = (
        (lambda b: (0, 0)) if grid_dims == 1 else (lambda b, i: (0, 0))
    )
    return [mat], [pl.BlockSpec((8 * o, 8 * s), index_map)]


@functools.partial(
    jax.jit,
    static_argnames=("parity_shards", "group", "formulation", "interpret"),
)
def encode_pack_fused(
    words,
    parity_shards: int,
    group: int = 0,
    formulation: str = "swar",
    interpret: bool = False,
):
    """One-kernel PUT codec pass (fused1): parity + bitrot partials +
    group-occupancy flags + nonzero-group prefix pack, ONE pallas_call.

    words: (B, k, w) u32.  Returns (parity (B, m, w) u32, partials
    (B, n, 8) u32 un-finalized, flags (B, m, g) u32 0/1, packed
    (B, m, w) u32) with g = w // group.  group == 0 disables the pack
    leg: flags has g == 0 and packed aliases parity.

    Same grid as encode_hash_fused; the parity tile is additionally
    screened per 256-word group and nonzero groups are appended to the
    VMEM-resident packed row at the slot a per-row SMEM counter tracks
    (TPU grids run sequentially, so the counter survives the w-tile
    loop).  The raw parity plane is still emitted - the drain picks raw
    vs packed by fill AFTER the fact - and each data byte is read from
    HBM exactly once.
    """
    B, k, w = words.shape
    m = parity_shards
    n = k + m
    if m <= 0:
        raise ValueError("encode_pack_fused needs parity_shards >= 1")
    if w % _TW:
        raise ValueError(f"words per shard ({w}) must be a multiple of {_TW}")
    if group and _TW % group:
        raise ValueError(f"group must divide the {_TW}-word tile")
    matrix = gf.parity_matrix(k, m)
    kernel = _fused1_kernel_factory(matrix, _TW, group, formulation)
    extra_in, extra_specs = (
        _mxu_operand(matrix) if formulation == "mxu" else ([], [])
    )
    in_specs = extra_specs + [
        pl.BlockSpec((1, k, _TW), lambda b, i: (b, 0, i))
    ]
    if not group:
        parity, hacc = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
                jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
            ),
            grid=(B, w // _TW),
            in_specs=in_specs,
            out_specs=(
                pl.BlockSpec((1, m, _TW), lambda b, i: (b, 0, i)),
                pl.BlockSpec((1, n, 8), lambda b, i: (b, 0, 0)),
            ),
            interpret=interpret,
        )(*extra_in, words)
        return parity, hacc, jnp.zeros((B, m, 0), jnp.uint32), parity
    g = w // group
    gpt = _TW // group
    parity, hacc, flags, packed = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
            jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
            jax.ShapeDtypeStruct((B, m, g), jnp.uint32),
            jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
        ),
        grid=(B, w // _TW),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, m, _TW), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, n, 8), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, m, gpt), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, m, w), lambda b, i: (b, 0, 0)),
        ),
        scratch_shapes=[pltpu.SMEM((m,), jnp.int32)],
        interpret=interpret,
    )(*extra_in, words)
    return parity, hacc, flags, packed


def _vr_kernel_factory(
    rmatrix: np.ndarray, idx: tuple, n: int, tw: int, formulation: str
):
    mxu = _rows_fn(formulation) is _mxu_rows

    def impl(sh_ref, data_ref, hacc_ref, mat):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _zero():
            hacc_ref[...] = jnp.zeros_like(hacc_ref)

        sh = sh_ref[0]  # (n, tw), rows AS READ (absent rows: garbage)
        surv = jnp.stack([sh[j, :] for j in idx])  # (k, tw) static gather
        rows = (
            _mxu_rows(rmatrix, surv, mat) if mxu else _swar_rows(rmatrix, surv)
        )
        data_ref[0] = jnp.stack(rows)
        hacc_ref[0] = hacc_ref[0] ^ _tile_hash_partials(sh, i, tw)

    if mxu:

        def kernel(mat_ref, sh_ref, data_ref, hacc_ref):
            impl(sh_ref, data_ref, hacc_ref, mat_ref[...])

    else:

        def kernel(sh_ref, data_ref, hacc_ref):
            impl(sh_ref, data_ref, hacc_ref, None)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "present_idx",
        "data_shards",
        "parity_shards",
        "formulation",
        "interpret",
    ),
)
def verify_reconstruct_fused(
    shards,
    present_idx: tuple,
    data_shards: int,
    parity_shards: int,
    formulation: str = "swar",
    interpret: bool = False,
):
    """One-kernel GET codec pass: bitrot partials for every shard row +
    reconstruction from the static survivor set, ONE pallas_call.

    shards: (B, n, w) u32 as read; present_idx: the k survivor row
    indices (static).  Returns (data (B, k, w) u32, partials (B, n, 8)
    u32 un-finalized - finalize and compare against stored digests
    outside; each shard byte is read from HBM exactly once for both).
    """
    B, n, w = shards.shape
    k, m = data_shards, parity_shards
    if n != k + m:
        raise ValueError("shard rows must equal k + m")
    idx = tuple(int(i) for i in present_idx)
    if len(idx) != k:
        raise ValueError(f"need exactly {k} survivor indices, got {len(idx)}")
    if w % _TW:
        raise ValueError(f"words per shard ({w}) must be a multiple of {_TW}")
    rm = gf.reconstruction_matrix(k, m, idx)
    kernel = _vr_kernel_factory(rm, idx, n, _TW, formulation)
    extra_in, extra_specs = (
        _mxu_operand(rm) if formulation == "mxu" else ([], [])
    )
    data, hacc = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, k, w), jnp.uint32),
            jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
        ),
        grid=(B, w // _TW),
        in_specs=extra_specs
        + [pl.BlockSpec((1, n, _TW), lambda b, i: (b, 0, i))],
        out_specs=(
            pl.BlockSpec((1, k, _TW), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, n, 8), lambda b, i: (b, 0, 0)),
        ),
        interpret=interpret,
    )(*extra_in, shards)
    return data, hacc


# ---------------------------------------------------------------------------
# DMA-pipelined codec (MINIO_TPU_CODEC_OVERLAP=pipeline): manual
# double-buffered HBM<->VMEM staging inside ONE pallas_call per direction
# ---------------------------------------------------------------------------
#
# The fused1 kernels above lean on the blocked-grid pipeline Pallas
# derives from their BlockSpecs; these variants restructure the same
# math around explicit make_async_copy stages so the overlap is under
# our control and visible: the shard plane stays in ANY/HBM memory
# space, a 2-slot VMEM double buffer prefetches w-tile t+1 while tile t
# computes, and the parity (or reconstructed-data) tile of t-1 drains
# VMEM->HBM behind the compute - the three-deep sub-chunk pipeline of
# ROADMAP item 1, one level below the host's batch double buffering.
# Outputs are bit-identical to the fused kernels: the hash accumulator,
# occupancy flags and the packed row stay VMEM-resident across the
# in-kernel w loop exactly as the fused kernels carry them across grid
# steps.


def _pipe_encode_kernel_factory(
    matrix: np.ndarray, tw: int, group: int, formulation: str, nt: int
):
    m, k = matrix.shape
    mxu = _rows_fn(formulation) is _mxu_rows
    gpt = tw // group if group else 0

    def impl(data_hbm, parity_hbm, hacc_ref, flags_ref, packed_ref, mat):
        # hoisted: program_id inside lax.cond/fori closures does not
        # lower under interpret mode
        b = pl.program_id(0)
        hacc_ref[...] = jnp.zeros_like(hacc_ref)
        if group:
            packed_ref[...] = jnp.zeros_like(packed_ref)

        def scoped(in_vmem, par_vmem, in_sem, par_sem, kept_ref):
            def in_copy(t, slot):
                return pltpu.make_async_copy(
                    data_hbm.at[b, :, pl.ds(t * tw, tw)],
                    in_vmem.at[slot],
                    in_sem.at[slot],
                )

            def par_copy(t, slot):
                return pltpu.make_async_copy(
                    par_vmem.at[slot],
                    parity_hbm.at[b, :, pl.ds(t * tw, tw)],
                    par_sem.at[slot],
                )

            if group:
                for r in range(m):
                    kept_ref[r] = 0
            in_copy(0, 0).start()  # warm-up: stage tile 0

            def body(t, carry):
                slot = jax.lax.rem(t, 2)
                nslot = jax.lax.rem(t + 1, 2)

                @pl.when(t + 1 < nt)
                def _prefetch():
                    in_copy(t + 1, nslot).start()

                in_copy(t, slot).wait()
                data = in_vmem[slot]
                parity_rows = (
                    _mxu_rows(matrix, data, mat)
                    if mxu
                    else _swar_rows(matrix, data)
                )
                all_rows = jnp.concatenate(
                    [data, jnp.stack(parity_rows)], axis=0
                )
                par_vmem[slot] = all_rows[k:]
                hacc_ref[0] = hacc_ref[0] ^ _tile_hash_partials(
                    all_rows, t, tw
                )
                par_copy(t, slot).start()
                if group:
                    flags = [
                        [
                            jnp.any(
                                parity_rows[r][
                                    j * group : (j + 1) * group
                                ]
                                != 0
                            )
                            for j in range(gpt)
                        ]
                        for r in range(m)
                    ]
                    flags_ref[0, :, pl.ds(t * gpt, gpt)] = jnp.stack(
                        [
                            jnp.stack(fr).astype(jnp.uint32)
                            for fr in flags
                        ]
                    )
                    for r in range(m):
                        off = kept_ref[r]
                        for j in range(gpt):

                            @pl.when(flags[r][j])
                            def _store(off=off, r=r, j=j):
                                packed_ref[
                                    0, r, pl.ds(off * group, group)
                                ] = parity_rows[r][
                                    j * group : (j + 1) * group
                                ]

                            off = off + flags[r][j].astype(jnp.int32)
                        kept_ref[r] = off

                @pl.when(t >= 1)
                def _drain_prev():
                    par_copy(t - 1, nslot).wait()

                return carry

            jax.lax.fori_loop(0, nt, body, 0)
            par_copy(nt - 1, (nt - 1) % 2).wait()

        pl.run_scoped(
            scoped,
            in_vmem=pltpu.VMEM((2, k, tw), jnp.uint32),
            par_vmem=pltpu.VMEM((2, m, tw), jnp.uint32),
            in_sem=pltpu.SemaphoreType.DMA((2,)),
            par_sem=pltpu.SemaphoreType.DMA((2,)),
            kept_ref=pltpu.SMEM((max(m, 1),), jnp.int32),
        )

    if mxu and group:

        def kernel(mat_ref, data_hbm, parity_hbm, hacc_ref, flags_ref,
                   packed_ref):
            impl(data_hbm, parity_hbm, hacc_ref, flags_ref, packed_ref,
                 mat_ref[...])

    elif mxu:

        def kernel(mat_ref, data_hbm, parity_hbm, hacc_ref):
            impl(data_hbm, parity_hbm, hacc_ref, None, None, mat_ref[...])

    elif group:

        def kernel(data_hbm, parity_hbm, hacc_ref, flags_ref, packed_ref):
            impl(data_hbm, parity_hbm, hacc_ref, flags_ref, packed_ref,
                 None)

    else:

        def kernel(data_hbm, parity_hbm, hacc_ref):
            impl(data_hbm, parity_hbm, hacc_ref, None, None, None)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("parity_shards", "group", "formulation", "interpret"),
)
def encode_pack_pipelined(
    words,
    parity_shards: int,
    group: int = 0,
    formulation: str = "swar",
    interpret: bool = False,
):
    """DMA-pipelined twin of encode_pack_fused: same outputs, same ONE
    pallas_call, but the w loop runs inside the kernel with manual
    double-buffered async copies so tile t+1's HBM->VMEM staging and
    tile t-1's parity VMEM->HBM drain overlap tile t's compute.

    Bit-identity contract (non-negotiable, tests/test_overlap.py):
    parity, un-finalized hash partials and flags are element-identical
    to encode_pack_fused; ``packed`` agrees on the compacted prefix
    [0, kept_r*group) of every row — all the drain ever reads
    (compress.unpack_nonzero_groups) — with zeros behind it.
    """
    B, k, w = words.shape
    m = parity_shards
    n = k + m
    if m <= 0:
        raise ValueError("encode_pack_pipelined needs parity_shards >= 1")
    if w % _TW:
        raise ValueError(f"words per shard ({w}) must be a multiple of {_TW}")
    if group and _TW % group:
        raise ValueError(f"group must divide the {_TW}-word tile")
    nt = w // _TW
    matrix = gf.parity_matrix(k, m)
    kernel = _pipe_encode_kernel_factory(
        matrix, _TW, group, formulation, nt
    )
    extra_in, extra_specs = (
        _mxu_operand(matrix, grid_dims=1)
        if formulation == "mxu"
        else ([], [])
    )
    in_specs = extra_specs + [pl.BlockSpec(memory_space=pltpu.ANY)]
    if not group:
        parity, hacc = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
                jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
            ),
            grid=(B,),
            in_specs=in_specs,
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec((1, n, 8), lambda b: (b, 0, 0)),
            ),
            interpret=interpret,
        )(*extra_in, words)
        return parity, hacc, jnp.zeros((B, m, 0), jnp.uint32), parity
    g = w // group
    parity, hacc, flags, packed = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
            jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
            jax.ShapeDtypeStruct((B, m, g), jnp.uint32),
            jax.ShapeDtypeStruct((B, m, w), jnp.uint32),
        ),
        grid=(B,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((1, n, 8), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, m, g), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, m, w), lambda b: (b, 0, 0)),
        ),
        interpret=interpret,
    )(*extra_in, words)
    return parity, hacc, flags, packed


def _pipe_vr_kernel_factory(
    rmatrix: np.ndarray,
    idx: tuple,
    n: int,
    tw: int,
    formulation: str,
    nt: int,
):
    mxu = _rows_fn(formulation) is _mxu_rows
    k = rmatrix.shape[0]

    def impl(sh_hbm, data_hbm, hacc_ref, mat):
        b = pl.program_id(0)  # hoisted (see _pipe_encode_kernel_factory)
        hacc_ref[...] = jnp.zeros_like(hacc_ref)

        def scoped(in_vmem, out_vmem, in_sem, out_sem):
            def in_copy(t, slot):
                return pltpu.make_async_copy(
                    sh_hbm.at[b, :, pl.ds(t * tw, tw)],
                    in_vmem.at[slot],
                    in_sem.at[slot],
                )

            def out_copy(t, slot):
                return pltpu.make_async_copy(
                    out_vmem.at[slot],
                    data_hbm.at[b, :, pl.ds(t * tw, tw)],
                    out_sem.at[slot],
                )

            in_copy(0, 0).start()

            def body(t, carry):
                slot = jax.lax.rem(t, 2)
                nslot = jax.lax.rem(t + 1, 2)

                @pl.when(t + 1 < nt)
                def _prefetch():
                    in_copy(t + 1, nslot).start()

                in_copy(t, slot).wait()
                sh = in_vmem[slot]  # (n, tw), rows AS READ
                surv = jnp.stack([sh[j, :] for j in idx])
                rows = (
                    _mxu_rows(rmatrix, surv, mat)
                    if mxu
                    else _swar_rows(rmatrix, surv)
                )
                out_vmem[slot] = jnp.stack(rows)
                hacc_ref[0] = hacc_ref[0] ^ _tile_hash_partials(sh, t, tw)
                out_copy(t, slot).start()

                @pl.when(t >= 1)
                def _drain_prev():
                    out_copy(t - 1, nslot).wait()

                return carry

            jax.lax.fori_loop(0, nt, body, 0)
            out_copy(nt - 1, (nt - 1) % 2).wait()

        pl.run_scoped(
            scoped,
            in_vmem=pltpu.VMEM((2, n, tw), jnp.uint32),
            out_vmem=pltpu.VMEM((2, k, tw), jnp.uint32),
            in_sem=pltpu.SemaphoreType.DMA((2,)),
            out_sem=pltpu.SemaphoreType.DMA((2,)),
        )

    if mxu:

        def kernel(mat_ref, sh_hbm, data_hbm, hacc_ref):
            impl(sh_hbm, data_hbm, hacc_ref, mat_ref[...])

    else:

        def kernel(sh_hbm, data_hbm, hacc_ref):
            impl(sh_hbm, data_hbm, hacc_ref, None)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "present_idx",
        "data_shards",
        "parity_shards",
        "formulation",
        "interpret",
    ),
)
def verify_reconstruct_pipelined(
    shards,
    present_idx: tuple,
    data_shards: int,
    parity_shards: int,
    formulation: str = "swar",
    interpret: bool = False,
):
    """DMA-pipelined twin of verify_reconstruct_fused (same outputs,
    one pallas_call): shard-tile staging, the verify+reconstruct
    compute, and the reconstructed-data drain overlap per w-tile."""
    B, n, w = shards.shape
    k, m = data_shards, parity_shards
    if n != k + m:
        raise ValueError("shard rows must equal k + m")
    idx = tuple(int(i) for i in present_idx)
    if len(idx) != k:
        raise ValueError(f"need exactly {k} survivor indices, got {len(idx)}")
    if w % _TW:
        raise ValueError(f"words per shard ({w}) must be a multiple of {_TW}")
    nt = w // _TW
    rm = gf.reconstruction_matrix(k, m, idx)
    kernel = _pipe_vr_kernel_factory(rm, idx, n, _TW, formulation, nt)
    extra_in, extra_specs = (
        _mxu_operand(rm, grid_dims=1) if formulation == "mxu" else ([], [])
    )
    data, hacc = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, k, w), jnp.uint32),
            jax.ShapeDtypeStruct((B, n, 8), jnp.uint32),
        ),
        grid=(B,),
        in_specs=extra_specs + [pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((1, n, 8), lambda b: (b, 0, 0)),
        ),
        interpret=interpret,
    )(*extra_in, shards)
    return data, hacc
