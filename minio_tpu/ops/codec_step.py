"""Fused erasure data-plane steps: one device pass per stripe batch.

The reference's PutObject hot loop does RS-encode on CPU and then streams
each shard through a HighwayHash writer (cmd/erasure-encode.go:73-109 +
cmd/bitrot-streaming.go:38-88) - two passes over every byte.  Here both
happen in a single fused XLA program per batch: parity generation and the
per-shard bitrot digest read each byte from HBM once.

These are the kernels the object layer batches concurrent requests into
(the analogue of erasure-sets feeding per-disk queues).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf, hash as phash, rs


@functools.partial(jax.jit, static_argnames=("parity_shards",))
def encode_and_hash(data: jax.Array, parity_shards: int):
    """Encode + bitrot-hash a batch of stripes in one fused pass.

    data: (batch, k, shard_len) uint8, shard_len % 32 == 0.
    Returns (shards, digests):
      shards:  (batch, k+m, shard_len) uint8 - data rows then parity rows
               (the write fan-out order of cmd/erasure-encode.go:39-54)
      digests: (batch, k+m, 8) uint32 phash256 per shard block.
    """
    batch, k, shard_len = data.shape
    m = parity_shards
    if shard_len % 32:
        raise ValueError("shard_len must be a multiple of 32 bytes")
    matrix = gf.parity_matrix(k, m)

    def one(stripe: jax.Array):
        words = rs.bytes_to_words(stripe)  # (k, w)
        parity = rs._encode_words(words, matrix)  # (m, w)
        all_words = jnp.concatenate([words, parity], axis=0)
        digests = jax.vmap(
            lambda w: phash.phash256_words(w, shard_len)
        )(all_words)
        return rs.words_to_bytes(all_words), digests

    return jax.vmap(one)(data)


@functools.partial(jax.jit, static_argnames=("shard_len",))
def verify_hashes(shards: jax.Array, digests: jax.Array, shard_len: int):
    """Recompute phash256 for (batch, n, shard_len) shards, compare.

    Returns (batch, n) bool - True where the shard is intact.  This is the
    read-side bitrot verification (cmd/bitrot-streaming.go:130-146 /
    xl-storage.go bitrotVerify) as one device pass over all shards.
    """
    def one(shard, want):
        words = rs.bytes_to_words(shard)
        got = phash.phash256_words(words, shard_len)
        return jnp.all(got == want)

    return jax.vmap(jax.vmap(one))(shards, digests)


@functools.partial(jax.jit, static_argnames=("parity_shards", "reps"))
def encode_throughput_probe(data: jax.Array, parity_shards: int, reps: int):
    """Run `reps` dependent encode+hash passes inside ONE device program.

    Benchmarking aid: chains iterations through a cheap XOR so XLA cannot
    elide work, letting per-pass device time be measured without host
    launch overhead (significant over the dev relay).  Returns a small
    checksum array.
    """
    k = data.shape[1]

    def body(carry, _):
        shards, digests = encode_and_hash(carry, parity_shards)
        nxt = shards[:, :k] ^ shards[:, k : k + 1]
        return nxt, digests[0, 0, 0]

    final, sums = jax.lax.scan(body, data, None, length=reps)
    return final[0, 0, :8], sums


@functools.partial(
    jax.jit,
    static_argnames=("present", "data_shards", "parity_shards", "reps"),
)
def reconstruct_throughput_probe(
    shards: jax.Array,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
    reps: int,
):
    """Chained batched static-pattern reconstructs (see encode probe)."""
    from . import rs as _rs

    def one(s):
        return _rs._reconstruct_static_jit(
            s, present, data_shards, parity_shards, False
        )

    def body(carry, _):
        data = jax.vmap(one)(carry)
        nxt = carry ^ jnp.concatenate(
            [data, jnp.zeros_like(carry[:, data_shards:])], axis=1
        )
        return nxt, data[0, 0, 0]

    final, sums = jax.lax.scan(body, shards, None, length=reps)
    return final[0, 0, :8], sums


def decode_and_verify(
    shards: np.ndarray,
    digests: np.ndarray,
    data_shards: int,
    parity_shards: int,
):
    """Read-path step: verify bitrot, reconstruct from intact shards.

    Host-driven composition of verify_hashes + rs.reconstruct (the
    erasure-decode.go:211-290 Decode semantics: verify every block read,
    escalate to parity on failure, flag heal when any shard was bad).

    Returns (data, ok_mask): data (k, shard_len) uint8, ok_mask (n,) bool.
    Raises ValueError when fewer than k shards are intact (errXLReadQuorum
    analogue).
    """
    n = data_shards + parity_shards
    shard_len = shards.shape[-1]
    ok = np.asarray(
        verify_hashes(shards[None], digests[None], shard_len)[0]
    )
    if int(ok.sum()) < data_shards:
        raise ValueError(
            f"bitrot: only {int(ok.sum())}/{n} shards intact, "
            f"need {data_shards}"
        )
    data = rs.reconstruct(shards, ok, data_shards, parity_shards)
    return data, ok
