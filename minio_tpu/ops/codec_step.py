"""Fused erasure data-plane steps: one device pass per stripe batch.

The reference's PutObject hot loop does RS-encode on CPU and then streams
each shard through a HighwayHash writer (cmd/erasure-encode.go:73-109 +
cmd/bitrot-streaming.go:38-88) - two passes over every byte.  Here both
happen in a single fused device pass per batch: parity generation and the
per-shard bitrot digest read each data byte from HBM once, and only parity
+ digests leave the device (the host already holds the data bytes).

Layout contract: the device works exclusively on uint32 "words" (4 field
elements per lane).  uint8<->uint32 bitcasts on TPU are full relayouts
((32,128) vs (8,128) tiling) costing more than the codec itself, so byte
views happen host-side where numpy's .view() is free.  Use
host_bytes_to_words / host_words_to_bytes at the boundary.

These are the kernels the object layer batches concurrent requests into
(the analogue of erasure-sets feeding per-disk queues).
"""

from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from . import gf, hash as phash, rs, rs_pallas

# encode_and_hash_words_digest donates its input buffer so the device
# reuses the H2D staging allocation for parity; on host-only platforms
# (the CPU test backend) XLA cannot always honor the donation and says
# so per call — that is expected there, not a bug worth a warning storm.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


def host_bytes_to_words(a: np.ndarray) -> np.ndarray:
    """(..., L) uint8 -> (..., L//4) uint32 view (host, zero-copy)."""
    assert a.dtype == np.uint8 and a.shape[-1] % 4 == 0
    a = np.ascontiguousarray(a)
    return a.view(np.uint32)


def host_words_to_bytes(a: np.ndarray) -> np.ndarray:
    """(..., w) uint32 -> (..., 4w) uint8 view (host, zero-copy)."""
    assert a.dtype == np.uint32
    return np.ascontiguousarray(a).view(np.uint8)


@functools.partial(jax.jit, static_argnames=("parity_shards", "shard_len"))
def encode_and_hash_words(
    words: jax.Array, parity_shards: int, shard_len: int
):
    """Encode + bitrot-hash a batch of stripes in one fused pass.

    words: (batch, k, w) uint32 data shards; shard_len = 4*w (bytes).
    Returns (parity, digests):
      parity:  (batch, m, w) uint32 parity shards
      digests: (batch, k+m, 8) uint32 finalized phash256 per shard
               (data rows first, then parity - the fan-out order of
               cmd/erasure-encode.go:39-54).
    """
    batch, k, w = words.shape
    m = parity_shards
    if shard_len != 4 * w:
        raise ValueError("shard_len must equal 4 * words-per-shard")
    if w % 8:
        raise ValueError("words per shard must be a multiple of 8")
    matrix = gf.parity_matrix(k, m)

    if jax.default_backend() == "tpu" and w % rs_pallas._TW == 0:
        parity, partials = rs_pallas.encode_hash_fused(words, m)
        return parity, phash.finalize_partials(partials, shard_len)

    # Portable path: RS is column-local, so a batch is ONE flat encode of
    # (k, B*w) - no vmap-of-small-ops - and hashing is one batched pass.
    flat = words.transpose(1, 0, 2).reshape(k, batch * w)
    parity = rs._matmul_static(flat, matrix).reshape(m, batch, w)
    aw = jnp.concatenate(
        [words.transpose(1, 0, 2), parity], axis=0
    )  # (n, B, w)
    digests = phash.phash256_words_batched(aw, shard_len)  # (n, B, 8)
    return parity.transpose(1, 0, 2), digests.transpose(1, 0, 2)


@functools.partial(
    jax.jit,
    static_argnames=("parity_shards", "shard_len"),
    donate_argnums=(0,),
)
def encode_and_hash_words_digest(
    words: jax.Array, parity_shards: int, shard_len: int
):
    """Digest-only fused encode: the device-resident-parity variant.

    Same math and same outputs as encode_and_hash_words, with two
    contract differences the PUT pipeline builds on:

    * ``words`` is DONATED — the H2D input buffer is dead after the
      pass, so XLA may reuse it for parity instead of allocating, and
      the caller must not touch its jax copy again.
    * The caller materializes ONLY ``digests`` eagerly (32 bytes per
      shard — all encode_end needs to frame bitrot metadata and ack);
      ``parity`` stays a device array parked in the backend's parity
      plane cache until the write path drains it D2H lazily.
    """
    return encode_and_hash_words(words, parity_shards, shard_len)


@functools.partial(jax.jit, static_argnames=("group",))
def group_flags(words: jax.Array, group: int):
    """Per-group nonzero flags: (..., w) u32 -> (..., w//group) bool.

    The cheap compressibility screen for the parity D2H transport:
    reading the flags costs one bool per ``group`` words, and a mostly-
    False mask means pack_nonzero_groups can shrink the bus transfer.
    """
    *lead, w = words.shape
    if w % group:
        raise ValueError("words per row must be a multiple of group")
    g = w // group
    return (words.reshape(*lead, g, group) != 0).any(axis=-1)


@functools.partial(jax.jit, static_argnames=("group",))
def pack_nonzero_groups(words: jax.Array, group: int):
    """Compact nonzero groups to the front of each row (device side).

    (..., w) u32 -> (flags (..., g) bool, packed (..., w) u32) where
    g = w // group.  Within each row the nonzero groups keep their
    original relative order at the front and the zero groups follow, so
    the host only pulls ``flags`` plus the first ``flags.sum()`` groups
    over the bus and scatters them back by np.nonzero(flags) — the
    fused on-device compression leg of the parity transport
    (codec/compress.py unpack_nonzero_groups is the inverse).
    """
    *lead, w = words.shape
    if w % group:
        raise ValueError("words per row must be a multiple of group")
    g = w // group
    grouped = words.reshape(*lead, g, group)
    flags = (grouped != 0).any(axis=-1)
    # unique, strictly ordered sort keys (nonzero group j -> j, zero
    # group j -> g + j): the permutation is deterministic without
    # leaning on argsort stability guarantees
    idx = jnp.arange(g, dtype=jnp.int32)
    key = jnp.where(flags, 0, jnp.int32(g)) + idx
    order = jnp.argsort(key, axis=-1)
    packed = jnp.take_along_axis(
        grouped, order[..., None], axis=-2
    ).reshape(*lead, w)
    return flags, packed


# ---------------------------------------------------------------------------
# One-kernel codec (fused1): PUT and GET as one device pass per direction
# ---------------------------------------------------------------------------


def codec_kernel_mode() -> str:
    """MINIO_TPU_CODEC_KERNEL: ``fused1`` (default) or ``legacy``.

    ``legacy`` is the bisection oracle: the exact pre-fusion pass
    structure (digest encode pass, then group_flags, then
    pack_nonzero_groups at drain; verify then reconstruct on heal) with
    byte-identical outputs.  Flip it to attribute a regression to the
    fused kernels vs everything around them.
    """
    v = os.environ.get("MINIO_TPU_CODEC_KERNEL", "fused1").strip().lower()
    return v if v in ("fused1", "legacy") else "fused1"


def codec_formulation() -> str:
    """MINIO_TPU_CODEC_FORMULATION: ``swar`` (default) or ``mxu``.

    Picks the GF(2^8) matrix-product formulation inside the fused
    kernels (see rs_pallas module doc); both are bit-exact.
    """
    v = os.environ.get(
        "MINIO_TPU_CODEC_FORMULATION", "swar"
    ).strip().lower()
    return v if v in ("swar", "mxu") else "swar"


def codec_overlap_mode() -> str:
    """MINIO_TPU_CODEC_OVERLAP: ``pipeline`` | ``async`` | ``off``.

    The device-side transfer/compute overlap seam (ROADMAP item 1):

    * ``pipeline`` — the Pallas DMA pipeline: the fused1 kernels run
      with an in-kernel w loop and manual double-buffered async copies
      (rs_pallas.encode_pack_pipelined / verify_reconstruct_pipelined),
      still ONE pallas_call per direction.  Needs the Pallas path
      (TPU, or MINIO_TPU_CODEC_INTERPRET=1).
    * ``async`` — the portable sub-chunk twin: the stripe batch splits
      along w into S sub-chunks double-buffered through donated
      ping-pong device buffers (encode_subchunk_words), so sub-chunk
      N+1's H2D overlaps N's pass which overlaps N-1's drain on any
      backend.  Honest about launches: S passes per direction.
    * ``off`` — the serialized PR 14 path, the bisection oracle.

    Default: ``pipeline`` on TPU, ``off`` elsewhere (on a host backend
    the serialized path is already compute-bound; the overlap win is
    the TPU bus/VPU story and CI exercises both modes explicitly).
    """
    v = os.environ.get("MINIO_TPU_CODEC_OVERLAP", "").strip().lower()
    if v in ("pipeline", "async", "off"):
        return v
    return "pipeline" if jax.default_backend() == "tpu" else "off"


def pallas_dispatch(words_per_shard: int) -> tuple[bool, bool]:
    """(use_pallas, interpret) statics for the fused1 entry points.

    Pallas runs compiled on TPU; MINIO_TPU_CODEC_INTERPRET=1 forces the
    interpreter on other backends (the CI kernel-regression mode,
    mirroring MINIO_TPU_SANITIZE); everything else takes the portable
    XLA path inside the same jit program, which is the same math.
    """
    if words_per_shard % rs_pallas._TW:
        return False, False
    if jax.default_backend() == "tpu":
        return True, False
    if os.environ.get("MINIO_TPU_CODEC_INTERPRET") == "1":
        return True, True
    return False, False


@functools.partial(
    jax.jit,
    static_argnames=(
        "parity_shards",
        "shard_len",
        "group",
        "formulation",
        "use_pallas",
        "interpret",
        "pipeline",
    ),
    donate_argnums=(0,),
)
def encode_words_fused1(
    words: jax.Array,
    parity_shards: int,
    shard_len: int,
    group: int = 0,
    formulation: str = "swar",
    use_pallas: bool = False,
    interpret: bool = False,
    pipeline: bool = False,
):
    """fused1 PUT codec step: parity + digests + occupancy + pack in ONE
    device pass.

    The legacy pipeline runs encode_and_hash_words_digest, then
    group_flags, then pack_nonzero_groups at drain time - three jitted
    passes re-reading the parity plane from HBM.  This entry fuses all
    three: on TPU (or under interpret) it is exactly one pallas_call
    (rs_pallas.encode_pack_fused); elsewhere it is one portable XLA
    program with the same math.

    words: (B, k, w) u32, DONATED like encode_and_hash_words_digest.
    Returns (parity (B, m, w) u32, digests (B, n, 8) u32 finalized,
    flags (B, m, g) bool, packed (B, m, w) u32) with g = w // group;
    group == 0 disables the pack leg (flags has g == 0, packed aliases
    parity).  Only ``digests`` may be materialized eagerly (MTPU107);
    parity/flags/packed park in the parity plane cache until drain.
    """
    batch, k, w = words.shape
    m = parity_shards
    if shard_len != 4 * w:
        raise ValueError("shard_len must equal 4 * words-per-shard")
    if w % 8:
        raise ValueError("words per shard must be a multiple of 8")
    if group and w % group:
        raise ValueError("words per shard must be a multiple of group")

    if use_pallas and m > 0 and w % rs_pallas._TW == 0:
        # pipeline=True swaps in the manual-DMA variant (same outputs,
        # same single pallas_call): MINIO_TPU_CODEC_OVERLAP=pipeline
        enc = (
            rs_pallas.encode_pack_pipelined
            if pipeline
            else rs_pallas.encode_pack_fused
        )
        parity, partials, flags_u, packed = enc(
            words,
            m,
            group=group,
            formulation=formulation,
            interpret=interpret,
        )
        digests = phash.finalize_partials(partials, shard_len)
        return parity, digests, flags_u != 0, packed

    # Portable single-program path: the legacy three-pass math
    # (encode_and_hash_words + group_flags + pack_nonzero_groups) fused
    # into one XLA program - the bit-identity oracle for the kernel.
    if m > 0:
        matrix = gf.parity_matrix(k, m)
        flat = words.transpose(1, 0, 2).reshape(k, batch * w)
        parity = rs._matmul_static(flat, matrix).reshape(m, batch, w)
        aw = jnp.concatenate([words.transpose(1, 0, 2), parity], axis=0)
        parity = parity.transpose(1, 0, 2)
    else:
        parity = jnp.zeros((batch, 0, w), jnp.uint32)
        aw = words.transpose(1, 0, 2)
    digests = phash.phash256_words_batched(aw, shard_len).transpose(1, 0, 2)
    if not group:
        return parity, digests, jnp.zeros((batch, m, 0), bool), parity
    g = w // group
    grouped = parity.reshape(batch, m, g, group)
    flags = (grouped != 0).any(axis=-1)
    idx = jnp.arange(g, dtype=jnp.int32)
    key = jnp.where(flags, 0, jnp.int32(g)) + idx
    order = jnp.argsort(key, axis=-1)
    packed = jnp.take_along_axis(
        grouped, order[..., None], axis=-2
    ).reshape(batch, m, w)
    return parity, digests, flags, packed


@functools.partial(
    jax.jit,
    static_argnames=(
        "present",
        "data_shards",
        "parity_shards",
        "shard_len",
        "formulation",
        "use_pallas",
        "interpret",
        "pipeline",
    ),
)
def verify_and_reconstruct_words(
    shards: jax.Array,
    digests: jax.Array,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
    shard_len: int,
    formulation: str = "swar",
    use_pallas: bool = False,
    interpret: bool = False,
    pipeline: bool = False,
):
    """fused1 GET codec step: digest-verify + reconstruct in ONE pass.

    Replaces the verify_hashes_words -> reconstruct_words_batch pair on
    the quorum-read/heal path: one pallas_call (or one portable XLA
    program) reads each shard byte once for both the bitrot check and
    the RS product.

    shards: (B, n, w) u32 as read (absent rows hold garbage); digests:
    (B, n, 8) u32 stored; present: static per-row availability.
    Returns (data (B, k, w) u32 reconstructed from the first k present
    rows, ok (B, n) bool = digest match AND present).  The caller
    rechecks ok over its chosen survivors and re-solves per-stripe when
    one was corrupt (backend reconstruct_and_verify escalation).
    """
    k, m = data_shards, parity_shards
    B, n, w = shards.shape
    if shard_len != 4 * w:
        raise ValueError("shard_len must equal 4 * words-per-shard")
    idx = [i for i, p in enumerate(present) if p][:k]
    if len(idx) < k:
        raise ValueError(f"need {k} shards, have {len(idx)}")
    pres = jnp.asarray(np.asarray(present, dtype=bool))
    if use_pallas and w % rs_pallas._TW == 0:
        vr = (
            rs_pallas.verify_reconstruct_pipelined
            if pipeline
            else rs_pallas.verify_reconstruct_fused
        )
        data, partials = vr(
            shards,
            tuple(idx),
            k,
            m,
            formulation=formulation,
            interpret=interpret,
        )
        got = phash.finalize_partials(partials, shard_len)
    else:
        got = phash.phash256_words_batched(shards, shard_len)
        rm = gf.reconstruction_matrix(k, m, tuple(idx))
        flat = shards.transpose(1, 0, 2).reshape(n, B * w)
        surv = jnp.stack([flat[i] for i in idx])
        data = (
            rs._matmul_static(surv, rm).reshape(k, B, w).transpose(1, 0, 2)
        )
    ok = jnp.all(got == digests, axis=-1) & pres
    return data, ok


# ---------------------------------------------------------------------------
# Sub-chunked async twin (MINIO_TPU_CODEC_OVERLAP=async): the portable
# double-buffered pipeline for non-TPU backends and interpret/CI mode
# ---------------------------------------------------------------------------
#
# The stripe batch splits along w into S sub-chunks; the backend stages
# chunk s+1 H2D (jax.device_put is async) while chunk s's pass runs and
# chunk s-1's results drain.  RS parity is column-local, so per-chunk
# parity is exact; the phash256 partials XOR-accumulate across chunks
# through a DONATED (B, n, 8) ping-pong accumulator whose key uses the
# GLOBAL word offset (hash.tile_partials_batched), and the LAST chunk
# finalizes in the same program — zero extra launches for the digest.
# ``word_offset`` is traced, so every equal-sized chunk of a stream
# shares one compiled program.


@functools.partial(
    jax.jit,
    static_argnames=("parity_shards", "shard_len", "group", "finalize"),
    donate_argnums=(0, 1),
)
def encode_subchunk_words(
    chunk: jax.Array,
    acc: jax.Array,
    word_offset,
    parity_shards: int,
    shard_len: int,
    group: int = 0,
    finalize: bool = False,
):
    """One PUT sub-chunk: parity + hash partials (+ flags/pack) for a
    (B, k, cw) u32 slice of the stripe batch at global ``word_offset``.

    ``chunk`` and ``acc`` are DONATED — the staging buffer dies into
    the parity allocation and the partial accumulator ping-pongs
    through the chunk chain.  Returns (parity (B, m, cw), acc' (B, n,
    8) — FINALIZED digests when ``finalize``, raw partials otherwise,
    flags (B, m, gc) bool, packed (B, m, cw)); group == 0 disables the
    pack leg exactly like encode_words_fused1.  ``shard_len`` is the
    FULL row byte length (the digest length-fold), not the chunk's.
    """
    B, k, cw = chunk.shape
    m = parity_shards
    if cw % 8:
        raise ValueError("chunk words must be a multiple of 8")
    if group and cw % group:
        raise ValueError("chunk words must be a multiple of group")
    if m > 0:
        matrix = gf.parity_matrix(k, m)
        flat = chunk.transpose(1, 0, 2).reshape(k, B * cw)
        parity = rs._matmul_static(flat, matrix).reshape(m, B, cw)
        aw = jnp.concatenate([chunk.transpose(1, 0, 2), parity], axis=0)
        parity = parity.transpose(1, 0, 2)
    else:
        parity = jnp.zeros((B, 0, cw), jnp.uint32)
        aw = chunk.transpose(1, 0, 2)
    acc = acc ^ phash.tile_partials_batched(aw, word_offset).transpose(
        1, 0, 2
    )
    out_acc = phash.finalize_partials(acc, shard_len) if finalize else acc
    if not group:
        return parity, out_acc, jnp.zeros((B, m, 0), bool), parity
    gc = cw // group
    grouped = parity.reshape(B, m, gc, group)
    flags = (grouped != 0).any(axis=-1)
    idx = jnp.arange(gc, dtype=jnp.int32)
    key = jnp.where(flags, 0, jnp.int32(gc)) + idx
    order = jnp.argsort(key, axis=-1)
    packed = jnp.take_along_axis(
        grouped, order[..., None], axis=-2
    ).reshape(B, m, cw)
    return parity, out_acc, flags, packed


@functools.partial(
    jax.jit,
    static_argnames=(
        "present",
        "data_shards",
        "parity_shards",
        "shard_len",
        "finalize",
    ),
    donate_argnums=(0, 1),
)
def verify_reconstruct_subchunk_words(
    chunk: jax.Array,
    acc: jax.Array,
    digests: jax.Array,
    word_offset,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
    shard_len: int,
    finalize: bool = False,
):
    """One GET sub-chunk: reconstruct a (B, n, cw) slice of the shard
    rows AND accumulate verify partials (donated ping-pong ``acc`` and
    staging ``chunk``, like encode_subchunk_words).

    Returns (data (B, k, cw) u32, acc' (B, n, 8), ok (B, n) bool).
    ``ok`` is meaningful only on the ``finalize`` call (digest match of
    the WHOLE row AND present); earlier chunks return all-False — the
    backend drains each data chunk D2H while the next one computes and
    reads ``ok`` once from the last.
    """
    B, n, cw = chunk.shape
    k, m = data_shards, parity_shards
    idx = [i for i, p in enumerate(present) if p][:k]
    if len(idx) < k:
        raise ValueError(f"need {k} shards, have {len(idx)}")
    acc = acc ^ phash.tile_partials_batched(
        chunk.transpose(1, 0, 2), word_offset
    ).transpose(1, 0, 2)
    rm = gf.reconstruction_matrix(k, m, tuple(idx))
    flat = chunk.transpose(1, 0, 2).reshape(n, B * cw)
    surv = jnp.stack([flat[i] for i in idx])
    data = rs._matmul_static(surv, rm).reshape(k, B, cw).transpose(1, 0, 2)
    if finalize:
        pres = jnp.asarray(np.asarray(present, dtype=bool))
        got = phash.finalize_partials(acc, shard_len)
        ok = jnp.all(got == digests, axis=-1) & pres
        return data, acc, ok
    return data, acc, jnp.zeros((B, n), bool)


@functools.partial(jax.jit, static_argnames=("shard_len",))
def verify_hashes_words(
    shards: jax.Array, digests: jax.Array, shard_len: int
):
    """Recompute phash256 for (batch, n, w) uint32 shards, compare.

    Returns (batch, n) bool - True where the shard is intact.  This is the
    read-side bitrot verification (cmd/bitrot-streaming.go:130-146 /
    xl-storage.go bitrotVerify) as one device pass over all shards.
    """
    got = phash.phash256_words_batched(shards, shard_len)  # (B, n, 8)
    return jnp.all(got == digests, axis=-1)


@functools.partial(
    jax.jit, static_argnames=("present", "data_shards", "parity_shards")
)
def reconstruct_words_batch(
    shards: jax.Array,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
):
    """Static-pattern batched reconstruct: (B, n, w) -> (B, k, w) words.

    Column-locality makes the whole batch one flat (k, B*w) matmul with
    the pattern's inverted sub-matrix (rows where present is False hold
    garbage and are ignored).
    """
    k, m = data_shards, parity_shards
    idx = [i for i, p in enumerate(present) if p][:k]
    if len(idx) < k:
        raise ValueError(f"need {k} shards, have {len(idx)}")
    rm = gf.reconstruction_matrix(k, m, tuple(idx))
    B, n, w = shards.shape
    flat = shards.transpose(1, 0, 2).reshape(n, B * w)
    surv = jnp.stack([flat[i] for i in idx])
    dw = rs._matmul_static(surv, rm)  # (k, B*w)
    return dw.reshape(k, B, w).transpose(1, 0, 2)


# ---------------------------------------------------------------------------
# Byte-domain convenience wrappers (tests, small host-side uses)
# ---------------------------------------------------------------------------


def encode_and_hash(data, parity_shards: int):
    """Byte-domain wrapper: (B, k, L) u8 -> ((B, n, L) u8, (B, n, 8) u32).

    Host-side byte views; prefer the *_words APIs on the hot path.
    """
    data = np.asarray(data, dtype=np.uint8)
    batch, k, shard_len = data.shape
    if shard_len % 32:
        raise ValueError("shard_len must be a multiple of 32 bytes")
    words = jnp.asarray(host_bytes_to_words(data))
    parity, digests = encode_and_hash_words(
        words, parity_shards, shard_len
    )
    # eager by design: this byte-domain wrapper serves tests and small
    # host-side callers that want concrete shards back; the hot path
    # goes through the backend's digest-only seam instead
    parity_b = host_words_to_bytes(np.asarray(parity))  # noqa: MTPU107
    shards = np.concatenate([data, parity_b], axis=1)
    return shards, np.asarray(digests)


def verify_hashes(shards, digests, shard_len: int):
    """Byte-domain wrapper over verify_hashes_words."""
    shards = np.asarray(shards, dtype=np.uint8)
    words = jnp.asarray(host_bytes_to_words(shards))
    return np.asarray(
        verify_hashes_words(words, jnp.asarray(digests), shard_len)
    )


def decode_and_verify(
    shards: np.ndarray,
    digests: np.ndarray,
    data_shards: int,
    parity_shards: int,
):
    """Read-path step: verify bitrot, reconstruct from intact shards.

    Host-driven composition (the erasure-decode.go:211-290 Decode
    semantics: verify every block read, escalate to parity on failure,
    flag heal when any shard was bad).

    Returns (data, ok_mask): data (k, shard_len) uint8, ok_mask (n,) bool.
    Raises ValueError when fewer than k shards are intact (errXLReadQuorum
    analogue).
    """
    n = data_shards + parity_shards
    shard_len = shards.shape[-1]
    words = jnp.asarray(host_bytes_to_words(np.asarray(shards)))
    ok = np.asarray(
        verify_hashes_words(words[None], jnp.asarray(digests)[None], shard_len)[0]
    )
    if int(ok.sum()) < data_shards:
        raise ValueError(
            f"bitrot: only {int(ok.sum())}/{n} shards intact, "
            f"need {data_shards}"
        )
    dw = reconstruct_words_batch(
        words[None],
        tuple(bool(b) for b in ok),
        data_shards,
        parity_shards,
    )[0]
    data = host_words_to_bytes(np.asarray(dw))
    return data, ok


# ---------------------------------------------------------------------------
# Benchmark probes (chained device passes, see bench.py)
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("parity_shards", "shard_len")
)
def encode_throughput_probe(
    words: jax.Array, parity_shards: int, shard_len: int, reps
):
    """Run `reps` dependent encode+hash passes inside ONE device program.

    Chains iterations through a cheap XOR so XLA cannot elide work,
    letting per-pass device time be measured without host launch overhead
    (significant over the dev relay).  `reps` is a DYNAMIC trip count
    (fori_loop), so one compiled program serves every chain length the
    adaptive bench harness probes.  Returns a small checksum array.
    """
    def body(_, carry):
        words_c, acc = carry
        parity, digests = encode_and_hash_words(
            words_c, parity_shards, shard_len
        )
        return words_c ^ parity[:, :1], acc ^ digests[0, 0, 0]

    final, acc = jax.lax.fori_loop(
        0, reps, body, (words, jnp.uint32(0))
    )
    return final[0, 0, :8], acc


@functools.partial(
    jax.jit,
    static_argnames=("present", "data_shards", "parity_shards"),
)
def reconstruct_throughput_probe(
    shards: jax.Array,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
    reps,
):
    """Chained batched static-pattern reconstructs (see encode probe)."""
    k = data_shards

    def body(_, carry):
        shards_c, acc = carry
        data = reconstruct_words_batch(
            shards_c, present, data_shards, parity_shards
        )
        nxt = shards_c.at[:, :k].set(shards_c[:, :k] ^ data)
        return nxt, acc ^ data[0, 0, 0]

    final, acc = jax.lax.fori_loop(
        0, reps, body, (shards, jnp.uint32(0))
    )
    return final[0, 0, :8], acc


@functools.partial(jax.jit, static_argnames=("shard_len",))
def verify_throughput_probe(
    shards: jax.Array, digests: jax.Array, shard_len: int, reps
):
    """Chained bitrot-verify passes: the HEALTHY read path (no RS math,
    just the device hash + compare every streamed block pays)."""
    def body(_, carry):
        shards_c, acc = carry
        ok = verify_hashes_words(shards_c, digests, shard_len)
        nxt = shards_c ^ jnp.where(ok[0, 0], 0, 1).astype(shards_c.dtype)
        return nxt, acc ^ ok.sum().astype(jnp.uint32)

    final, acc = jax.lax.fori_loop(
        0, reps, body, (shards, jnp.uint32(0))
    )
    return final[0, 0, :8], acc
