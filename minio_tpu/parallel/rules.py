"""Partition rules, the elastic compile seam, and submesh placement.

This module is the single source of truth for how the codec's logical
planes map onto mesh axes.  Three layers live here:

* **Partition rules** (``PARTITION_RULES``/``spec_for``): a declarative
  pattern -> ``PartitionSpec`` table in the style of fmengine's
  ``match_partition_rules``.  Kernels name their operand planes
  ("stripe_words", "parity_words", ...) and the rules resolve the
  sharding; nothing outside this file writes a ``PartitionSpec`` literal
  (enforced by lint rule MTPU109).

* **Compile seam** (``register_kernel``/``compile_kernel``): a
  Titanax-style memoized factory that picks the cheaper lowering per
  geometry.  Kernels that need the XOR all-reduce register a
  ``build_local`` (per-device body for shard_map); collective-free
  geometries (shard axis == 1, or kernels that are embarrassingly
  parallel) lower through plain ``jax.jit`` with ``NamedSharding``
  in/out constraints instead.  The memo is keyed on the rules
  fingerprint, the mesh's *device ids* and axis shape, and the static
  geometry - so a rebuilt ``Mesh`` over the same devices hits the cache
  instead of silently recompiling (``Mesh`` equality is
  identity-flavored across re-creation).

* **Placement** (``PlacementRouter``/``placed``): carve the device set
  into submeshes and route independent merged batches to the
  least-loaded one instead of always spanning the mesh.  Policy comes
  from ``MINIO_TPU_PLACEMENT``:

  - ``span``:  always use every device (the pre-elastic behaviour);
  - ``route``: always place each batch on one submesh;
  - ``auto``  (default): route small batches, span once a batch is big
    enough to keep every device busy on the stripe axis.

  ``MINIO_TPU_SUBMESH_DEVICES`` sets the submesh width (default 1 chip).
  The routed device set travels to ``TpuBackend._mesh_for`` through a
  thread-local (``placed()``/``current_placement()``), so the batcher's
  per-submesh workers don't need to thread devices through the backend
  API.
"""

from __future__ import annotations

import hashlib
import os
import re
import threading
import warnings
from contextlib import contextmanager

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Input donation on the CPU test platform is accepted but not honored;
# jax warns per-compile.  Mirrors the filter in ops/codec_step.py.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

# jax.shard_map only exists as a top-level alias in newer releases;
# older ones (e.g. 0.4.x) ship it under jax.experimental.shard_map with
# the replication check spelled `check_rep` instead of `check_vma`
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _exp_shard_map(
            f, mesh, in_specs, out_specs, check_rep=check_vma
        )


# ---------------------------------------------------------------------------
# Partition rules: logical plane name -> PartitionSpec
# ---------------------------------------------------------------------------
#
# Plane naming: kernels declare operands by what the array *is*, not by
# position.  Batched planes are (B, rows, width): batch over "stripe",
# rows over "shard" when the k data shards are split across devices.
# Parity and reconstructed outputs are replicated over "shard" (every
# shard-group device holds the full parity, like every disk holding its
# own shard after the fan-out write).

PARTITION_RULES: tuple[tuple[str, PartitionSpec], ...] = (
    # (B, k, w|L) data planes: batch over stripe, shards over shard
    (
        r"^(stripe|data|survivor)_(batch|words|bytes)$",
        PartitionSpec("stripe", "shard", None),
    ),
    # (B, k, 8) per-data-shard digests follow their data rows
    (r"^data_digests$", PartitionSpec("stripe", "shard", None)),
    # (B, m, w|L) parity planes: replicated over shard after all-reduce
    (r"^parity_(words|bytes|plane)$", PartitionSpec("stripe", None, None)),
    (r"^parity_digests$", PartitionSpec("stripe", None, None)),
    # (B, k, w) reconstructed data: whole stripes, replicated over shard
    (r"^recon_words$", PartitionSpec("stripe", None, None)),
    # (B, n, w|8) quorum-read planes (fused verify+reconstruct): all n
    # shard rows of a stripe stay together - the bitrot check is
    # row-local but the decode needs every survivor row
    (r"^quorum_(words|digests)$", PartitionSpec("stripe", None, None)),
    # (B, n) per-shard verify verdicts
    (r"^ok_mask$", PartitionSpec("stripe", None)),
    # (R, w) flattened digest rows: spread over every device on both axes
    (r"^digest_(rows|out)$", PartitionSpec(("stripe", "shard"), None)),
    # (k, L) sequence-parallel stream: length over every device
    (r"^seq_", PartitionSpec(None, ("stripe", "shard"))),
)


def spec_for(
    name: str,
    rules: tuple[tuple[str, PartitionSpec], ...] = PARTITION_RULES,
) -> PartitionSpec:
    """Resolve one logical plane name to its PartitionSpec.

    Raises ``KeyError`` on no match - a kernel naming a plane the rules
    don't cover is a bug, not a replicate-by-default.
    """
    for pattern, spec in rules:
        if re.search(pattern, name):
            return spec
    raise KeyError(f"no partition rule matches plane {name!r}")


def match_partition_rules(names, rules=PARTITION_RULES):
    """Resolve a pytree of plane names to a matching pytree of specs."""
    if isinstance(names, str):
        return spec_for(names, rules)
    return tuple(match_partition_rules(n, rules) for n in names)


_FINGERPRINT: list[str | None] = [None]


def rules_fingerprint(
    rules: tuple[tuple[str, PartitionSpec], ...] = PARTITION_RULES,
) -> str:
    """Stable digest of the rule table (part of the compile-cache key)."""
    if rules is PARTITION_RULES and _FINGERPRINT[0] is not None:
        return _FINGERPRINT[0]
    h = hashlib.sha256()
    for pattern, spec in rules:
        h.update(f"{pattern}->{tuple(spec)}\n".encode())
    fp = h.hexdigest()[:16]
    if rules is PARTITION_RULES:
        _FINGERPRINT[0] = fp
    return fp


# ---------------------------------------------------------------------------
# Compile seam: one memoized factory, two lowerings
# ---------------------------------------------------------------------------


class KernelDef:
    """One registered mesh kernel: plane names + geometry-specialized builders.

    ``build_local(mesh, **statics)`` returns the per-device body for a
    shard_map lowering (it may use collectives over mesh axes).
    ``build_global(mesh, **statics)`` returns a whole-array function for
    the jit+NamedSharding lowering (no collectives; XLA partitions it).
    Either may be None, but not both.
    """

    __slots__ = (
        "kind",
        "in_names",
        "out_names",
        "build_local",
        "build_global",
        "donate_argnums",
    )

    def __init__(
        self,
        kind,
        in_names,
        out_names,
        build_local,
        build_global,
        donate_argnums,
    ):
        self.kind = kind
        self.in_names = tuple(in_names)
        self.out_names = tuple(out_names)
        self.build_local = build_local
        self.build_global = build_global
        self.donate_argnums = tuple(donate_argnums)

    def in_specs(self, rules=PARTITION_RULES):
        return tuple(spec_for(n, rules) for n in self.in_names)

    def out_specs(self, rules=PARTITION_RULES):
        return tuple(spec_for(n, rules) for n in self.out_names)


_KERNELS: dict[str, KernelDef] = {}


def register_kernel(
    kind: str,
    *,
    in_names,
    out_names,
    build_local=None,
    build_global=None,
    donate_argnums=(),
) -> KernelDef:
    """Register a mesh kernel with the compile seam (idempotent by kind)."""
    if build_local is None and build_global is None:
        raise ValueError(f"kernel {kind!r} registered with no builder")
    kd = KernelDef(
        kind, in_names, out_names, build_local, build_global, donate_argnums
    )
    _KERNELS[kind] = kd
    return kd


def registered_kernels() -> tuple[str, ...]:
    """Kinds known to the seam (the MTPU204 closure set for mesh kernels)."""
    return tuple(sorted(_KERNELS))


def kernel_def(kind: str) -> KernelDef:
    return _KERNELS[kind]


def mesh_cache_key(mesh: Mesh) -> tuple:
    """Identity-free mesh key: device ids + axis shape + axis names."""
    return (
        tuple(int(d.id) for d in mesh.devices.flat),
        tuple(mesh.devices.shape),
        tuple(mesh.axis_names),
    )


_compile_mu = threading.Lock()
_compiled: dict[tuple, tuple[object, str]] = {}
_cache_stats = {"hits": 0, "misses": 0}


def _single(tree):
    return tree[0] if len(tree) == 1 else tree


def _pick_mode(kd: KernelDef, mesh: Mesh) -> str:
    if kd.build_global is None:
        return "shard_map"
    if kd.build_local is None:
        return "jit"
    # both lowerings available: shard_map only pays off when the shard
    # axis actually needs the XOR all-reduce; otherwise let XLA
    # partition the whole-array program (no collectives to hand-roll)
    shard_n = dict(zip(mesh.axis_names, mesh.devices.shape)).get("shard", 1)
    return "shard_map" if shard_n > 1 else "jit"


def compile_kernel(
    kind: str, mesh: Mesh, *, force_mode: str | None = None, **statics
):
    """Compile (or fetch) one kernel for one geometry.

    Cache key: (kind, rules fingerprint, device ids + axis shape,
    force_mode, sorted statics) - NOT the Mesh object, so a rebuilt mesh
    over the same devices reuses the compiled executable.
    """
    kd = _KERNELS[kind]
    key = (
        kind,
        rules_fingerprint(),
        mesh_cache_key(mesh),
        force_mode,
        tuple(sorted(statics.items())),
    )
    with _compile_mu:
        hit = _compiled.get(key)
        if hit is not None:
            _cache_stats["hits"] += 1
            return hit[0]
    mode = force_mode or _pick_mode(kd, mesh)
    in_specs = kd.in_specs()
    out_specs = kd.out_specs()
    if mode == "jit":
        step = kd.build_global(mesh, **statics)
        fn = jax.jit(
            step,
            in_shardings=_single(
                tuple(NamedSharding(mesh, s) for s in in_specs)
            ),
            out_shardings=_single(
                tuple(NamedSharding(mesh, s) for s in out_specs)
            ),
            donate_argnums=kd.donate_argnums,
        )
    elif mode == "shard_map":
        step = kd.build_local(mesh, **statics)
        fn = jax.jit(
            _shard_map(
                step,
                mesh=mesh,
                in_specs=_single(in_specs),
                out_specs=_single(out_specs),
                check_vma=False,
            ),
            donate_argnums=kd.donate_argnums,
        )
    else:
        raise ValueError(f"unknown lowering mode {mode!r}")
    with _compile_mu:
        prior = _compiled.get(key)
        if prior is not None:
            # lost a build race; keep the first executable
            _cache_stats["hits"] += 1
            return prior[0]
        _compiled[key] = (fn, mode)
        _cache_stats["misses"] += 1
    return fn


def kernel_mode(kind: str, mesh: Mesh, **statics) -> str:
    """The lowering the seam would pick (compiles lazily as a side effect)."""
    kd = _KERNELS[kind]
    return _pick_mode(kd, mesh)


def cache_info() -> dict:
    with _compile_mu:
        return {
            "entries": len(_compiled),
            "hits": _cache_stats["hits"],
            "misses": _cache_stats["misses"],
        }


def clear_compile_cache() -> None:
    with _compile_mu:
        _compiled.clear()
        _cache_stats["hits"] = 0
        _cache_stats["misses"] = 0


# ---------------------------------------------------------------------------
# Placement: submesh carving + least-loaded routing
# ---------------------------------------------------------------------------

PLACEMENT_POLICIES = ("span", "route", "auto")


def placement_policy() -> str:
    pol = os.environ.get("MINIO_TPU_PLACEMENT", "auto").strip().lower()
    return pol if pol in PLACEMENT_POLICIES else "auto"


class Submesh:
    """One carved slice of the device set with a live queue-depth count."""

    __slots__ = ("name", "devices", "depth")

    def __init__(self, name: str, devices: tuple):
        self.name = name
        self.devices = devices
        self.depth = 0

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Submesh({self.name}, n={len(self.devices)}, depth={self.depth})"


class PlacementRouter:
    """Route independent merged batches to the least-loaded submesh.

    The device set is carved into contiguous submeshes of
    ``submesh_devices`` chips (``MINIO_TPU_SUBMESH_DEVICES``, default 1);
    a remainder that can't fill a submesh folds into the last one.
    ``route`` returns None when the batch should span the full mesh
    (policy ``span``, a single submesh, or ``auto`` with a batch big
    enough to occupy every device on the stripe axis).
    """

    def __init__(self, devices, policy: str | None = None,
                 submesh_devices: int | None = None):
        self.devices = tuple(devices)
        if policy is None:
            policy = placement_policy()
        self.policy = policy if policy in PLACEMENT_POLICIES else "auto"
        if submesh_devices is None:
            try:
                submesh_devices = int(
                    os.environ.get("MINIO_TPU_SUBMESH_DEVICES", "1") or "1"
                )
            except ValueError:
                submesh_devices = 1
        width = max(1, min(submesh_devices, len(self.devices)))
        subs = []
        full = (len(self.devices) // width) * width
        for lo in range(0, full, width):
            subs.append(
                Submesh(f"sub{len(subs)}", self.devices[lo:lo + width])
            )
        if full < len(self.devices):
            if subs:
                last = subs[-1]
                subs[-1] = Submesh(
                    last.name, last.devices + self.devices[full:]
                )
            else:  # pragma: no cover - width clamped to len(devices)
                subs.append(Submesh("sub0", self.devices))
        self._subs = tuple(subs)
        self._mu = threading.Lock()

    @property
    def submeshes(self) -> tuple[Submesh, ...]:
        return self._subs

    def route(self, batch_blocks: int) -> Submesh | None:
        """Claim a submesh for a batch (None -> span the full mesh)."""
        if self.policy == "span" or len(self._subs) <= 1:
            return None
        if self.policy == "auto" and batch_blocks >= len(self.devices):
            # enough stripes to occupy every device data-parallel: the
            # span path's stripe axis beats any single submesh
            return None
        with self._mu:
            sub = min(self._subs, key=lambda s: s.depth)
            sub.depth += 1
            return sub

    def release(self, sub: Submesh) -> None:
        with self._mu:
            sub.depth = max(0, sub.depth - 1)

    def depths(self) -> dict[str, int]:
        with self._mu:
            return {s.name: s.depth for s in self._subs}


_placement_tls = threading.local()


def current_placement():
    """The device set routed to this thread, or None (span)."""
    return getattr(_placement_tls, "devices", None)


@contextmanager
def placed(devices):
    """Scope mesh construction on this thread to a routed device set."""
    prev = getattr(_placement_tls, "devices", None)
    _placement_tls.devices = tuple(devices)
    try:
        yield
    finally:
        _placement_tls.devices = prev
