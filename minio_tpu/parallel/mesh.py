"""Device-mesh parallelism for the erasure data plane.

The reference scales by fanning shard I/O across disks/nodes with
goroutines + REST (SURVEY.md section 2.4 "parallelism strategies").  The
TPU-native analogue maps those strategies onto a jax.sharding.Mesh:

* axis "stripe" (data-parallel analogue of erasure *sets*,
  cmd/erasure-sets.go:543-580): independent stripes of a batch are placed on
  different devices; no collectives.
* axis "seq" (sequence-parallel analogue of the 10 MiB block streaming,
  cmd/object-api-common.go:31): the byte stream of one object is sharded
  along its length; RS is column-local so each device encodes its slice
  independently - unbounded object size with a fixed per-device working set.
* axis "shard" (tensor-parallel analogue of the per-disk shard fan-out in
  cmd/erasure-encode.go:39-54): the k data shards are sharded across
  devices; each device computes a partial parity (XOR of its terms) and
  partials are combined with a recursive-doubling XOR all-reduce over ICI.

Shardings are not written here: every entry point declares its operand
planes by name and `parallel.rules` resolves them (PARTITION_RULES) and
picks the lowering (shard_map when the shard axis needs the XOR
all-reduce, jit+NamedSharding for collective-free geometries) behind one
compile cache keyed on device ids rather than Mesh identity.

All entry points work under jit/shard_map with static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf, rs
from . import rules

# compat alias: tests and older callers import the shim from here
_shard_map = rules._shard_map


def make_mesh(
    devices: "list[jax.Device] | None" = None,
    stripe: int | None = None,
    shard: int | None = None,
) -> Mesh:
    """Build a ("stripe", "shard") mesh over the available devices.

    Defaults to putting all devices on the stripe axis (pure
    set-parallelism) since XOR all-reduce traffic is then zero, mirroring
    the reference's default of independent sets per object.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if stripe is None and shard is None:
        stripe, shard = n, 1
    elif stripe is None:
        stripe = n // shard
    elif shard is None:
        shard = n // stripe
    if stripe * shard != n:
        raise ValueError(f"mesh {stripe}x{shard} != {n} devices")
    arr = np.asarray(devices).reshape(stripe, shard)
    return Mesh(arr, ("stripe", "shard"))


_overlap_fallback_warned = False


def warn_overlap_fallback() -> None:
    """Warn once that MINIO_TPU_CODEC_OVERLAP degrades to "off" on mesh.

    The sub-chunk overlap pipeline double-buffers per-device staging
    arrays; the mesh entry points shard one whole stripe batch across
    devices with collective parity accumulation, so splitting the
    stripe-length axis again underneath them would fight the "seq"
    axis for the same dimension.  Mesh callers silently get the
    serialized (bit-identical) path; this warning surfaces that the
    overlap knob is being ignored so operators do not chase missing
    overlap_windows counters on multi-device runs.
    """
    global _overlap_fallback_warned
    if _overlap_fallback_warned:
        return
    _overlap_fallback_warned = True
    import warnings

    warnings.warn(
        "MINIO_TPU_CODEC_OVERLAP is not supported on the device-mesh "
        "codec path; falling back to the serialized (off) pipeline",
        RuntimeWarning,
        stacklevel=3,
    )


def xor_allreduce(x: jax.Array, axis_name: str) -> jax.Array:
    """All-reduce with XOR over a mesh axis via recursive doubling.

    GF(2^8) addition is XOR, which psum cannot express; this is the
    collective backing shard-parallel parity accumulation.  Rides ICI as
    log2(n) ppermute steps (falls back to all-gather+fold for non powers
    of two).
    """
    # lax.axis_size is missing on older releases; psum of a unit is the
    # portable spelling and stays a static int under shard_map
    _axis_size = getattr(jax.lax, "axis_size", None)
    n = _axis_size(axis_name) if _axis_size else jax.lax.psum(1, axis_name)
    if n == 1:
        return x
    if n & (n - 1) == 0:
        idx = jax.lax.axis_index(axis_name)
        step = 1
        while step < n:
            # partner = idx XOR step; ppermute perm maps src->dst
            perm = [(int(i), int(i ^ step)) for i in range(n)]
            other = jax.lax.ppermute(x, axis_name, perm)
            x = x ^ other
            step <<= 1
        return x
    gathered = jax.lax.all_gather(x, axis_name)  # (n, ...)
    return jax.lax.reduce(
        gathered, x.dtype.type(0), jax.lax.bitwise_xor, (0,)
    )


def _partial_parity(
    local_data_words: jax.Array, matrix_cols: np.ndarray
) -> jax.Array:
    """Partial parity for a device's slice of data shards (static matrix)."""
    return rs._encode_words(local_data_words, matrix_cols)


def _col_blocks(matrix: np.ndarray, shard_n: int) -> np.ndarray:
    """Split a generator/reconstruction matrix into per-shard-device columns."""
    k = matrix.shape[1]
    k_local = k // shard_n
    return np.stack(
        [matrix[:, s * k_local : (s + 1) * k_local] for s in range(shard_n)]
    )  # (shard_n, rows, k_local) - static stack, dynamic row pick


def put_sharded(mesh: Mesh, x: np.ndarray, spec: P) -> jax.Array:
    """Place a host array onto the mesh with the given partition spec."""
    return jax.device_put(x, NamedSharding(mesh, spec))


def _pad_batch(arr: np.ndarray, rows: int) -> np.ndarray:
    """Zero-pad the leading axis to ``rows`` with a single allocation.

    (np.concatenate would reallocate AND copy the batch through a
    temporary; here the only traffic is one memcpy into fresh zeros, and
    the unpadded case returns the input untouched.)
    """
    if arr.shape[0] == rows:
        return arr
    out = np.zeros((rows,) + arr.shape[1:], dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


# ---------------------------------------------------------------------------
# Kernel bodies, registered with the rules.py compile seam
# ---------------------------------------------------------------------------
#
# Each kernel kind has up to two builders: `build_local` (per-device body
# for shard_map; may use the XOR all-reduce over "shard") and
# `build_global` (whole-array program for jit+NamedSharding; XLA
# partitions it, valid because it needs no hand-rolled collective).


def _encode_local(mesh: Mesh, k: int, m: int):
    shard_n = mesh.shape["shard"]
    col_blocks = _col_blocks(gf.parity_matrix(k, m), shard_n)

    def step(local: jax.Array) -> jax.Array:
        # local: (B_local, k_local, length) uint8
        idx = jax.lax.axis_index("shard")
        words = rs.bytes_to_words(local)
        my_cols = jnp.asarray(col_blocks)[idx]
        partial = jax.vmap(
            lambda w: rs._matmul_words_dynamic(w, my_cols)
        )(words)
        total = xor_allreduce(partial, "shard")
        return rs.words_to_bytes(total)

    return step


def _encode_global(mesh: Mesh, k: int, m: int):
    matrix = gf.parity_matrix(k, m)

    def step(data: jax.Array) -> jax.Array:
        # data: (B, k, length) uint8
        words = rs.bytes_to_words(data)
        parity = jax.vmap(lambda w: rs._encode_words(w, matrix))(words)
        return rs.words_to_bytes(parity)

    return step


def _encode_seq_global(mesh: Mesh, k: int, m: int):
    matrix = gf.parity_matrix(k, m)

    def step(data: jax.Array) -> jax.Array:
        # data: (k, length) uint8, length sharded; RS is column-local
        words = rs.bytes_to_words(data)
        return rs.words_to_bytes(rs._encode_words(words, matrix))

    return step


def _encode_hash_local(mesh: Mesh, k: int, m: int, shard_len: int):
    from ..ops import hash as phash

    shard_n = mesh.shape["shard"]
    col_blocks = _col_blocks(gf.parity_matrix(k, m), shard_n)

    def step(local: jax.Array):
        # local: (B_local, k_local, w)
        idx = jax.lax.axis_index("shard")
        my_cols = jnp.asarray(col_blocks)[idx]
        partial = jax.vmap(
            lambda wds: rs._matmul_words_dynamic(wds, my_cols)
        )(local)
        parity = xor_allreduce(partial, "shard")  # (B_local, m, w)
        ddig = phash.phash256_words_batched(local, shard_len)
        pdig = phash.phash256_words_batched(parity, shard_len)
        return parity, ddig, pdig

    return step


def _encode_hash_global(mesh: Mesh, k: int, m: int, shard_len: int):
    from ..ops import codec_step

    def step(words: jax.Array):
        # whole stripes are device-local on a stripe-only mesh: run the
        # fused single-device kernel (static matrix -> Pallas on TPU)
        # instead of the dynamic bit-walk
        parity, digests = codec_step.encode_and_hash_words(
            words, m, shard_len
        )
        return parity, digests[:, :k], digests[:, k:]

    return step


def _reconstruct_local(mesh: Mesh, k: int, m: int, idx: tuple[int, ...]):
    shard_n = mesh.shape["shard"]
    rm = gf.reconstruction_matrix(k, m, idx)  # (k, k) survivors -> data
    col_blocks = _col_blocks(rm, shard_n)

    def step(local: jax.Array):
        # local: (B_local, k_local, w) compacted survivor rows
        dev = jax.lax.axis_index("shard")
        my_cols = jnp.asarray(col_blocks)[dev]
        partial = jax.vmap(
            lambda wds: rs._matmul_words_dynamic(wds, my_cols)
        )(local)
        return xor_allreduce(partial, "shard")

    return step


def _reconstruct_global(mesh: Mesh, k: int, m: int, idx: tuple[int, ...]):
    rm = gf.reconstruction_matrix(k, m, idx)

    def step(surv: jax.Array):
        # surv: (B, k, w) compacted survivor rows
        return jax.vmap(lambda wds: rs._matmul_static(wds, rm))(surv)

    return step


def _digest_global(mesh: Mesh, shard_len: int):
    from ..ops import hash as phash

    def step(rows: jax.Array):
        # rows: (R, w) flattened shard rows; embarrassingly parallel
        return phash.phash256_words_batched(rows, shard_len)

    return step


def _verify_reconstruct_global(
    mesh: Mesh,
    k: int,
    m: int,
    present: tuple[bool, ...],
    shard_len: int,
):
    from ..ops import codec_step

    def step(words: jax.Array, digests: jax.Array):
        # words: (B, n, w) quorum rows; stripes are device-local on the
        # stripe axis, so the fused GET step (verify + reconstruct in
        # one program) partitions with no collective.  The portable
        # formulation keeps the program XLA-partitionable; the Pallas
        # kernel stays on the single-device path.
        return codec_step.verify_and_reconstruct_words(
            words, digests, present, k, m, shard_len
        )

    return step


rules.register_kernel(
    "sharded_encode",
    in_names=("stripe_bytes",),
    out_names=("parity_bytes",),
    build_local=_encode_local,
    build_global=_encode_global,
)
rules.register_kernel(
    "sharded_encode_seq",
    in_names=("seq_bytes",),
    out_names=("seq_parity",),
    build_global=_encode_seq_global,
)
rules.register_kernel(
    "mesh_encode_hash",
    in_names=("stripe_words",),
    out_names=("parity_words", "data_digests", "parity_digests"),
    build_local=_encode_hash_local,
    build_global=_encode_hash_global,
    # the data-words buffer is a fresh device_put per batch; donating it
    # lets XLA alias it into the parity output instead of copying
    donate_argnums=(0,),
)
rules.register_kernel(
    "mesh_reconstruct",
    in_names=("survivor_words",),
    out_names=("recon_words",),
    build_local=_reconstruct_local,
    build_global=_reconstruct_global,
)
rules.register_kernel(
    "mesh_digest",
    in_names=("digest_rows",),
    out_names=("digest_out",),
    build_global=_digest_global,
)
rules.register_kernel(
    "mesh_verify_reconstruct",
    in_names=("quorum_words", "quorum_digests"),
    out_names=("recon_words", "ok_mask"),
    build_global=_verify_reconstruct_global,
)


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def sharded_encode(
    mesh: Mesh, data: jax.Array, parity_shards: int
) -> jax.Array:
    """Encode a batch of stripes across the mesh.

    data: (batch, k, length) uint8, batch sharded over "stripe", the k data
    shards sharded over "shard".  Returns (batch, m, length) parity
    replicated over "shard" (each shard-group device holds the full parity,
    like every disk holding its own shard after the fan-out write).
    """
    _, k, _ = data.shape
    shard_n = mesh.shape["shard"]
    if k % shard_n:
        raise ValueError(f"k={k} not divisible by shard axis {shard_n}")
    fn = rules.compile_kernel(
        "sharded_encode", mesh, k=k, m=parity_shards
    )
    return fn(data)


def sharded_encode_seq(mesh: Mesh, data: jax.Array, parity_shards: int) -> jax.Array:
    """Sequence-parallel encode: one long object sharded along its length.

    data: (k, length) with length sharded over every mesh device (both
    axes flattened); RS columns are independent so there is no collective -
    this is the long-context scaling path (SURVEY.md section 5
    "long-context / sequence parallelism").
    """
    k, _ = data.shape
    fn = rules.compile_kernel(
        "sharded_encode_seq", mesh, k=k, m=parity_shards
    )
    return fn(data)


# ---------------------------------------------------------------------------
# Production mesh paths (the backend seam's multi-device implementation)
# ---------------------------------------------------------------------------
#
# These are what codec.backend.TpuBackend dispatches to when more than one
# device is visible: the "stripe" axis carries independent stripes (the
# erasure-sets data-parallel analogue) and the "shard" axis splits the k
# data shards of each stripe (the per-disk fan-out analogue,
# cmd/erasure-encode.go:39-54) with partial parities combined by the XOR
# all-reduce over ICI.


def pick_axes(n_devices: int, batch: int, data_shards: int) -> tuple[int, int]:
    """Choose (stripe, shard) axis sizes for a batch of stripes.

    Minimize rounds of work (ceil(batch/stripe)), then maximize device
    utilization, then prefer the smaller shard axis (less collective
    traffic).  Large batches therefore get pure stripe parallelism; small
    batches of wide stripes soak leftover devices on the shard axis.
    """
    best_key, best = None, (n_devices, 1)
    for shard in range(1, n_devices + 1):
        if n_devices % shard or data_shards % shard:
            continue
        stripe = n_devices // shard
        rounds = -(-batch // stripe)
        util = min(batch, stripe) * shard
        key = (rounds, -util, shard)
        if best_key is None or key < best_key:
            best_key, best = key, (stripe, shard)
    return best


def _bucket_batch(batch: int, stripe: int) -> int:
    """Pad batch to stripe * next_pow2(rounds): bounds jit cache entries to
    O(log B) per geometry while wasting <2x compute on odd sizes."""
    rounds = -(-batch // stripe)
    p = 1
    while p < rounds:
        p <<= 1
    return stripe * p


def mesh_encode_hash(
    mesh: Mesh, words: np.ndarray, parity_shards: int, shard_len: int
):
    """Mesh-parallel fused encode+digest over a batch of stripes.

    words: (B, k, w) uint32 host array.  Returns (parity (B, m, w),
    digests (B, k+m, 8)) as numpy, digest rows in data-then-parity order
    (the contract of ops.codec_step.encode_and_hash_words).
    """
    return mesh_encode_hash_end(
        mesh_encode_hash_begin(mesh, words, parity_shards, shard_len)
    )


def mesh_encode_hash_begin(
    mesh: Mesh, words: np.ndarray, parity_shards: int, shard_len: int
):
    """Dispatch the mesh encode+digest WITHOUT synchronizing.

    jax dispatch is async for shard_map exactly as for plain jit: the
    returned tuple holds device-array futures plus the unpadded batch
    size.  ``mesh_encode_hash_end`` materializes them, so the erasure
    layer's double-buffered pipeline (encode_begin/encode_end) overlaps
    this batch's mesh pass with the previous batch's disk writes on the
    mesh path too, not just the single-device one.

    The device copy of ``words`` is donated to the kernel (the host
    array is untouched; only the fresh on-device buffer is recycled).
    """
    B, k, _ = words.shape
    stripe = mesh.shape["stripe"]
    words = _pad_batch(words, _bucket_batch(B, stripe))
    fn = rules.compile_kernel(
        "mesh_encode_hash", mesh, k=k, m=parity_shards, shard_len=shard_len
    )
    dd = put_sharded(mesh, words, rules.spec_for("stripe_words"))
    parity, ddig, pdig = fn(dd)
    return parity, ddig, pdig, B


def mesh_encode_hash_end(handle):
    """Materialize a ``mesh_encode_hash_begin`` handle (the sync point)."""
    parity, ddig, pdig, B = handle
    digests = np.concatenate(
        [np.asarray(ddig)[:B], np.asarray(pdig)[:B]], axis=1
    )
    return np.asarray(parity)[:B], digests


def mesh_reconstruct(
    mesh: Mesh,
    words: np.ndarray,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
) -> np.ndarray:
    """Mesh-parallel batched reconstruct: (B, n, w) + mask -> (B, k, w).

    Survivor rows are compacted host-side (free fancy-index view) so the
    device program is one partial-matmul + XOR all-reduce per device.
    """
    k, m = data_shards, parity_shards
    idx = tuple(i for i, p in enumerate(present) if p)[:k]
    if len(idx) < k:
        raise ValueError(f"need {k} shards, have {len(idx)}")
    surv = np.ascontiguousarray(words[:, idx, :])  # (B, k, w)
    B = surv.shape[0]
    stripe = mesh.shape["stripe"]
    surv = _pad_batch(surv, _bucket_batch(B, stripe))
    fn = rules.compile_kernel(
        "mesh_reconstruct", mesh, k=k, m=m, idx=idx
    )
    dd = put_sharded(mesh, surv, rules.spec_for("survivor_words"))
    return np.asarray(fn(dd))[:B]


def mesh_verify_reconstruct(
    mesh: Mesh,
    words: np.ndarray,
    digests: np.ndarray,
    present: tuple[bool, ...],
    data_shards: int,
    parity_shards: int,
    shard_len: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Mesh-parallel fused GET step: verify digests + reconstruct, one program.

    words: (B, n, w) quorum rows, digests: (B, n, 8) expected phash256 -
    both sharded over "stripe".  Returns ((B, k, w) data, (B, n) ok mask).
    Padded stripes hash to garbage and come back ok=False; the [:B] slice
    drops them before anyone looks.
    """
    k, m = data_shards, parity_shards
    B = words.shape[0]
    stripe = mesh.shape["stripe"]
    rows = _bucket_batch(B, stripe)
    words = _pad_batch(words, rows)
    digests = _pad_batch(digests, rows)
    fn = rules.compile_kernel(
        "mesh_verify_reconstruct",
        mesh,
        k=k,
        m=m,
        present=tuple(bool(p) for p in present),
        shard_len=shard_len,
    )
    dw = put_sharded(mesh, words, rules.spec_for("quorum_words"))
    dg = put_sharded(mesh, digests, rules.spec_for("quorum_digests"))
    data, ok = fn(dw, dg)
    return np.asarray(data)[:B], np.asarray(ok)[:B]


def mesh_digest(mesh: Mesh, words: np.ndarray, shard_len: int) -> np.ndarray:
    """Mesh-parallel phash256: (R, w) uint32 rows -> (R, 8) digests.

    Rows (any flattened batch of shards) are spread over every device on
    both axes - digesting is embarrassingly parallel.
    """
    R = words.shape[0]
    n_dev = mesh.devices.size
    words = _pad_batch(words, _bucket_batch(R, n_dev))
    fn = rules.compile_kernel("mesh_digest", mesh, shard_len=shard_len)
    dd = put_sharded(mesh, words, rules.spec_for("digest_rows"))
    return np.asarray(fn(dd))[:R]
