"""Device-mesh parallelism for the erasure data plane.

The reference scales by fanning shard I/O across disks/nodes with
goroutines + REST (SURVEY.md section 2.4 "parallelism strategies").  The
TPU-native analogue maps those strategies onto a jax.sharding.Mesh:

* axis "stripe" (data-parallel analogue of erasure *sets*,
  cmd/erasure-sets.go:543-580): independent stripes of a batch are placed on
  different devices; no collectives.
* axis "seq" (sequence-parallel analogue of the 10 MiB block streaming,
  cmd/object-api-common.go:31): the byte stream of one object is sharded
  along its length; RS is column-local so each device encodes its slice
  independently - unbounded object size with a fixed per-device working set.
* axis "shard" (tensor-parallel analogue of the per-disk shard fan-out in
  cmd/erasure-encode.go:39-54): the k data shards are sharded across
  devices; each device computes a partial parity (XOR of its terms) and
  partials are combined with a recursive-doubling XOR all-reduce over ICI.

All entry points work under jit/shard_map with static shapes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf, rs


def make_mesh(
    devices: "list[jax.Device] | None" = None,
    stripe: int | None = None,
    shard: int | None = None,
) -> Mesh:
    """Build a ("stripe", "shard") mesh over the available devices.

    Defaults to putting all devices on the stripe axis (pure
    set-parallelism) since XOR all-reduce traffic is then zero, mirroring
    the reference's default of independent sets per object.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if stripe is None and shard is None:
        stripe, shard = n, 1
    elif stripe is None:
        stripe = n // shard
    elif shard is None:
        shard = n // stripe
    if stripe * shard != n:
        raise ValueError(f"mesh {stripe}x{shard} != {n} devices")
    arr = np.asarray(devices).reshape(stripe, shard)
    return Mesh(arr, ("stripe", "shard"))


def xor_allreduce(x: jax.Array, axis_name: str) -> jax.Array:
    """All-reduce with XOR over a mesh axis via recursive doubling.

    GF(2^8) addition is XOR, which psum cannot express; this is the
    collective backing shard-parallel parity accumulation.  Rides ICI as
    log2(n) ppermute steps (falls back to all-gather+fold for non powers
    of two).
    """
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    if n & (n - 1) == 0:
        idx = jax.lax.axis_index(axis_name)
        step = 1
        while step < n:
            # partner = idx XOR step; ppermute perm maps src->dst
            perm = [(int(i), int(i ^ step)) for i in range(n)]
            other = jax.lax.ppermute(x, axis_name, perm)
            x = x ^ other
            step <<= 1
        return x
    gathered = jax.lax.all_gather(x, axis_name)  # (n, ...)
    return jax.lax.reduce(
        gathered, x.dtype.type(0), jax.lax.bitwise_xor, (0,)
    )


def _partial_parity(
    local_data_words: jax.Array, matrix_cols: np.ndarray
) -> jax.Array:
    """Partial parity for a device's slice of data shards (static matrix)."""
    return rs._encode_words(local_data_words, matrix_cols)


def sharded_encode(
    mesh: Mesh, data: jax.Array, parity_shards: int
) -> jax.Array:
    """Encode a batch of stripes across the mesh.

    data: (batch, k, length) uint8, batch sharded over "stripe", the k data
    shards sharded over "shard".  Returns (batch, m, length) parity
    replicated over "shard" (each shard-group device holds the full parity,
    like every disk holding its own shard after the fan-out write).
    """
    batch, k, length = data.shape
    m = parity_shards
    shard_n = mesh.shape["shard"]
    if k % shard_n:
        raise ValueError(f"k={k} not divisible by shard axis {shard_n}")
    matrix = gf.parity_matrix(k, m)
    k_local = k // shard_n

    def step(local: jax.Array) -> jax.Array:
        # local: (batch/stripe_n, k_local, length)
        idx = jax.lax.axis_index("shard")
        words = rs.bytes_to_words(local)

        def one_stripe(w):
            # select this device's columns of the generator matrix
            cols = jnp.stack(
                [
                    jnp.asarray(matrix[:, s * k_local : (s + 1) * k_local])
                    for s in range(shard_n)
                ]
            )  # (shard_n, m, k_local) - static stack, dynamic row pick
            my_cols = cols[idx]
            partial = rs._matmul_words_dynamic(w, my_cols)
            return partial

        partial = jax.vmap(one_stripe)(words)
        total = xor_allreduce(partial, "shard")
        return rs.words_to_bytes(total)

    fn = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=P("stripe", "shard", None),
        out_specs=P("stripe", None, None),
        check_vma=False,
    )
    return fn(data)


def sharded_encode_seq(mesh: Mesh, data: jax.Array, parity_shards: int) -> jax.Array:
    """Sequence-parallel encode: one long object sharded along its length.

    data: (k, length) with length sharded over every mesh device (both
    axes flattened); RS columns are independent so there is no collective -
    this is the long-context scaling path (SURVEY.md section 5
    "long-context / sequence parallelism").
    """
    k, length = data.shape
    matrix = gf.parity_matrix(k, parity_shards)

    def step(local: jax.Array) -> jax.Array:
        words = rs.bytes_to_words(local)
        return rs.words_to_bytes(rs._encode_words(words, matrix))

    fn = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=P(None, ("stripe", "shard")),
        out_specs=P(None, ("stripe", "shard")),
        check_vma=False,
    )
    return fn(data)


def put_sharded(mesh: Mesh, x: np.ndarray, spec: P) -> jax.Array:
    """Place a host array onto the mesh with the given partition spec."""
    return jax.device_put(x, NamedSharding(mesh, spec))
