"""Per-disk I/O fan-out pool (the parallelWriter/parallelReader plane).

The reference fans every shard write out to one goroutine per disk with
quorum-aware early completion (cmd/erasure-encode.go:39-70
parallelWriter, cmd/erasure-decode.go parallelReader).  The Python
analogue here is a process-wide pool of ORDERED worker queues:

* One queue per routing key.  Writers/readers tagged with a stable
  ``io_key`` (the disk endpoint, set by the object layer) get a
  dedicated queue, so all writes to one shard file flow through one
  worker in submission order — shard-file framing survives concurrent
  PUTs without any per-file locking.
* Bounded depth per queue (backpressure): a slow disk stalls its own
  submitters instead of ballooning memory.
* ``ShardFlusher`` adds the quorum protocol on top: ``flush()`` returns
  as soon as ``quorum`` disks acked the batch, stragglers keep draining
  in the background, and failed disks are reported so the caller can
  mark ``writers[s] = None`` exactly like the sequential path did.

Worker threads are lazy, daemonized, and named ``iopool-<n>`` (the
leakcheck fixture allowlists the prefix: the global pool is a
process-lifetime singleton like the codec batcher).  All locks come
from the module-global ``threading`` so the MTPU3xx lock-order auditor
can swap in its audited primitives.

Jobs run OUTSIDE every pool lock; a job submitted from its own queue's
worker thread executes inline (read-ahead jobs that fan out leaf reads
can never deadlock on their own queue).
"""

from __future__ import annotations

import collections
import os
import threading
import time

from ..utils.log import kv, logger

_log = logger("iopool")

_MAX_STABLE_KEYS = 4096  # stop memoizing routing past this many keys


def _env_int(name: str, default: int, lo: int, hi: int) -> int:
    try:
        v = int(os.environ.get(name) or default)
    except ValueError:
        v = default
    return max(lo, min(hi, v))


class IopoolTimeout(TimeoutError):
    """A pool job missed its caller's deadline (the job itself may
    still be running; see IOFuture.abandon for the disavowal half)."""


class IopoolAbandoned(RuntimeError):
    """A queued job was abandoned before its worker dequeued it — the
    caller hedged past it and disavowed the result."""


class IOFuture:
    """Completion handle for one pool job (result OR error, both kept)."""

    __slots__ = (
        "_lk", "_event", "_finished", "_cbs", "abandoned", "result", "error"
    )

    def __init__(self):
        self._lk = threading.Lock()
        self._event = threading.Event()
        self._finished = False
        self._cbs: list = []
        self.abandoned = False
        self.result = None
        self.error: "BaseException | None" = None

    def abandon(self) -> None:
        """Disavow a hedged-past job: nobody will consume its result.

        Still-queued jobs resolve ``IopoolAbandoned`` at dequeue
        WITHOUT running — the band slot frees immediately instead of
        behind a straggling disk.  An already-running job finishes
        normally (its thread can't be interrupted) and simply resolves
        unobserved; either way the caller never blocks on it.
        """
        with self._lk:
            if not self._finished:
                self.abandoned = True

    def _resolve(self, result, error: "BaseException | None") -> None:
        with self._lk:
            self.result = result
            self.error = error
            self._finished = True
            cbs, self._cbs = self._cbs, []
        self._event.set()
        for cb in cbs:
            try:
                cb(self)
            except Exception as exc:  # callback bugs must not kill workers
                _log.warning("iopool callback failed", extra=kv(err=str(exc)))

    def add_done_callback(self, cb) -> None:
        with self._lk:
            if not self._finished:
                self._cbs.append(cb)
                return
        cb(self)

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: "float | None" = None) -> bool:
        return self._event.wait(timeout)

    def result_or_raise(self, timeout: "float | None" = None):
        if not self._event.wait(timeout):
            raise IopoolTimeout(
                f"iopool job did not complete within {timeout}s"
            )
        if self.error is not None:
            raise self.error
        return self.result


class _IOQueue:
    __slots__ = ("idx", "label", "cv", "items", "thread")

    def __init__(self, idx: int):
        self.idx = idx
        self.label = f"q{idx}"
        self.cv = threading.Condition()
        self.items: "collections.deque" = collections.deque()
        self.thread: "threading.Thread | None" = None


class IOPool:
    """Bounded pool of ordered per-key worker queues."""

    def __init__(
        self,
        queues: "int | None" = None,
        depth: "int | None" = None,
        name_prefix: str = "iopool",
    ):
        self.n_queues = queues if queues is not None else _env_int(
            "MINIO_TPU_IOPOOL_QUEUES", 16, 1, 256
        )
        self.depth = depth if depth is not None else _env_int(
            "MINIO_TPU_IOPOOL_DEPTH", 8, 1, 1024
        )
        self._name_prefix = name_prefix
        self._mu = threading.Lock()  # routing table + lifecycle
        self._assign: "dict[str, int]" = {}
        # two bands: leaf I/O jobs (shard reads/writes — never block
        # on another pool job) fill the main band; PIPELINE jobs that
        # themselves wait on leaf futures (decode read-ahead) live in
        # a small reserved aux band.  Waits only ever flow aux -> main,
        # so a pipeline job queued behind another pipeline job can
        # never close a cycle with the disk queues it is waiting on.
        self.n_aux = max(1, self.n_queues // 4) if self.n_queues > 1 else 0
        self.n_main = self.n_queues - self.n_aux
        self._queues = [_IOQueue(i) for i in range(self.n_queues)]
        self._running = True

    # -- routing ----------------------------------------------------------

    def _queue_for(self, key, aux: bool = False) -> _IOQueue:
        """Stable string keys (disk endpoints) get dedicated main-band
        queues round-robin — up to ``n_main`` disks never share a
        worker.  Ephemeral keys (id()s, read-ahead sequence tuples)
        hash-route: their ordering does not matter, only their
        concurrency."""
        if aux and self.n_aux:
            return self._queues[self.n_main + hash(key) % self.n_aux]
        if isinstance(key, str):
            with self._mu:
                idx = self._assign.get(key)
                if idx is None:
                    if len(self._assign) < _MAX_STABLE_KEYS:
                        idx = len(self._assign) % self.n_main
                        self._assign[key] = idx
                    else:
                        idx = hash(key) % self.n_main
            return self._queues[idx]
        return self._queues[hash(key) % self.n_main]

    # -- submission -------------------------------------------------------

    def submit(self, key, fn, nbytes: int = 0, aux: bool = False) -> IOFuture:
        """Enqueue ``fn`` on the key's ordered queue; returns a future.

        The job's exception (if any) lands in ``future.error`` — it is
        never raised on the worker.  Called from the owning worker
        thread itself, the job runs inline (nested fan-out can't
        deadlock on its own queue).  Jobs that BLOCK on other pool
        futures must pass ``aux=True`` to run in the reserved band —
        a blocking job in the main band can deadlock the disk queues
        it waits on."""
        q = self._queue_for(key, aux=aux)
        fut = IOFuture()
        if q.thread is threading.current_thread():
            self._run_job(q, fut, fn, nbytes, len(q.items))
            return fut
        with q.cv:
            while len(q.items) >= self.depth and self._running:
                q.cv.wait(0.5)
            if not self._running:
                raise RuntimeError("iopool is shut down")
            q.items.append((fut, fn, nbytes))
            depth = len(q.items)
            if q.thread is None:
                q.thread = threading.Thread(
                    target=self._worker,
                    args=(q,),
                    name=f"{self._name_prefix}-{q.idx}",
                    daemon=True,
                )
                q.thread.start()
            q.cv.notify_all()
        _stats_record_depth(q.label, depth)
        return fut

    # -- worker -----------------------------------------------------------

    def _worker(self, q: _IOQueue) -> None:
        while True:
            with q.cv:
                while not q.items and self._running:
                    q.cv.wait(0.5)
                if not q.items:
                    return  # shut down and drained
                fut, fn, nbytes = q.items.popleft()
                depth = len(q.items)
                q.cv.notify_all()  # wake backpressured submitters
            self._run_job(q, fut, fn, nbytes, depth)
            # an idle worker must not pin its last job's closure or
            # result (a decoded read-ahead batch is many MiB) until
            # the next job happens to arrive
            del fut, fn

    def submit_hedged(self, key, fn, nbytes: int = 0) -> IOFuture:
        """Launch a duplicate/alternate read racing a straggler
        (first useful result wins; the caller abandons whichever
        future it stops caring about).  Same ordered-queue semantics
        as ``submit`` — the hedge targets a DIFFERENT disk's queue, so
        it never queues behind the straggler it is hedging against.
        Counted as ``miniotpu_hedge_launched_total``."""
        try:
            _kernel_stats().record_hedge("launched")
        except Exception as exc:  # telemetry must never block a hedge
            _log.warning("hedge stats failed", extra=kv(err=str(exc)))
        return self.submit(key, fn, nbytes=nbytes)

    def _run_job(self, q, fut, fn, nbytes, depth) -> None:
        if fut.abandoned:
            # hedged past while still queued: resolve without running
            # so the band slot frees now, not behind a straggling disk
            fut._resolve(
                None, IopoolAbandoned("job abandoned before dequeue")
            )
            return
        t0 = time.monotonic()
        result = None
        error: "BaseException | None" = None
        try:
            result = fn()
        except BaseException as e:  # noqa: BLE001 - surfaced via future
            error = e
        try:
            _stats_record_job(
                q.label, nbytes, time.monotonic() - t0, depth
            )
        except Exception as exc:  # stats must never wedge a future
            _log.warning("iopool stats failed", extra=kv(err=str(exc)))
        fut._resolve(result, error)

    # -- lifecycle --------------------------------------------------------

    def shutdown(self, timeout: float = 10.0) -> None:
        """Drain every queue and join the workers (tests / reset)."""
        with self._mu:
            self._running = False
        for q in self._queues:
            with q.cv:
                q.cv.notify_all()
        for q in self._queues:
            t = q.thread
            if t is not None:
                t.join(timeout)

    def live_workers(self) -> int:
        return sum(
            1
            for q in self._queues
            if q.thread is not None and q.thread.is_alive()
        )

    def queued_jobs(self) -> int:
        """Jobs waiting (not yet dequeued) across every band — the
        server plane's codec-stage queue-depth gauge samples this."""
        return sum(len(q.items) for q in self._queues)


class ShardFlusher:
    """Quorum-aware batch completion over an IOPool.

    One flusher per encode call.  ``flush(jobs, quorum)`` submits every
    job and returns once ``quorum`` distinct slots fully acked this
    batch — surviving stragglers drain in the background and are
    awaited by ``drain()`` (or the next flush's quorum math).  Failed
    slots accumulate; ``flush``/``drain`` return the newly-dead set so
    the caller can mark ``writers[s] = None``.
    """

    def __init__(self, pool: IOPool, quorum_exc: type = RuntimeError):
        self._pool = pool
        self._quorum_exc = quorum_exc
        self._cv = threading.Condition()
        self._pending_total = 0
        self._gen = 0
        self._cur_gen = -1
        self._cur_pending: "dict[int, int]" = {}
        self._cur_failed: "set[int]" = set()
        self._gen_pending: "dict[int, int]" = {}
        self._slot_pending: "dict[int, int]" = {}
        self._dead: "set[int]" = set()
        self._reported: "set[int]" = set()
        self._acked_gens: "set[int]" = set()
        self.submitted = 0
        # Invoked (outside the flusher lock) as on_late_dead(slot, err)
        # when a job fails AFTER its batch already returned from
        # flush() — i.e. past the quorum ack, where nobody is left
        # waiting to observe the error.  The quorum-early commit path
        # points this at ParityBand.flag_heal so a parity straggler
        # dying behind an acked PUT is heal-flagged, never silent.
        self.on_late_dead = None

    def _on_done(self, gen: int, slot: int, fut: IOFuture) -> None:
        late_cb = None
        with self._cv:
            self._pending_total -= 1
            left = self._gen_pending.get(gen, 1) - 1
            if left <= 0:
                self._gen_pending.pop(gen, None)
            else:
                self._gen_pending[gen] = left
            sleft = self._slot_pending.get(slot, 1) - 1
            if sleft <= 0:
                self._slot_pending.pop(slot, None)
            else:
                self._slot_pending[slot] = sleft
            if fut.error is not None:
                self._dead.add(slot)
                _log.warning(
                    "shard writer failed; disk marked dead",
                    extra=kv(slot=slot, err=str(fut.error)),
                )
                if gen in self._acked_gens:
                    late_cb = self.on_late_dead
            if gen == self._cur_gen:
                self._cur_pending[slot] = self._cur_pending.get(slot, 1) - 1
                if fut.error is not None:
                    self._cur_failed.add(slot)
            self._cv.notify_all()
        if late_cb is not None:
            try:
                late_cb(slot, fut.error)
            except Exception as exc:  # observer bugs must not kill workers
                _log.warning(
                    "late-dead callback failed", extra=kv(err=str(exc))
                )

    def _take_dead_locked(self) -> "set[int]":
        new = self._dead - self._reported
        self._reported |= new
        return new

    def flush(self, jobs, quorum: int) -> "set[int]":
        """jobs: [(slot, key, fn, nbytes), ...].  Blocks until quorum
        slots acked every one of their jobs in this batch; raises
        ``quorum_exc`` the moment quorum becomes unreachable."""
        slots = {s for s, _k, _f, _n in jobs}
        gen = self._gen = self._gen + 1
        with self._cv:
            # bounded overlap: the previous batch must fully drain
            # before this one submits — the quorum-early return still
            # hides a straggler behind the NEXT batch's assemble+codec
            # work, but pinned shard buffers stay capped at ~1 batch
            # regardless of object size
            while any(
                g < gen and c > 0
                for g, c in self._gen_pending.items()
            ):
                self._cv.wait()
            self._cur_gen = gen
            self._cur_pending = {}
            self._cur_failed = set()
            for s, _k, _f, _n in jobs:
                self._cur_pending[s] = self._cur_pending.get(s, 0) + 1
            self._gen_pending[gen] = len(jobs)
            for s, _k, _f, _n in jobs:
                self._slot_pending[s] = self._slot_pending.get(s, 0) + 1
            self._pending_total += len(jobs)
            self.submitted += len(jobs)
        for slot, key, fn, nbytes in jobs:
            fut = self._pool.submit(key, fn, nbytes=nbytes)
            fut.add_done_callback(
                lambda f, g=gen, s=slot: self._on_done(g, s, f)
            )
        with self._cv:
            while True:
                acked = sum(
                    1
                    for s in slots
                    if self._cur_pending.get(s, 0) == 0
                    and s not in self._cur_failed
                )
                if acked >= quorum:
                    self._acked_gens.add(gen)
                    return self._take_dead_locked()
                possible = len(slots) - len(self._cur_failed)
                if possible < quorum:
                    # dead slots stay un-reported: the caller's error
                    # path drain() still gets to mark its writers
                    self._acked_gens.add(gen)
                    raise self._quorum_exc(
                        f"write quorum lost: {possible} < {quorum}"
                    )
                self._cv.wait()

    def drain(self) -> "set[int]":
        """Wait for every outstanding job (all batches); newly-dead set."""
        with self._cv:
            while self._pending_total > 0:
                self._cv.wait()
            return self._take_dead_locked()

    def drain_slots(self, slots) -> "set[int]":
        """Wait until every outstanding job for ``slots`` (all batches)
        finished; return the newly-dead subset of ``slots``.

        The quorum-early commit path drains ONLY the data slots before
        acking — parity slots keep streaming in the background band and
        are settled by the ParityBand afterwards."""
        want = set(slots)
        with self._cv:
            while any(self._slot_pending.get(s, 0) > 0 for s in want):
                self._cv.wait()
            new = (self._dead - self._reported) & want
            self._reported |= new
            return new


class ParityBand:
    """Background drain band for the quorum-early parity plane.

    The commit path acks a PUT at data-shard write quorum and hands the
    still-pending parity work to this band: straggling parity writes
    adopted from the ShardFlusher, plus the parity close/rename jobs
    submitted here.  Everything that fails PAST the ack is heal-flagged
    — logged, counted (miniotpu_codec_stream_heal_required_total) and
    surfaced via ``heal_required``/``dead_slots`` to the object layer's
    heal hook — never silent.  ``finish`` parks the settle wait on the
    pool's aux band so the request thread returns at ack time.
    """

    def __init__(self, pool: "IOPool | None" = None):
        self._pool = pool or get_pool()
        self._lk = threading.Lock()
        self._futs: "list[tuple[int, IOFuture]]" = []
        self._flusher: "ShardFlusher | None" = None
        self._flagged: "set[int]" = set()
        self.heal_required = False
        self.dead_slots: "set[int]" = set()

    def submit(self, slot: int, key, fn) -> IOFuture:
        """Post-ack job (parity close / rename) on the MAIN band under
        the disk's own routing key: queue order after that disk's
        writes gives write -> close -> rename for free."""
        fut = self._pool.submit(key, fn)
        with self._lk:
            self._futs.append((slot, fut))
        return fut

    def adopt(self, flusher: ShardFlusher) -> None:
        """Take ownership of a flusher's straggling parity jobs: late
        deaths flag heal immediately; settle() awaits the rest."""
        with self._lk:
            self._flusher = flusher
        flusher.on_late_dead = self.flag_heal

    @property
    def adopted(self) -> bool:
        """True once encode handed its flusher over — i.e. the encode
        actually ran quorum-early (False means it fell back to the
        legacy settle path and the band has nothing to own)."""
        with self._lk:
            return self._flusher is not None

    def flag_heal(self, slot: int, err) -> None:
        """Idempotent per slot (a slot can be reported both by the
        late-dead callback and by the settle-time drain)."""
        with self._lk:
            if slot in self._flagged:
                return
            self._flagged.add(slot)
            self.heal_required = True
            self.dead_slots.add(slot)
        _log.warning(
            "parity drain failed past ack; object flagged for heal",
            extra=kv(slot=slot, err=str(err)),
        )
        try:
            _kernel_stats().record_heal_required()
        except Exception as exc:  # telemetry must never block settle
            _log.warning("heal stats failed", extra=kv(err=str(exc)))

    def settle(self) -> bool:
        """Wait for every adopted/submitted job; True when all clean."""
        with self._lk:
            futs = list(self._futs)
            flusher = self._flusher
        if flusher is not None:
            for s in flusher.drain():
                self.flag_heal(s, "parity straggler write failed")
        for slot, fut in futs:
            fut.wait()
            err = fut.error
            if err is not None:
                self.flag_heal(slot, err)
        return not self.heal_required

    def finish(self, on_done=None) -> IOFuture:
        """Settle in the BACKGROUND (aux band — settle blocks on main-
        band futures) and then invoke ``on_done(band)`` with the
        verdict; returns the settle future for tests/drain barriers."""

        def _settle():
            clean = self.settle()
            if on_done is not None:
                on_done(self)
            return clean

        return self._pool.submit(
            ("parityband", id(self)), _settle, aux=True
        )


# -- telemetry seam (lazy: avoid import cycles, tolerate bare installs) ---

_KS = None


def _kernel_stats():
    global _KS
    if _KS is None:
        from ..codec.telemetry import KERNEL_STATS

        _KS = KERNEL_STATS
    return _KS


def _stats_record_job(queue: str, nbytes: int, seconds: float, depth: int):
    _kernel_stats().record_io_job(queue, nbytes, seconds, depth)


def _stats_record_depth(queue: str, depth: int):
    _kernel_stats().record_io_depth(queue, depth)


# -- process-wide singleton (one I/O plane per process) -------------------

_POOL: "IOPool | None" = None
_POOL_LK = threading.Lock()


def get_pool() -> IOPool:
    global _POOL
    p = _POOL
    if p is None:
        with _POOL_LK:
            if _POOL is None:
                _POOL = IOPool()
            p = _POOL
    return p


def queued_depth() -> int:
    """Codec-stage queue-depth gauge for the server plane — reads the
    singleton without instantiating it (a scrape must not boot an I/O
    plane)."""
    p = _POOL
    return p.queued_jobs() if p is not None else 0


def reset_pool() -> None:
    """Shut down and discard the singleton (tests)."""
    global _POOL
    with _POOL_LK:
        p, _POOL = _POOL, None
    if p is not None:
        p.shutdown()


def stream_io_key(stream):
    """Routing key of a tagged writer/reader (identity fallback keeps
    untagged streams hash-routed without serializing them)."""
    return getattr(stream, "io_key", None) or id(stream)


def fanout(ops, pool: "IOPool | None" = None) -> list:
    """Run ``[(key, fn), ...]`` concurrently; return ``[error, ...]``
    (None on success) in submission order.  The object layer's per-disk
    commit loops (writer close -> fsync, rename_data -> meta fsync) go
    through here so a PUT pays one disk's metadata latency, not the sum
    over all n — fsync parks in the kernel and releases the GIL, so the
    overlap is real even on a single-core host."""
    p = pool or get_pool()
    futs = [p.submit(k, f) for k, f in ops]
    errs = []
    for fut in futs:
        fut.wait()
        errs.append(fut.error)
    return errs


def wait_any(futs, timeout: "float | None" = None) -> list:
    """Block until at least one future is finished; return the finished
    subset (empty list = deadline expired with nothing done).

    This is the hedging loop's clock: ``codec/erasure.py`` waits on its
    outstanding shard reads with the p99-derived deadline and, when the
    list comes back empty, launches a duplicate read on the next
    preferred shard instead of blocking on the straggler.
    """
    done = [f for f in futs if f.done()]
    if done or not futs:
        return done
    ev = threading.Event()

    def _wake(_f, _ev=ev):
        _ev.set()

    for f in futs:
        f.add_done_callback(_wake)
    ev.wait(timeout)
    return [f for f in futs if f.done()]


def tag_io_key(obj, key: str) -> None:
    """Stamp a writer/reader with its routing key (best effort: remote
    stubs with __slots__ simply keep id()-hash routing)."""
    try:
        obj.io_key = key
    except AttributeError as exc:
        _log.debug("io_key tag skipped", extra=kv(key=key, err=str(exc)))


def disk_io_key(disk) -> "str | None":
    """Stable routing key for a StorageAPI disk: its endpoint string
    (MeteredDisk exposes the unwrapped disk's endpoint)."""
    for attr in ("metered_endpoint", "endpoint"):
        fn = getattr(disk, attr, None)
        if fn is None:
            continue
        try:
            return str(fn())
        except Exception as exc:
            _log.debug(
                "disk endpoint probe failed",
                extra=kv(attr=attr, err=str(exc)),
            )
    return None


def tag_disk_stream(stream, disk):
    """Route a shard writer/reader to its disk's ordered pool queue;
    returns the stream for inline use at construction sites."""
    if stream is not None:
        key = disk_io_key(disk)
        if key:
            tag_io_key(stream, key)
    return stream
