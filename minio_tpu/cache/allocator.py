"""Shared device-memory budget ledger for cache planes.

The parity plane (codec/backend.py ParityPlaneCache) and the read
cache's device hot tier both pin bytes in device memory.  Each plane
keeps its own eviction policy, but they draw on ONE budget: the ledger
tracks live bytes per account so the read cache can size its effective
device capacity to what the parity plane is not using, instead of the
two planes independently believing they own the whole device.

Accounts are advisory for the parity plane (its own capacity knob
still bounds it — PR 7 tests depend on that contract) and binding for
the read cache, which computes headroom against the combined total.
"""

from __future__ import annotations

import os
import threading

DEFAULT_BUDGET_MB = 192


class DeviceBudget:
    """Thread-safe ledger: account name -> live device bytes."""

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = int(capacity_bytes)
        self._mu = threading.Lock()
        self._usage: dict[str, int] = {}

    def set_usage(self, account: str, nbytes: int) -> None:
        with self._mu:
            if nbytes <= 0:
                self._usage.pop(account, None)
            else:
                self._usage[account] = int(nbytes)

    def usage(self, account: "str | None" = None) -> int:
        with self._mu:
            if account is not None:
                return self._usage.get(account, 0)
            return sum(self._usage.values())

    def headroom(self) -> int:
        """Unclaimed device bytes under the combined budget."""
        return max(0, self.capacity_bytes - self.usage())

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "capacity_bytes": self.capacity_bytes,
                "usage_bytes": sum(self._usage.values()),
                "accounts": dict(self._usage),
            }


_lock = threading.Lock()
_BUDGET: "DeviceBudget | None" = None


def device_budget() -> DeviceBudget:
    """Process-wide ledger; capacity from MINIO_TPU_DEVICE_BUDGET_MB
    (default covers the parity plane default plus a device hot tier)."""
    global _BUDGET
    with _lock:
        if _BUDGET is None:
            try:
                mb = int(
                    os.environ.get(
                        "MINIO_TPU_DEVICE_BUDGET_MB", str(DEFAULT_BUDGET_MB)
                    )
                )
            except ValueError:
                mb = DEFAULT_BUDGET_MB
            _BUDGET = DeviceBudget(max(1, mb) << 20)
        return _BUDGET


def reset_device_budget() -> None:
    global _BUDGET
    with _lock:
        _BUDGET = None
