"""minio_tpu.cache — tiered read cache for hot encoded groups.

Process-wide singleton gated by MINIO_TPU_READ_CACHE:

* ``off``  (default) — GETs take exactly the quorum-read path; the
  bisection oracle for every cache bug.
* ``host``   — single host-RAM tier.
* ``device`` — device hot tier + host second tier.
* ``auto``   — ``device`` when a non-CPU jax device is visible,
  ``host`` otherwise.

Budget knobs: MINIO_TPU_READ_CACHE_MB (host tier, default 64),
MINIO_TPU_READ_CACHE_DEVICE_MB (device tier, default 64, additionally
bounded by the shared DeviceBudget it splits with the parity plane).

Cross-node coherence: the object layer calls ``invalidate_object`` on
every mutation; the server registers a broadcast hook wired to
``PeerNotifier.read_cache_invalidated`` so peers drop their copies
(``invalidate_local`` is the remote-called twin that must NOT
re-broadcast).
"""

from __future__ import annotations

import logging
import os
import threading

from .admission import AdmissionFilter, FrequencySketch
from .allocator import DeviceBudget, device_budget, reset_device_budget
from .tiered import ReadCacheContext, TieredReadCache, TIERS

__all__ = [
    "AdmissionFilter",
    "FrequencySketch",
    "DeviceBudget",
    "device_budget",
    "reset_device_budget",
    "ReadCacheContext",
    "TieredReadCache",
    "TIERS",
    "cache_mode",
    "read_cache",
    "reset_read_cache",
    "context_for",
    "invalidate_object",
    "invalidate_local",
    "set_broadcast",
    "seed_heat",
    "read_cache_stats",
    "clear_read_cache",
]

_log = logging.getLogger("minio_tpu.cache")

_lock = threading.Lock()
_CACHE: "TieredReadCache | None" = None
_MODE: "str | None" = None
_BROADCAST = None  # fn(bucket, object_name) -> None, server-registered


def _env_mb(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, str(default))))
    except ValueError:
        return default


def cache_mode() -> str:
    """Resolved mode: off | host | device (auto resolves here)."""
    raw = os.environ.get("MINIO_TPU_READ_CACHE", "off").strip().lower()
    if raw in ("off", "host", "device"):
        return raw
    if raw == "auto":
        try:
            import jax

            if any(d.platform != "cpu" for d in jax.devices()):
                return "device"
        except Exception as exc:  # noqa: BLE001 - no jax, no device tier
            _log.debug("auto mode: no device tier: %s", exc)
        return "host"
    return "off"


def read_cache() -> "TieredReadCache | None":
    """The process singleton, or None when the mode is off."""
    global _CACHE, _MODE
    with _lock:
        if _MODE is None:
            _MODE = cache_mode()
            if _MODE != "off":
                _CACHE = TieredReadCache(
                    mode=_MODE,
                    host_capacity=_env_mb("MINIO_TPU_READ_CACHE_MB", 64)
                    << 20,
                    device_capacity=_env_mb(
                        "MINIO_TPU_READ_CACHE_DEVICE_MB", 64
                    )
                    << 20,
                    budget=device_budget() if _MODE == "device" else None,
                )
        return _CACHE


def reset_read_cache() -> None:
    """Testing/admin aid: drop the singleton so the next call re-reads
    the environment (mirrors codec.backend.reset_backend)."""
    global _CACHE, _MODE
    with _lock:
        _CACHE = None
        _MODE = None


def context_for(
    bucket: str, object_name: str, data_dir: str, part: int
) -> "ReadCacheContext | None":
    c = read_cache()
    if c is None:
        return None
    return ReadCacheContext(c, bucket, object_name, data_dir, part)


def set_broadcast(fn) -> None:
    """Register the cross-node fan-out (PeerNotifier hook)."""
    global _BROADCAST
    _BROADCAST = fn


def invalidate_object(bucket: str, object_name: str) -> int:
    """Mutation seam: drop local cached groups AND tell every peer.
    Called on PUT/overwrite/heal/delete before the caller acks."""
    n = invalidate_local(bucket, object_name)
    fn = _BROADCAST
    if fn is not None:
        try:
            fn(bucket, object_name)
        except Exception as exc:  # noqa: BLE001 - fan-out is fire-and-forget
            _log.debug("invalidate broadcast failed: %s", exc)
    return n


def invalidate_local(bucket: str, object_name: str) -> int:
    """Peer-RPC twin of invalidate_object: never re-broadcasts."""
    c = _CACHE
    if c is None:
        return 0
    return c.invalidate(bucket, object_name)


def clear_read_cache() -> int:
    """Admin aid: drop every cached group (keeps admission history).
    Returns the number of entries dropped."""
    c = _CACHE
    if c is None:
        return 0
    return c.clear()


def seed_heat(bucket: str, object_name: str, hits: int = 2) -> None:
    """Crawler heat: pre-credit an object's admission frequency."""
    c = read_cache()
    if c is not None:
        c.admission.seed(f"{bucket}/{object_name}", hits=hits)


def _zero_stats() -> dict:
    tiers = {
        t: {
            "hits": 0, "misses": 0, "evictions": 0, "rejects": 0,
            "entries": 0, "occupancy_bytes": 0, "capacity_bytes": 0,
        }
        for t in TIERS
    }
    return {
        "mode": "off",
        "tiers": tiers,
        "demotions": 0,
        "invalidations": 0,
        "verify_drops": 0,
        "admission": {
            "recorded": 0, "seeded": 0, "admitted": 0, "rejected": 0,
            "sketch_ages": 0,
        },
    }


def read_cache_stats() -> dict:
    """Zero-filled when the cache is off/unused, so metrics and
    healthinfo render identical shapes in every mode."""
    c = _CACHE
    if c is None:
        return _zero_stats()
    return c.stats()
