"""Two-tier read cache for digest-verified encoded groups.

The unit of caching is one decode group: the (g, k, shard_len) data
rows of ``g`` equal-size blocks plus their (g, k, 8) uint32 bitrot
digest words — exactly what ``Erasure._decode_blocks`` needs to stream
a group without touching ``_read_group_quorum``.  Entries are keyed by
(bucket, object, data_dir, part, first_block, g, shard_len): the
data_dir makes every PUT generation a distinct key space, and a
(bucket, object) prefix index gives O(entries-per-object)
invalidation.

Tiers:

* device — hot tier; the group's data rows live as a device array
  (the PUT path already had them on device before the ack), charged
  against the shared DeviceBudget so the parity plane and the read
  cache split one pool instead of double-booking device memory.
* host — second tier; plain numpy.  Device evictions demote here
  (write-back generalization of ParityPlaneCache's drain); host
  evictions drop.

Both tiers sit behind the TinyLFU admission contest (admission.py),
and every hit re-verifies the stored digests against the stored rows
before serving — a corrupted cached group is dropped and falls back
to the quorum-read path, never served.
"""

from __future__ import annotations

import copy
import threading
from collections import OrderedDict

import numpy as np

from .admission import AdmissionFilter
from .allocator import DeviceBudget

TIER_DEVICE = "device"
TIER_HOST = "host"
TIERS = (TIER_DEVICE, TIER_HOST)

BUDGET_ACCOUNT = "read_cache"


def _to_device(arr: np.ndarray):
    """Pin an array in device memory; None when no device path exists
    (jax absent/broken) so the caller can fall back to the host tier."""
    try:
        import jax

        return jax.device_put(arr)
    except Exception:  # noqa: BLE001 - host tier is the fallback
        return None


class _Entry:
    __slots__ = ("key", "heat_key", "data", "digests", "tier",
                 "nbytes", "pins")

    def __init__(self, key, heat_key, data, digests, tier, nbytes):
        self.key = key
        self.heat_key = heat_key
        self.data = data
        self.digests = digests
        self.tier = tier
        self.nbytes = nbytes
        self.pins = 0


class TieredReadCache:
    """Bounded two-tier group cache with admission, pinning and
    prefix invalidation.  All bookkeeping sits under one lock; the
    digest re-verification on hit runs OUTSIDE it with the entry
    pinned, so eviction never yanks a group mid-serve."""

    def __init__(
        self,
        mode: str,
        host_capacity: int,
        device_capacity: int,
        admission: "AdmissionFilter | None" = None,
        budget: "DeviceBudget | None" = None,
    ):
        if mode not in (TIER_HOST, TIER_DEVICE):
            raise ValueError(f"bad cache mode {mode!r}")
        self.mode = mode
        self._mu = threading.Lock()
        self._tiers: "dict[str, OrderedDict]" = {
            t: OrderedDict() for t in TIERS
        }
        self._caps = {
            TIER_DEVICE: int(device_capacity) if mode == TIER_DEVICE else 0,
            TIER_HOST: int(host_capacity),
        }
        self._bytes = {t: 0 for t in TIERS}
        self._index: "dict[tuple, set]" = {}
        self.admission = admission or AdmissionFilter()
        self._budget = budget
        self._order = (
            (TIER_DEVICE, TIER_HOST) if mode == TIER_DEVICE
            else (TIER_HOST,)
        )
        self._hits = {t: 0 for t in TIERS}
        self._misses = {t: 0 for t in TIERS}
        self._evictions = {t: 0 for t in TIERS}
        self._rejects = {t: 0 for t in TIERS}
        self._demotions = 0
        self._invalidations = 0
        self._verify_drops = 0
        # FileInfo side-car: the latest-version metadata a locked GET
        # just quorum-read, keyed (bucket, object) and dropped through
        # the SAME invalidation seam as the groups — a full hit then
        # skips the per-GET xl.meta fan-out too.  Small fixed-count
        # LRU; entries are deep-copied both ways so no caller ever
        # aliases the stored FileInfo.
        self._meta: "OrderedDict[tuple, object]" = OrderedDict()
        self._meta_cap = 4096

    # ---- read side ------------------------------------------------------

    def lookup(self, be, key: tuple, heat_key: str):
        """Return the verified (g, k, shard_len) data rows, or None."""
        self.admission.record(heat_key)
        with self._mu:
            ent = None
            for tier in self._order:
                e = self._tiers[tier].get(key)
                if e is None:
                    self._misses[tier] += 1
                    continue
                e.pins += 1
                self._tiers[tier].move_to_end(key)
                ent = e
                break
            if ent is None:
                return None
        try:
            data = np.asarray(ent.data)
            # verify on the raw backend: the batcher's submit/coalesce
            # hop buys nothing for a single synchronous digest pass and
            # costs ~0.5 ms of thread handoff per hit
            vbe = getattr(be, "inner", be)
            good = bool(np.all(vbe.verify(data, ent.digests)))
        finally:
            with self._mu:
                ent.pins -= 1
        if not good:
            # the cached copy rotted (or was tampered with): drop it
            # and miss through to the quorum read, which has the real
            # on-disk digests to arbitrate
            with self._mu:
                self._drop(key)
                self._rejects[ent.tier] += 1
                self._misses[ent.tier] += 1
                self._verify_drops += 1
            return None
        with self._mu:
            self._hits[ent.tier] += 1
        return data

    def device_entries(self, bucket: str, object_name: str) -> dict:
        """Device-tier group arrays of one object, keyed by full cache
        key, WITHOUT host materialization — the S3 Select pushdown
        assembles them into a scan plane entirely on device.

        Device-tier only by design: jax buffers are immutable once
        put, so the host-side rot re-verification ``lookup`` performs
        (which would cost a full D2H) does not apply; a host-tier or
        missing group simply keeps the scan on the spooled read path."""
        with self._mu:
            keys = self._index.get((bucket, object_name), ())
            out = {}
            for key in keys:
                e = self._tiers[TIER_DEVICE].get(key)
                if e is not None:
                    self._tiers[TIER_DEVICE].move_to_end(key)
                    out[key] = e.data
            if out:
                self._hits[TIER_DEVICE] += len(out)
            return out

    # ---- write side -----------------------------------------------------

    def put(
        self, key: tuple, heat_key: str,
        data: np.ndarray, digests: np.ndarray, source: str = "get",
    ) -> bool:
        """Admit one group.  ``data``/``digests`` must be safe for the
        cache to retain (callers copy views).  Returns admitted."""
        nbytes = int(data.nbytes) + int(digests.nbytes)
        if source == "put":
            # a fresh write gets one frequency credit; it still cannot
            # displace an established hot object (contest is strict >)
            self.admission.record(heat_key)
        with self._mu:
            self._drop(key)  # replacement: never two generations
            target = TIER_DEVICE if self._caps[TIER_DEVICE] else TIER_HOST
            if not self._make_room(target, nbytes, heat_key):
                if target == TIER_DEVICE:
                    target = TIER_HOST
                    if not self._make_room(target, nbytes, heat_key):
                        self._rejects[target] += 1
                        return False
                else:
                    self._rejects[target] += 1
                    return False
            stored = data
            if target == TIER_DEVICE:
                dev = _to_device(data)
                if dev is None:
                    target = TIER_HOST
                    if not self._make_room(target, nbytes, heat_key):
                        self._rejects[target] += 1
                        return False
                else:
                    stored = dev
            ent = _Entry(key, heat_key, stored, digests, target, nbytes)
            self._tiers[target][key] = ent
            self._bytes[target] += nbytes
            self._index.setdefault((key[0], key[1]), set()).add(key)
            self._account()
            return True

    # ---- FileInfo side-car ----------------------------------------------

    def meta_lookup(self, bucket: str, object_name: str):
        """Latest-version FileInfo cached by a locked GET, or None.

        The returned object is SHARED across hits — the GET path only
        reads it (``_to_object_info`` copies metadata/parts before
        anything downstream may mutate), and a deepcopy here would be
        the single biggest cost of a fully-cached GET."""
        with self._mu:
            fi = self._meta.get((bucket, object_name))
            if fi is not None:
                self._meta.move_to_end((bucket, object_name))
            return fi

    def meta_store(self, bucket: str, object_name: str, fi) -> None:
        """Retain the FileInfo a quorum read just produced (deep-copied
        once here so no caller aliases the stored instance).  Callers
        MUST hold the object's namespace lock for the read that
        produced ``fi`` — the lock orders this store against the
        post-commit invalidate of any concurrent mutation."""
        with self._mu:
            self._meta[(bucket, object_name)] = copy.deepcopy(fi)
            self._meta.move_to_end((bucket, object_name))
            while len(self._meta) > self._meta_cap:
                self._meta.popitem(last=False)

    # ---- invalidation ---------------------------------------------------

    def invalidate(self, bucket: str, object_name: str) -> int:
        """Drop every cached group AND the FileInfo side-car entry of
        (bucket, object); returns the group count."""
        with self._mu:
            self._meta.pop((bucket, object_name), None)
            keys = self._index.pop((bucket, object_name), None)
            if not keys:
                return 0
            n = 0
            for key in list(keys):
                if self._drop(key, unindex=False):
                    n += 1
            self._invalidations += 1
            self._account()
            return n

    def clear(self) -> int:
        with self._mu:
            n = sum(len(t) for t in self._tiers.values())
            for t in TIERS:
                self._tiers[t].clear()
                self._bytes[t] = 0
            self._index.clear()
            self._meta.clear()
            self._account()
            return n

    # ---- internals (lock held) ------------------------------------------

    def _account(self) -> None:
        if self._budget is not None:
            self._budget.set_usage(
                BUDGET_ACCOUNT, self._bytes[TIER_DEVICE]
            )

    def _drop(self, key: tuple, unindex: bool = True) -> bool:
        for tier in TIERS:
            ent = self._tiers[tier].pop(key, None)
            if ent is not None:
                self._bytes[tier] -= ent.nbytes
                if unindex:
                    pref = self._index.get((key[0], key[1]))
                    if pref is not None:
                        pref.discard(key)
                        if not pref:
                            del self._index[(key[0], key[1])]
                return True
        return False

    def _free(self, tier: str) -> int:
        free = self._caps[tier] - self._bytes[tier]
        if tier == TIER_DEVICE and self._budget is not None:
            # the parity plane's live occupancy shrinks our headroom:
            # one device, one budget
            free = min(free, self._budget.headroom())
        return free

    def _make_room(self, tier: str, nbytes: int, heat_key: str) -> bool:
        if self._caps[tier] <= 0 or nbytes > self._caps[tier]:
            return False
        while self._free(tier) < nbytes:
            victim = next(
                (e for e in self._tiers[tier].values() if e.pins == 0),
                None,
            )
            if victim is None:
                return False  # everything pinned mid-serve
            if not self.admission.contest(heat_key, victim.heat_key):
                return False
            self._evict(victim)
        return True

    def _evict(self, ent: "_Entry") -> None:
        self._tiers[ent.tier].pop(ent.key, None)
        self._bytes[ent.tier] -= ent.nbytes
        self._evictions[ent.tier] += 1
        if ent.tier == TIER_DEVICE:
            # write-back demotion: the device copy drains to the host
            # tier (same admission contest against host victims) before
            # the device bytes free up
            if self._make_room(TIER_HOST, ent.nbytes, ent.heat_key):
                ent.data = np.asarray(ent.data)
                ent.tier = TIER_HOST
                self._tiers[TIER_HOST][ent.key] = ent
                self._bytes[TIER_HOST] += ent.nbytes
                self._demotions += 1
                self._account()
                return
        pref = self._index.get((ent.key[0], ent.key[1]))
        if pref is not None:
            pref.discard(ent.key)
            if not pref:
                del self._index[(ent.key[0], ent.key[1])]
        self._account()

    # ---- introspection --------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            tiers = {}
            for t in TIERS:
                tiers[t] = {
                    "hits": self._hits[t],
                    "misses": self._misses[t],
                    "evictions": self._evictions[t],
                    "rejects": self._rejects[t],
                    "entries": len(self._tiers[t]),
                    "occupancy_bytes": self._bytes[t],
                    "capacity_bytes": self._caps[t],
                }
            return {
                "mode": self.mode,
                "tiers": tiers,
                "demotions": self._demotions,
                "invalidations": self._invalidations,
                "verify_drops": self._verify_drops,
                "admission": self.admission.stats(),
            }


class ReadCacheContext:
    """Per-(object, part) handle the codec threads through decode and
    encode: owns the key prefix so erasure.py only speaks in
    (first_block, g, shard_len) group coordinates."""

    __slots__ = ("cache", "bucket", "object_name", "data_dir", "part")

    def __init__(self, cache, bucket, object_name, data_dir, part):
        self.cache = cache
        self.bucket = bucket
        self.object_name = object_name
        self.data_dir = data_dir
        self.part = part

    def _key(self, first_block: int, g: int, shard_len: int) -> tuple:
        return (
            self.bucket, self.object_name, self.data_dir, self.part,
            first_block, g, shard_len,
        )

    @property
    def heat_key(self) -> str:
        return f"{self.bucket}/{self.object_name}"

    def lookup(self, be, first_block, g, shard_len):
        return self.cache.lookup(
            be, self._key(first_block, g, shard_len), self.heat_key
        )

    def admit_from_decode(self, first_block, g, shard_len,
                          data, digests) -> bool:
        """Cache-miss GET population: the decoded data rows + digest
        words (on-disk words when the data slots read intact, freshly
        computed when rows were reconstructed from verified parity;
        views into the quorum-read frame buffer are copied here so the
        cache owns its bytes)."""
        return self.cache.put(
            self._key(first_block, g, shard_len),
            self.heat_key,
            np.ascontiguousarray(data),
            np.ascontiguousarray(digests),
            source="get",
        )

    def populate_from_encode(self, first_block, batch, digests_u32) -> bool:
        """PUT population: the encode batch's data rows are already
        assembled (and device-resident in digest mode); the batch array
        is immutable after the encode began, so the host tier retains
        it zero-copy."""
        g, _k, shard_len = batch.shape
        return self.cache.put(
            self._key(first_block, g, shard_len),
            self.heat_key,
            batch,
            np.ascontiguousarray(digests_u32),
            source="put",
        )
