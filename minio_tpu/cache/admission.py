"""TinyLFU-style frequency admission for the tiered read cache.

A small count-min sketch estimates per-object access frequency (4-bit
counters, conservative update, periodic halving so the window tracks
RECENT popularity — the TinyLFU aging step).  Admission is the classic
contest: a candidate only displaces the eviction victim when its
estimated frequency is strictly higher, so a one-shot scan (frequency
1 per key) can never evict an established working set.

Keys are OBJECT-level ("bucket/object"), not group-level: one hot
object admits all of its encoded groups, and the crawler can seed heat
for keys it observes without knowing shard geometry.
"""

from __future__ import annotations

import hashlib
import threading

_MAX_COUNT = 15  # 4-bit counters, TinyLFU-style saturation
_ROW_SEEDS = (
    0x9E3779B97F4A7C15,
    0xC2B2AE3D27D4EB4F,
    0x165667B19E3779F9,
    0x27D4EB2F165667C5,
)


def _hash64(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "little"
    )


class FrequencySketch:
    """Count-min sketch with saturating counters and halving decay."""

    def __init__(self, width: int = 4096, depth: int = 4,
                 sample_factor: int = 8):
        if width & (width - 1):
            raise ValueError("width must be a power of two")
        self.width = width
        self.depth = min(depth, len(_ROW_SEEDS))
        self._rows = [bytearray(width) for _ in range(self.depth)]
        self._ops = 0
        self._sample = width * sample_factor
        self.ages = 0

    def _indexes(self, key: str) -> "list[int]":
        h = _hash64(key)
        mask = self.width - 1
        return [
            ((h ^ _ROW_SEEDS[r]) * _ROW_SEEDS[(r + 1) % len(_ROW_SEEDS)]
             >> 17) & mask
            for r in range(self.depth)
        ]

    def touch(self, key: str, hits: int = 1) -> int:
        """Record ``hits`` accesses; returns the new estimate."""
        est = _MAX_COUNT
        for _ in range(max(1, hits)):
            idxs = self._indexes(key)
            est = min(self._rows[r][i] for r, i in enumerate(idxs))
            if est < _MAX_COUNT:
                # conservative update: bump only the minimal counters,
                # halving over-counts from hash collisions
                for r, i in enumerate(idxs):
                    if self._rows[r][i] == est:
                        self._rows[r][i] = est + 1
                est += 1
            self._ops += 1
            if self._ops >= self._sample:
                self._age()
        return est

    def estimate(self, key: str) -> int:
        idxs = self._indexes(key)
        return min(self._rows[r][i] for r, i in enumerate(idxs))

    def _age(self) -> None:
        for row in self._rows:
            for i, v in enumerate(row):
                if v:
                    row[i] = v >> 1
        self._ops = 0
        self.ages += 1


class AdmissionFilter:
    """Frequency-contest gatekeeper in front of both cache tiers."""

    def __init__(self, sketch: "FrequencySketch | None" = None):
        self._mu = threading.Lock()
        self.sketch = sketch or FrequencySketch()
        self.recorded = 0
        self.seeded = 0
        self.admitted = 0
        self.rejected = 0

    def record(self, heat_key: str) -> None:
        with self._mu:
            self.sketch.touch(heat_key)
            self.recorded += 1

    def seed(self, heat_key: str, hits: int = 2) -> None:
        """Crawler heat: pre-warm a key's frequency so the first flood
        request already wins the admission contest."""
        with self._mu:
            self.sketch.touch(heat_key, hits=hits)
            self.seeded += 1

    def estimate(self, heat_key: str) -> int:
        with self._mu:
            return self.sketch.estimate(heat_key)

    def contest(self, candidate: str, victim: "str | None") -> bool:
        """True when ``candidate`` may displace ``victim`` (or there is
        no victim — free space is always admissible)."""
        with self._mu:
            if victim is None:
                ok = True
            else:
                ok = (
                    self.sketch.estimate(candidate)
                    > self.sketch.estimate(victim)
                )
            if ok:
                self.admitted += 1
            else:
                self.rejected += 1
            return ok

    def stats(self) -> dict:
        with self._mu:
            return {
                "recorded": self.recorded,
                "seeded": self.seeded,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "sketch_ages": self.sketch.ages,
            }
