"""Namespace locking: per-object ref-counted RW locks.

Local counterpart of cmd/namespace-lock.go (nsLockMap): every object
operation takes a read or write lock on "<volume>/<path>" so concurrent
PUT/GET/DELETE on one object serialize correctly.  In distributed mode the
same interface is backed by dsync quorum locks (dsync/drwmutex.py),
mirroring distLockInstance (namespace-lock.go:140).
"""

from __future__ import annotations

import contextlib
import threading
import time


class _RWLock:
    """Writer-preference RW lock with timeout support."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        self.ref = 0  # nsLockMap refcount

    def acquire_read(self, timeout: "float | None" = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._writer or self._writers_waiting:
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    return False
                if not self._cond.wait(rem):
                    return False
            self._readers += 1
            return True

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self, timeout: "float | None" = None) -> bool:
        with self._cond:
            self._writers_waiting += 1
            try:
                deadline = (
                    None if timeout is None else time.monotonic() + timeout
                )
                while self._writer or self._readers:
                    rem = (
                        None
                        if deadline is None
                        else deadline - time.monotonic()
                    )
                    if rem is not None and rem <= 0:
                        return False
                    if not self._cond.wait(rem):
                        return False
                self._writer = True
                return True
            finally:
                self._writers_waiting -= 1

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class LockTimeout(Exception):
    pass


class NamespaceLock:
    """nsLockMap: path -> refcounted RW lock, created/destroyed on demand."""

    def __init__(self):
        self._mu = threading.Lock()
        self._locks: dict[str, _RWLock] = {}

    def _get(self, key: str) -> _RWLock:
        with self._mu:
            lk = self._locks.get(key)
            if lk is None:
                lk = self._locks[key] = _RWLock()
            lk.ref += 1
            return lk

    def _put(self, key: str) -> None:
        with self._mu:
            lk = self._locks.get(key)
            if lk is None:
                return
            lk.ref -= 1
            if lk.ref <= 0:
                del self._locks[key]

    @contextlib.contextmanager
    def read(self, volume: str, path: str, timeout: "float | None" = 30.0):
        key = f"{volume}/{path}"
        lk = self._get(key)
        try:
            if not lk.acquire_read(timeout):
                raise LockTimeout(key)
            try:
                yield
            finally:
                lk.release_read()
        finally:
            self._put(key)

    @contextlib.contextmanager
    def write(self, volume: str, path: str, timeout: "float | None" = 30.0):
        key = f"{volume}/{path}"
        lk = self._get(key)
        try:
            if not lk.acquire_write(timeout):
                raise LockTimeout(key)
            try:
                yield
            finally:
                lk.release_write()
        finally:
            self._put(key)


class DistNamespaceLock:
    """NamespaceLock backed by dsync quorum locks (distLockInstance,
    namespace-lock.go:140): selected when the cluster spans more than
    one node, so concurrent object ops from different processes
    serialize through the lock plane."""

    def __init__(self, ds, source: str = ""):
        from ..utils.dyntimeout import DynamicTimeout
        from .drwmutex import DRWMutex, Dsync  # noqa: F401 (typing aid)

        self._ds = ds
        self._source = source
        # self-tuning lock-wait budgets (the reference wraps its object
        # locks in newDynamicTimeout(30s, 1s)); the write budget is
        # overridable so a write that can never reach lock quorum 503s
        # on an operator-chosen clock instead of 30s. Reads keep the
        # full default: a read below quorum fails fast anyway, and a
        # shorter seed decays to the 1s floor quickly enough to shed
        # healthy reads under hot-key load.
        import os

        wbudget = max(
            1.0,
            float(
                os.environ.get("MINIO_TPU_WRITE_LOCK_ACQUIRE_S") or 30.0
            ),
        )
        self._rtimeout = DynamicTimeout(30.0, 1.0)
        self._wtimeout = DynamicTimeout(wbudget, 1.0)

    def release_all(self) -> int:
        """Graceful-shutdown unwind: release every lock this process
        still holds on the cluster, then stop the refresher threads.
        Stragglers a peer could not be told about age out via expiry."""
        released = self._ds.release_all()
        self._ds.close()
        return released

    @contextlib.contextmanager
    def read(self, volume: str, path: str, timeout: "float | None" = None):
        import time as _t

        from .drwmutex import DRWMutex

        if timeout is None:
            timeout = self._rtimeout.timeout
        m = DRWMutex(self._ds, f"{volume}/{path}")
        t0 = _t.monotonic()
        if not m.get_rlock(self._source, timeout):
            self._rtimeout.log_failure()
            raise LockTimeout(f"{volume}/{path}")
        self._rtimeout.log_success(_t.monotonic() - t0)
        try:
            yield
        finally:
            m.runlock()

    @contextlib.contextmanager
    def write(self, volume: str, path: str, timeout: "float | None" = None):
        import time as _t

        from .drwmutex import DRWMutex

        if timeout is None:
            timeout = self._wtimeout.timeout
        m = DRWMutex(self._ds, f"{volume}/{path}")
        t0 = _t.monotonic()
        if not m.get_lock(self._source, timeout):
            self._wtimeout.log_failure()
            raise LockTimeout(f"{volume}/{path}")
        self._wtimeout.log_success(_t.monotonic() - t0)
        try:
            yield
        finally:
            m.unlock()
