"""LocalLocker: this node's share of the distributed lock state
(cmd/local-locker.go).

A map of resource -> granted entries.  A write grant owns the resource
exclusively; read grants stack.  Entries carry the holder's UID and a
last-refresh timestamp; `expire_old` drops entries whose holder stopped
refreshing (dead process / partitioned node), which is what frees locks
after a holder dies (the modern analogue of lockMaintenance,
lock-rest-server.go:238).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from .drwmutex import EXPIRY_S, LockArgs

from ..utils.log import kv, logger

_log = logger("dsync")


@dataclasses.dataclass
class LockEntry:
    uid: str
    writer: bool
    source: str
    acquired_at: float
    refreshed_at: float


def _is_write_locked(entries: "list[LockEntry]") -> bool:
    return len(entries) == 1 and entries[0].writer


class LocalLocker:
    """In-process NetLocker backing one node's lock REST plane."""

    def __init__(self, endpoint: str = "local"):
        self.endpoint = endpoint
        self._mu = threading.Lock()
        self._locks: dict[str, list[LockEntry]] = {}

    # -- NetLocker --------------------------------------------------------

    def lock(self, args: LockArgs) -> bool:
        now = time.monotonic()
        with self._mu:
            # all-or-nothing across resources (canTakeLock,
            # local-locker.go:64-72)
            if any(r in self._locks for r in args.resources):
                return False
            for r in args.resources:
                self._locks[r] = [
                    LockEntry(
                        uid=args.uid,
                        writer=True,
                        source=args.source,
                        acquired_at=now,
                        refreshed_at=now,
                    )
                ]
            return True

    def unlock(self, args: LockArgs) -> bool:
        with self._mu:
            ok = True
            for r in args.resources:
                entries = self._locks.get(r)
                if entries is None or not _is_write_locked(entries):
                    ok = False
                    continue
                if not self._remove(r, args.uid):
                    ok = False
            return ok

    def rlock(self, args: LockArgs) -> bool:
        # read locks are single-resource by contract (the reference's
        # RLock also only honours Resources[0], local-locker.go:162)
        if len(args.resources) != 1:
            raise ValueError("read locks take exactly one resource")
        now = time.monotonic()
        resource = args.resources[0]
        entry = LockEntry(
            uid=args.uid,
            writer=False,
            source=args.source,
            acquired_at=now,
            refreshed_at=now,
        )
        with self._mu:
            entries = self._locks.get(resource)
            if entries is None:
                self._locks[resource] = [entry]
                return True
            if _is_write_locked(entries):
                return False
            entries.append(entry)
            return True

    def runlock(self, args: LockArgs) -> bool:
        if len(args.resources) != 1:
            raise ValueError("read locks take exactly one resource")
        resource = args.resources[0]
        with self._mu:
            entries = self._locks.get(resource)
            if entries is None or _is_write_locked(entries):
                return False
            return self._remove(resource, args.uid)

    def refresh(self, args: LockArgs) -> bool:
        now = time.monotonic()
        with self._mu:
            found = False
            for r in args.resources:
                for e in self._locks.get(r, ()):
                    if e.uid == args.uid:
                        e.refreshed_at = now
                        found = True
            return found

    def force_unlock(self, args: LockArgs) -> bool:
        """Admin: drop every entry for the resources unconditionally."""
        with self._mu:
            removed = False
            for r in args.resources:
                if self._locks.pop(r, None) is not None:
                    removed = True
            return removed

    def is_online(self) -> bool:
        return True

    def dump(self) -> "list[dict]":
        """Snapshot of held locks (admin top-locks / peer GetLocks).

        Entries carry this node's endpoint and WALL-clock acquisition
        time (internal timestamps are monotonic, which would be
        incomparable across processes when the admin API aggregates
        every node's dump)."""
        now_mono = time.monotonic()
        now_wall = time.time()
        with self._mu:
            return [
                {
                    "endpoint": self.endpoint,
                    "resource": r,
                    "uid": e.uid,
                    "writer": e.writer,
                    "source": e.source,
                    "age_s": round(now_mono - e.acquired_at, 3),
                    "acquired_at": round(
                        now_wall - (now_mono - e.acquired_at), 3
                    ),
                }
                for r, entries in self._locks.items()
                for e in entries
            ]

    def close(self) -> None:
        pass

    # -- maintenance ------------------------------------------------------

    def expire_old(self, max_age_s: float = EXPIRY_S) -> int:
        """Drop entries not refreshed within max_age_s; returns count."""
        cutoff = time.monotonic() - max_age_s
        dropped = 0
        with self._mu:
            for r in list(self._locks):
                entries = self._locks[r]
                keep = [e for e in entries if e.refreshed_at >= cutoff]
                dropped += len(entries) - len(keep)
                if keep:
                    self._locks[r] = keep
                else:
                    del self._locks[r]
        return dropped

    def dup_lock_map(self) -> dict:
        """Snapshot for admin top-locks (DupLockMap)."""
        with self._mu:
            return {
                r: [dataclasses.asdict(e) for e in entries]
                for r, entries in self._locks.items()
            }

    # internal; caller holds self._mu
    def _remove(self, resource: str, uid: str) -> bool:
        entries = self._locks.get(resource, [])
        for i, e in enumerate(entries):
            if e.uid == uid:
                del entries[i]
                if not entries:
                    del self._locks[resource]
                return True
        return False


class LockMaintenance:
    """Per-node expiry sweep: a daemon thread dropping unrefreshed
    entries from this node's LocalLocker (the lockMaintenance analogue,
    run against local state only - see module docstring)."""

    def __init__(
        self,
        locker: LocalLocker,
        interval_s: float = 10.0,
        expiry_s: float = EXPIRY_S,
    ):
        self._locker = locker
        self._interval = interval_s
        self._expiry = expiry_s
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def start(self) -> "LockMaintenance":
        self._thread = threading.Thread(
            target=self._run, name="lock-maintenance", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._locker.expire_old(self._expiry)
            except Exception as exc:
                _log.warning("lock maintenance sweep failed", extra=kv(err=str(exc)))
