"""Lock REST plane: the NetLocker service each node exposes to peers
(cmd/lock-rest-server.go:87, lock-rest-client.go).

Mounted on the node's single internode listener under
/minio-tpu/lock/v1/<method> next to the storage plane (routers.go:25-38):
POST bodies are msgpack {uid, resources, source}, responses are msgpack
booleans, and every request carries the internode JWT.  Connection
failures surface as False grants on lock/rlock (the requesting DRWMutex
counts them against tolerance) and are swallowed on release/refresh
(the entry ages out server-side).
"""

from __future__ import annotations

import http.client
import random
import threading
import time
import urllib.parse

import msgpack

from ..utils import jwt
from .drwmutex import LockArgs, NetLocker
from .local_locker import LocalLocker

from ..utils.log import kv, logger

_log = logger("dsync")

PREFIX = "/minio-tpu/lock/v1"
_TOKEN_TTL_S = 900

_METHODS = ("lock", "unlock", "rlock", "runlock", "refresh", "forceunlock")


def _never_sent(e: Exception) -> bool:
    """True when the transport failure provably happened before the
    request reached the peer, making a retry safe even for
    non-idempotent grant methods.  ECONNREFUSED means the TCP connect
    itself failed — no byte of the request was transmitted."""
    return isinstance(e, ConnectionRefusedError)


def _pack_args(args: LockArgs) -> bytes:
    return msgpack.packb(
        {
            "uid": args.uid,
            "resources": list(args.resources),
            "source": args.source,
        },
        use_bin_type=True,
    )


def _unpack_args(body: bytes) -> LockArgs:
    d = msgpack.unpackb(body, raw=False)
    return LockArgs(
        uid=d["uid"],
        resources=tuple(d["resources"]),
        source=d.get("source", ""),
    )


class LockRESTServer:
    """Dispatches lock-plane requests onto this node's LocalLocker."""

    def __init__(self, locker: LocalLocker, secret: str):
        self.locker = locker
        self._secret = secret

    def handle(
        self,
        method_name: str,
        query: dict,
        body: bytes,
        headers: "dict | None" = None,
    ) -> tuple[int, bytes, dict]:
        try:
            authz = {
                k.lower(): v for k, v in (headers or {}).items()
            }.get("authorization", "")
            if not authz.startswith("Bearer "):
                raise jwt.JWTError("missing bearer token")
            jwt.verify(authz[len("Bearer ") :], self._secret)
        except Exception as e:  # noqa: BLE001
            return 401, msgpack.packb(str(e)), {}
        if method_name not in _METHODS:
            return 400, msgpack.packb(f"unknown method {method_name}"), {}
        try:
            args = _unpack_args(body)
            fn = {
                "lock": self.locker.lock,
                "unlock": self.locker.unlock,
                "rlock": self.locker.rlock,
                "runlock": self.locker.runlock,
                "refresh": self.locker.refresh,
                "forceunlock": self.locker.force_unlock,
            }[method_name]
            return 200, msgpack.packb(bool(fn(args))), {}
        except Exception as e:  # noqa: BLE001
            return 400, msgpack.packb(str(e)), {}


class LockRESTClient(NetLocker):
    """NetLocker for a peer node's lock plane."""

    def __init__(
        self,
        host: str,
        port: int,
        secret: str,
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self._secret = secret
        self._timeout = timeout
        self._local = threading.local()
        self._token = ""
        self._token_exp = 0.0

    def _bearer(self) -> str:
        now = time.time()
        if now > self._token_exp - 60:
            self._token = jwt.sign(
                {"sub": "minio-tpu-lock"}, self._secret, _TOKEN_TTL_S
            )
            self._token_exp = now + _TOKEN_TTL_S
        return self._token

    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            from ..utils import tlsconf

            c = tlsconf.client_connection(
                self.host, self.port, self._timeout
            )
            self._local.conn = c
        return c

    def _drop_conn(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception as exc:
                _log.debug("lock REST connection close failed", extra=kv(err=str(exc)))
            self._local.conn = None

    def _call(self, method: str, args: LockArgs) -> bool:
        body = _pack_args(args)
        headers = {
            "Authorization": f"Bearer {self._bearer()}",
            "Content-Length": str(len(body)),
        }
        url = f"{PREFIX}/{method}"
        # lock/rlock are normally NOT retried: a lost response may mean
        # the grant was applied server-side, and re-sending the same uid
        # would turn it into an unowned phantom grant.  The one safe
        # exception is a refused/never-established connection (a peer
        # mid-restart rebinding its listener): nothing reached the
        # server, so one retry after a jittered backoff converts the
        # restart window into latency instead of a transient quorum
        # error.  Releases and refreshes are idempotent and retry once
        # on any transport failure.
        idempotent = method not in ("lock", "rlock")
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request("POST", url, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                break
            except (OSError, http.client.HTTPException) as e:
                self._drop_conn()
                if attempt or not (
                    idempotent or _never_sent(e)
                ):
                    raise ConnectionError(
                        f"lock plane {self.host}:{self.port} unreachable"
                    ) from None
                # jittered backoff: give a restarting peer a beat to
                # finish rebinding before the single retry
                time.sleep(0.02 + random.random() * 0.08)
        if resp.status != 200:
            raise ConnectionError(
                f"lock plane {self.host}:{self.port}: "
                f"HTTP {resp.status} {msgpack.unpackb(payload, raw=False)!r}"
            )
        return bool(msgpack.unpackb(payload, raw=False))

    # -- NetLocker --------------------------------------------------------

    def lock(self, args: LockArgs) -> bool:
        return self._call("lock", args)

    def unlock(self, args: LockArgs) -> bool:
        return self._call("unlock", args)

    def rlock(self, args: LockArgs) -> bool:
        return self._call("rlock", args)

    def runlock(self, args: LockArgs) -> bool:
        return self._call("runlock", args)

    def refresh(self, args: LockArgs) -> bool:
        return self._call("refresh", args)

    def force_unlock(self, args: LockArgs) -> bool:
        return self._call("forceunlock", args)

    def is_online(self) -> bool:
        try:
            self._call(
                "refresh", LockArgs(uid="probe", resources=("probe",))
            )
            return True
        except Exception:  # noqa: BLE001
            return False

    def close(self) -> None:
        self._drop_conn()
