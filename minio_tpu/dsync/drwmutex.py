"""dsync: distributed quorum RW mutex (pkg/dsync/drwmutex.go:180-321).

Algorithm (matching the reference's DRWMutex):

- A lock names one or more resources.  Acquisition broadcasts the request
  to every locker node in parallel; it succeeds iff a quorum grants it
  within the acquire window (DRWMutexAcquireTimeout, drwmutex.go:47).
- Write quorum is n - n//2, bumped by one when that equals the tolerance
  (even n) so two halves of a split brain cannot both hold the lock
  (drwmutex.go:190-199).  Read quorum is n - n//2.
- A failed attempt releases whatever grants it did collect
  (releaseAll, drwmutex.go:336) and retries with jittered backoff until
  the caller's timeout expires (lockBlocking, drwmutex.go:140-177).

Stale-lock recovery: the reference's 2020-era lockMaintenance loop
(lock-rest-server.go:238) polls peers with an Expired RPC once a minute;
it cannot free a fully-granted lock whose holder process died.  We keep
the same quorum acquisition but recover staleness the way the modern
dsync does: holders REFRESH their held locks on a cadence, and every
lock server locally expires entries that have not been refreshed within
the expiry window.  A dead holder stops refreshing, so its grants age
out on every node independently - no cross-node GC RPC required, and a
killed node's locks always free.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
import uuid

from ..utils.log import kv, logger

_log = logger("dsync")

ACQUIRE_TIMEOUT_S = 1.0  # DRWMutexAcquireTimeout (drwmutex.go:47)
REFRESH_INTERVAL_S = 10.0  # holder-side refresh cadence
EXPIRY_S = 30.0  # server-side entry expiry (3 missed refreshes)


@dataclasses.dataclass(frozen=True)
class LockArgs:
    """One lock request (dsync.LockArgs)."""

    uid: str
    resources: tuple
    source: str = ""


class NetLocker:
    """The per-node lock service interface (pkg/dsync
    rpc-client-interface.go:35).  Implementations: LocalLocker
    (in-process) and LockRESTClient (peer node over the lock plane)."""

    def lock(self, args: LockArgs) -> bool:
        raise NotImplementedError

    def unlock(self, args: LockArgs) -> bool:
        raise NotImplementedError

    def rlock(self, args: LockArgs) -> bool:
        raise NotImplementedError

    def runlock(self, args: LockArgs) -> bool:
        raise NotImplementedError

    def refresh(self, args: LockArgs) -> bool:
        raise NotImplementedError

    def force_unlock(self, args: LockArgs) -> bool:
        raise NotImplementedError

    def is_online(self) -> bool:
        return True

    def close(self) -> None:
        pass


class Dsync:
    """Locker topology + the holder-side refresh loop.

    One Dsync per process; its refresher thread keeps every currently
    held lock alive on all locker nodes until release.
    """

    def __init__(
        self,
        lockers: list,
        refresh_interval_s: float = REFRESH_INTERVAL_S,
    ):
        if not lockers:
            raise ValueError("dsync needs at least one locker")
        self.lockers = list(lockers)
        self._refresh_interval = refresh_interval_s
        self._held: dict[str, tuple] = {}  # uid -> (args, read)
        self._lost: set[str] = set()  # uids whose refresh lost quorum
        self._refresh_fails: dict[str, set] = {}  # uid -> failing idxs
        self._mu = threading.Lock()
        self._stop = threading.Event()
        # one refresher thread PER locker so a hung node cannot starve
        # refreshes to healthy nodes past the expiry window
        self._threads: "list[threading.Thread] | None" = None

    # -- held-lock registry (feeds the refreshers) ------------------------

    def track(self, args: LockArgs, read: bool = False) -> None:
        with self._mu:
            self._held[args.uid] = (args, read)
            if self._threads is None:
                self._threads = [
                    threading.Thread(
                        target=self._refresh_loop,
                        args=(i,),
                        name=f"dsync-refresh-{i}",
                        daemon=True,
                    )
                    for i in range(len(self.lockers))
                ]
                for t in self._threads:
                    t.start()

    def untrack(self, uid: str) -> None:
        with self._mu:
            self._held.pop(uid, None)
            self._lost.discard(uid)
            self._refresh_fails.pop(uid, None)

    def is_lost(self, uid: str) -> bool:
        """True when refresh lost quorum for this lock: the holder can
        no longer assume exclusivity (a stalled process may observe
        this after resuming and must treat the operation as failed)."""
        with self._mu:
            return uid in self._lost

    def release_all(self) -> int:
        """Release every held lock on every locker node (graceful
        shutdown): a restarting node must unwind its grants instead of
        leaving orphaned entries for peers to expire by timeout.
        Returns the number of locks released."""
        with self._mu:
            held = list(self._held.values())
            self._held.clear()
            self._lost.clear()
            self._refresh_fails.clear()
        for args, read in held:
            for c in self.lockers:
                try:
                    if read:
                        c.runlock(args)
                    else:
                        c.unlock(args)
                except Exception as exc:
                    _log.debug(
                        "shutdown release failed; entry ages out",
                        extra=kv(uid=args.uid, err=str(exc)),
                    )
        if held:
            _log.info(
                "released held locks at shutdown",
                extra=kv(count=len(held)),
            )
        return len(held)

    def close(self) -> None:
        self._stop.set()
        if self._threads is not None:
            for t in self._threads:
                t.join(timeout=2)
        for c in self.lockers:
            try:
                c.close()
            except Exception as exc:
                _log.debug("locker client close failed", extra=kv(err=str(exc)))

    def _refresh_loop(self, locker_index: int) -> None:
        c = self.lockers[locker_index]
        while not self._stop.wait(self._refresh_interval):
            with self._mu:
                batch = [a for a, _ in self._held.values()]
            for args in batch:
                try:
                    ok = c.refresh(args)
                except Exception:  # noqa: BLE001
                    ok = False  # unreachable node: entry ages out there
                self._note_refresh(args, locker_index, ok)

    def _note_refresh(self, args: LockArgs, idx: int, ok: bool) -> None:
        """Track per-uid refresh failures; when a full round cannot
        reach quorum anymore, mark the lock lost and stop refreshing so
        a zombie holder cannot keep a contested resource pinned."""
        with self._mu:
            entry = self._held.get(args.uid)
            if entry is None:
                return
            fails = self._refresh_fails.setdefault(args.uid, set())
            if ok:
                fails.discard(idx)
                return
            fails.add(idx)
            _, read = entry
            quorum, _tol = _quorums(len(self.lockers), read)
            if len(self.lockers) - len(fails) < quorum:
                self._lost.add(args.uid)
                self._held.pop(args.uid, None)
                self._refresh_fails.pop(args.uid, None)


def _quorums(n: int, read: bool) -> tuple[int, int]:
    """(quorum, tolerance) - drwmutex.go:184-199."""
    tolerance = n // 2
    quorum = n - tolerance
    if not read and quorum == tolerance:
        quorum += 1  # even n: write needs n/2+1 against split brain
    return quorum, n - quorum


class DRWMutex:
    """Distributed RW mutex over a Dsync locker set."""

    def __init__(self, ds: Dsync, *names: str):
        if not names:
            raise ValueError("lock needs at least one resource name")
        self._ds = ds
        self.names = tuple(names)
        self._uid = ""
        self._read = False

    # -- public API -------------------------------------------------------

    def get_lock(
        self, source: str = "", timeout: "float | None" = 30.0
    ) -> bool:
        return self._lock_blocking(source, read=False, timeout=timeout)

    def get_rlock(
        self, source: str = "", timeout: "float | None" = 30.0
    ) -> bool:
        return self._lock_blocking(source, read=True, timeout=timeout)

    def unlock(self) -> None:
        self._release()

    def runlock(self) -> None:
        self._release()

    # -- acquisition ------------------------------------------------------

    def _lock_blocking(
        self, source: str, read: bool, timeout: "float | None"
    ) -> bool:
        if read and len(self.names) != 1:
            raise ValueError("read locks take exactly one resource")
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        attempt = 0
        while True:
            args = LockArgs(
                uid=uuid.uuid4().hex,
                resources=self.names,
                source=source,
            )
            if self._try_lock(args, read):
                self._uid = args.uid
                self._read = read
                self._ds.track(args, read)
                return True
            attempt += 1
            # jittered incremental backoff (retry.NewTimer analogue)
            delay = min(0.003 * (2 ** min(attempt, 6)), 0.25)
            delay *= 0.5 + random.random()
            if deadline is not None:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                delay = min(delay, rem)
            time.sleep(delay)

    def _try_lock(self, args: LockArgs, read: bool) -> bool:
        lockers = self._ds.lockers
        n = len(lockers)
        quorum, tolerance = _quorums(n, read)
        grants = [False] * n
        done = threading.Event()
        pending = [n]
        failed = [0]
        granted = [0]
        abandoned = [False]  # set when the attempt is given up
        mu = threading.Lock()

        def release_one(i: int) -> None:
            try:
                if read:
                    lockers[i].runlock(args)
                else:
                    lockers[i].unlock(args)
            except Exception as exc:
                _log.debug("release failed; entry ages out via expiry", extra=kv(err=str(exc)))

        def ask(i: int, c) -> None:
            ok = False
            errored = False
            try:
                ok = c.rlock(args) if read else c.lock(args)
            except Exception:  # noqa: BLE001
                errored = True
            if errored:
                # a lost response may have left a grant applied
                # server-side under this uid: best-effort cleanup so a
                # phantom grant cannot pin the resource until expiry
                release_one(i)
            with mu:
                grants[i] = ok
                pending[0] -= 1
                if ok:
                    granted[0] += 1
                else:
                    failed[0] += 1
                # early exit: quorum met, all answered, or impossible
                if (
                    granted[0] >= quorum
                    or pending[0] == 0
                    or failed[0] > tolerance
                ):
                    done.set()
                late_abandoned = abandoned[0] and ok
            if late_abandoned:
                # grant arrived after the attempt was given up
                # (drwmutex.go releases post-timeout grants the same way)
                release_one(i)

        threads = [
            threading.Thread(target=ask, args=(i, c), daemon=True)
            for i, c in enumerate(lockers)
        ]
        for t in threads:
            t.start()
        done.wait(ACQUIRE_TIMEOUT_S)
        with mu:
            met = granted[0] >= quorum
            if not met:
                abandoned[0] = True
            to_release = (
                [] if met else [i for i, g in enumerate(grants) if g]
            )
        if not met:
            self._send_release(args, read, to_release)
            return False
        # stragglers that grant after a successful acquire belong to the
        # held lock and are released at unlock (indices=None).
        return True

    def _send_release(
        self, args: LockArgs, read: bool, indices: "list[int] | None" = None
    ) -> None:
        lockers = self._ds.lockers
        idx = range(len(lockers)) if indices is None else indices
        for i in idx:
            try:
                if read:
                    lockers[i].runlock(args)
                else:
                    lockers[i].unlock(args)
            except Exception as exc:
                _log.debug("unlock on unreachable node; entry ages out", extra=kv(err=str(exc)))

    def _release(self) -> None:
        if not self._uid:
            return
        args = LockArgs(uid=self._uid, resources=self.names)
        self._ds.untrack(self._uid)
        self._send_release(args, self._read)
        self._uid = ""
